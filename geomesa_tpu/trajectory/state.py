"""Device-resident per-entity track layout + batched track aggregation.

The ``geomesa-process`` track tier (``TrackLabelProcess``, the per-track
halves of ``TubeBuilder`` — PAPER.md §1) survives host-side as Python
loops over ``groups.astype(object)``; at millions of entities that is the
grouped-aggregation regime where BENCH_r05 fell to 0.16×. This module
builds ONE planned columnar scan into a track layout the device can
segment-reduce:

- rows sorted by ``(track, time)`` (stable lexsort), entity boundaries as
  CSR offsets — the classic segmented layout, so every per-entity
  aggregate is one ``jax.ops.segment_sum`` over contiguous segments;
- device columns (x/y f32, per-step seconds f32, entity ids int32) are
  pinned through the ISSUE-7 :class:`~geomesa_tpu.store.bufferpool.
  BufferPool` under ledger group ``"tracks"`` and fingerprinted by the
  store's ``(rebuild epoch, delta version)`` DATA EPOCH — any write
  (delta included) invalidates with one tuple compare, eviction under
  HBM pressure just restages on next use;
- :func:`track_stats` answers length / duration / average speed /
  heading change / dwell / last-position label for EVERY entity in one
  fused pass (:func:`cached_track_stats_step`), with
  :func:`track_stats_host` as the independent f64 referee.

Step-bearing semantics (shared by device kernel and host referee so they
cannot drift): a step's bearing is defined only when its length is
positive; heading change accumulates ``|wrap(b_i - b_{i-1})|`` over
consecutive DEFINED-bearing step pairs within an entity; dwell sums step
durations whose step length is ≤ ``dwell_eps_deg``.

Locking: ``TrackState._lock`` and the manager cache lock are LEAVES
(docs/concurrency.md) — device staging runs outside both.
"""

from __future__ import annotations

import threading
import weakref
from functools import lru_cache

import numpy as np

from geomesa_tpu.analysis.contracts import cache_surface, device_band
from geomesa_tpu.planning.planner import Query

__all__ = [
    "TrackState", "build_track_state", "cached_track_stats_step",
    "get_track_state", "track_stats", "track_stats_host",
]

LEDGER_GROUP = "tracks"  # devmon residency ledger group for track columns
MIN_ROW_BUCKET = 1024  # power-of-two row-padding floor (J003 shape bucket)
DEFAULT_DWELL_EPS_DEG = 1e-4


def pow2_bucket(n: int, floor: int = 1) -> int:
    """THE trajectory plane's shape-bucket rule (shared with
    :mod:`geomesa_tpu.trajectory.corridor` so the two planes' padding
    discipline cannot diverge): smallest power of two ≥ max(n, floor)."""
    c = floor
    while c < n:
        c <<= 1
    return c


_pow2 = pow2_bucket  # module-local alias


class _DeviceSlot:
    """One staging's device columns. A FRESH slot per staging is the
    accounting unit: the ledger entry finalizes when the slot dies, and
    the pool's same-(type, key) entry REPLACES on the next staging's
    different owner — re-registering the TrackState itself would merge
    group bytes across evict/restage cycles and double-count."""

    __slots__ = ("cols", "n_cap", "e_cap", "__weakref__")

    def __init__(self, cols, n_cap, e_cap):
        self.cols = cols
        self.n_cap = n_cap
        self.e_cap = e_cap

    @property
    def nbytes(self) -> int:
        return int(sum(int(a.nbytes) for a in self.cols))


class TrackState:
    """One (type, track-field, filter) snapshot in segmented track layout.

    Host truth: ``order`` (row permutation of the scanned table), ``t_ms``
    int64 times, ``x``/``y`` f64 coords, ``entities`` (E,) object keys and
    ``offsets`` (E+1,) int64 CSR — entity ``e`` owns sorted rows
    ``offsets[e]:offsets[e+1]``. Device columns stage lazily and drop on
    pool eviction (``_dev`` cleared; next use restages)."""

    def __init__(self, type_name: str, track_field: str, epoch,
                 entities, offsets, table, order, t_ms, x, y,
                 filter_text: str = "", auths=None):
        self.type_name = type_name
        self.track_field = track_field
        self.epoch = epoch
        self.filter_text = filter_text
        self.auths = None if auths is None else tuple(sorted(auths))
        self.entities = entities
        self.offsets = offsets
        self.table = table  # the scanned snapshot table (sorted via order)
        self.order = order
        self.t_ms = t_ms
        self.x = x
        self.y = y
        self._lock = threading.Lock()  # leaf: device slot only
        self._dev = None  # (x32, y32, dt32, sid, first, n_cap, e_cap)
        self._pool = None

    @property
    def n(self) -> int:
        return len(self.order)

    @property
    def n_entities(self) -> int:
        return len(self.entities)

    @property
    def nbytes(self) -> int:
        """Device bytes of the staged columns (0 while unstaged)."""
        slot = self._dev
        return 0 if slot is None else slot.nbytes

    # -- device staging -------------------------------------------------------
    def _evict(self) -> None:
        """Pool-eviction callback: drop the device slot (restage on use)."""
        with self._lock:
            self._dev = None

    def device_columns(self, pool=None):
        """The padded device columns, staging (and pool-registering) on
        first use: ``(x32, y32, dt_s, sid, first, n_cap, e_cap)``. Pads
        carry ``sid == n_entities`` (a discard segment past every real
        entity) and ``first=True`` so they contribute nothing."""
        with self._lock:
            if self._dev is not None:
                s = self._dev
                return tuple(s.cols) + (s.n_cap, s.e_cap)
        import jax.numpy as jnp

        from geomesa_tpu.obs.jaxmon import count_h2d

        n = self.n
        n_cap = _pow2(max(n, 1), MIN_ROW_BUCKET)
        e_cap = _pow2(self.n_entities + 1)
        sid = np.full(n_cap, self.n_entities, dtype=np.int32)
        first = np.ones(n_cap, dtype=bool)
        x32 = np.zeros(n_cap, dtype=np.float32)
        y32 = np.zeros(n_cap, dtype=np.float32)
        dt32 = np.zeros(n_cap, dtype=np.float32)
        if n:
            ent_ids = np.repeat(
                np.arange(self.n_entities, dtype=np.int32),
                np.diff(self.offsets).astype(np.int64))
            sid[:n] = ent_ids
            f = np.zeros(n, dtype=bool)
            f[self.offsets[:-1]] = True
            first[:n] = f
            x32[:n] = self.x.astype(np.float32)
            y32[:n] = self.y.astype(np.float32)
            dt = np.zeros(n, dtype=np.float64)
            dt[1:] = (self.t_ms[1:] - self.t_ms[:-1]) / 1000.0
            dt[f] = 0.0
            dt32[:n] = dt.astype(np.float32)
        cols = [x32, y32, dt32, sid, first]
        # track staging belongs to the trajectory plane, not whichever
        # query happens to be profiled concurrently (the ISSUE-7 rule)
        count_h2d(*cols, label="tracks")
        slot = _DeviceSlot(
            tuple(jnp.asarray(a) for a in cols), n_cap, e_cap)
        register = False
        with self._lock:
            if self._dev is None:
                self._dev = slot
                self._pool = pool
                register = pool is not None
            slot = self._dev
        if register:
            from geomesa_tpu.store.bufferpool import register_residency

            register_residency(
                pool, self.type_name, self._pool_key(), LEDGER_GROUP,
                slot.nbytes, owner=slot, fingerprint=self.epoch,
                on_evict=self._evict)
        return tuple(slot.cols) + (slot.n_cap, slot.e_cap)

    def _pool_key(self) -> str:
        """Pool/ledger entry key. DISTINCT per (field, filter, auths):
        two concurrently-live states (an auth-restricted caller beside an
        unrestricted one, or two long filters sharing a prefix) must not
        collide on one pool entry — the pool replaces same-key entries on
        a different owner WITHOUT evicting the old slot, which would
        leave the older state's device columns alive but unbudgeted."""
        key = f"tracks:{self.track_field}"
        if self.filter_text or self.auths is not None:
            import hashlib

            scope = repr((self.filter_text, self.auths)).encode()
            key += f"[{hashlib.sha1(scope).hexdigest()[:10]}]"
        return key

    def release(self) -> None:
        """Drop the device slot (manager invalidation). The pool's
        (type, tracks:field) entry still holds the old slot until the
        SUCCESSOR state's staging replaces it (different owner, same
        key) or pressure evicts it — the same cold-buffer lifecycle as
        any other residency unit; schema delete/rename purges by type
        name through the existing ``pool.purge`` path."""
        with self._lock:
            self._dev = None
            self._pool = None

    # -- invariants (obs/audit.py InvariantSweeper surface) -------------------
    def validate(self) -> list[str]:
        """Structural CSR invariants: offsets start at 0, end at the row
        count, never decrease; every entity's times are nondecreasing.
        Returns violation strings (empty = clean)."""
        out: list[str] = []
        off = np.asarray(self.offsets, dtype=np.int64)
        if len(off) != self.n_entities + 1:
            out.append(
                f"offsets length {len(off)} != entities+1 "
                f"{self.n_entities + 1}")
            return out
        if len(off) and off[0] != 0:
            out.append(f"offsets[0] = {off[0]} != 0")
        if len(off) and off[-1] != self.n:
            out.append(f"offsets[-1] = {off[-1]} != rows {self.n}")
        if np.any(np.diff(off) < 0):
            out.append("offsets decrease")
            return out
        if self.n:
            d = np.diff(self.t_ms)
            boundary = np.zeros(self.n - 1, dtype=bool)
            inner = off[1:-1]
            boundary[inner[(inner > 0) & (inner < self.n)] - 1] = True
            bad = np.nonzero((d < 0) & ~boundary)[0]
            if len(bad):
                out.append(
                    f"time not monotone within entity at sorted rows "
                    f"{bad[:4].tolist()}")
        return out


def _data_epoch(ds, type_name: str):
    """The store's (rebuild epoch, delta version) pair, or None when the
    store does not expose one (remote/merged callers skip caching)."""
    try:
        return ds._state(type_name).data_epoch()
    except (AttributeError, KeyError):
        return None


def build_track_state(ds, type_name: str, track_field: str,
                      filter=None, auths=None) -> TrackState:
    """ONE planned columnar scan → segmented track layout.

    The DATA EPOCH is read BEFORE the scan (the ISSUE-13 rule): a racing
    write can only make the cached state look stale, never fresh.
    ``auths``: record-level visibility for the scan — a restricted
    caller's state holds only the rows it may see."""
    epoch = _data_epoch(ds, type_name)
    r = ds.query(type_name, Query(filter=filter, auths=auths))
    t = r.table
    from geomesa_tpu.schema.columnar import representative_xy

    if track_field not in t.columns:
        raise KeyError(f"{type_name!r} has no attribute {track_field!r}")
    tms = t.dtg_millis()
    groups = t.columns[track_field].values.astype(object)
    if len(t):
        ents, codes = np.unique(groups, return_inverse=True)
        # tertiary key: DESCENDING row index, so among equal (track,
        # time) rows the LOWEST original row sorts last — the layout's
        # last-of-entity row (the TRACK_STATS label) then resolves ties
        # exactly like process/tracks.track_label (pinned there
        # red/green); a plain stable sort would pick the HIGHEST row
        order = np.lexsort((-np.arange(len(t)), tms, codes))
        sorted_codes = codes[order]
        starts = np.nonzero(
            np.r_[True, sorted_codes[1:] != sorted_codes[:-1]])[0]
        offsets = np.concatenate(
            [starts, [len(t)]]).astype(np.int64)
        xs, ys = representative_xy(t)
    else:
        ents = np.empty(0, dtype=object)
        order = np.empty(0, dtype=np.int64)
        offsets = np.zeros(1, dtype=np.int64)
        xs = ys = np.empty(0, dtype=np.float64)
        tms = np.empty(0, dtype=np.int64)
    filter_text = "" if filter is None else str(filter)
    return TrackState(
        type_name, track_field, epoch, ents, offsets,
        t.take(order) if len(t) else t, order,
        tms[order] if len(t) else tms,
        xs[order].astype(np.float64) if len(t) else xs,
        ys[order].astype(np.float64) if len(t) else ys,
        filter_text=filter_text, auths=auths,
    )


# -- manager cache (epoch-fingerprinted) --------------------------------------

_lock = threading.Lock()  # leaf: the manager cache table
_states: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


@cache_surface(name="track-state-cache", keyed_by="type_name",
               purge=("invalidate",))
def get_track_state(ds, type_name: str, track_field: str,
                    filter=None, auths=None) -> TrackState:
    """The cached track state for (store, type, field, filter, auths),
    rebuilt when the store's data epoch moved (delta writes included —
    the epoch check is one tuple compare, so invalidation costs
    nothing). ``auths`` is part of the cache key: a restricted caller
    must never be served an unrestricted caller's cached rows."""
    key = (type_name, track_field,
           "" if filter is None else str(filter),
           None if auths is None else tuple(sorted(auths)))
    epoch = _data_epoch(ds, type_name)
    with _lock:
        table = _states.get(ds)
        st = table.get(key) if table else None
    if st is not None and epoch is not None and st.epoch == epoch:
        return st
    fresh = build_track_state(ds, type_name, track_field, filter=filter,
                              auths=auths)
    with _lock:
        table = _states.get(ds)
        if table is None:
            table = {}
            _states[ds] = table
        prev = table.get(key)
        table[key] = fresh
    if prev is not None:
        prev.release()
    return fresh


def invalidate(ds, type_name: str | None = None) -> None:
    """Drop cached states (schema delete/rename hygiene; tests)."""
    with _lock:
        table = _states.get(ds)
        if not table:
            return
        keys = [k for k in table
                if type_name is None or k[0] == type_name]
        dropped = [table.pop(k) for k in keys]
    for st in dropped:
        st.release()


# -- the fused per-entity aggregation -----------------------------------------

@cache_surface(name="track-stats-step-memo", keyed_by="shape-bucket",
               immutable=True)
@device_band(certain=True)
@lru_cache(maxsize=None)
def cached_track_stats_step(n_cap: int, e_cap: int):
    """Memoized segment-reduce step, one observed identity per (row
    bucket, entity bucket) — same zero-steady-recompile contract as
    :func:`geomesa_tpu.parallel.query.cached_corridor_step`.

    fn(x, y, dt, sid, first, dwell_eps) → (length_deg, duration_s,
    heading_change_deg, dwell_s), each (e_cap,) f32; callers slice the
    real entity count. All f32 (J004) — the f64 referee is
    :func:`track_stats_host`."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.obs.jaxmon import observed

    @jax.jit
    def step(x, y, dt, sid, first, dwell_eps):
        dx = x - jnp.concatenate([x[:1], x[:-1]])
        dy = y - jnp.concatenate([y[:1], y[:-1]])
        dist = jnp.where(first, 0.0, jnp.sqrt(dx * dx + dy * dy))
        step_dt = jnp.where(first, 0.0, dt)
        length = jax.ops.segment_sum(dist, sid, num_segments=e_cap)
        duration = jax.ops.segment_sum(step_dt, sid, num_segments=e_cap)
        # step bearings (deg CW from N); defined only for moving steps
        brg = jnp.degrees(jnp.arctan2(dx, dy))
        moved = ~first & (dist > 0)
        pbrg = jnp.concatenate([brg[:1], brg[:-1]])
        pmoved = jnp.concatenate([jnp.zeros(1, bool), moved[:-1]])
        turn = jnp.abs(jnp.mod(brg - pbrg + 180.0, 360.0) - 180.0)
        turn = jnp.where(moved & pmoved, turn, 0.0)
        heading_change = jax.ops.segment_sum(turn, sid, num_segments=e_cap)
        dwell = jax.ops.segment_sum(
            jnp.where(dist <= dwell_eps, step_dt, 0.0), sid,
            num_segments=e_cap)
        return length, duration, heading_change, dwell

    return observed(f"track_stats_n{n_cap}_e{e_cap}", step)


def track_stats(ds, type_name: str, track_field: str, filter=None,
                dwell_eps_deg: float = DEFAULT_DWELL_EPS_DEG,
                state: TrackState | None = None, auths=None) -> dict:
    """Batched per-entity track aggregation: every entity's length /
    duration / avg speed / heading change / dwell / last-position label
    in one fused device pass over the cached track state. Returns a
    column dict (the SQL ``TRACK_STATS`` / HTTP surface). ``auths``
    scopes the underlying scan (and the cache entry) to the caller's
    visible rows."""
    import jax.numpy as jnp

    st = state or get_track_state(ds, type_name, track_field,
                                  filter=filter, auths=auths)
    pool = getattr(getattr(ds, "backend", None), "pool", None)
    x32, y32, dt32, sid, first, n_cap, e_cap = st.device_columns(pool=pool)
    step = cached_track_stats_step(n_cap, e_cap)
    length, duration, hc, dwell = step(
        x32, y32, dt32, sid, first, jnp.float32(dwell_eps_deg))
    e = st.n_entities
    length = np.asarray(length)[:e].astype(np.float64)
    duration = np.asarray(duration)[:e].astype(np.float64)
    hc = np.asarray(hc)[:e].astype(np.float64)
    dwell = np.asarray(dwell)[:e].astype(np.float64)
    return _assemble(st, length, duration, hc, dwell)


def track_stats_host(state: TrackState,
                     dwell_eps_deg: float = DEFAULT_DWELL_EPS_DEG) -> dict:
    """Independent f64 NumPy referee with the identical step-bearing
    semantics — the parity oracle for :func:`track_stats` and the audit
    plane's comparison surface (no jax anywhere)."""
    st = state
    n, e = st.n, st.n_entities
    length = np.zeros(e)
    duration = np.zeros(e)
    hc = np.zeros(e)
    dwell = np.zeros(e)
    if n:
        first = np.zeros(n, dtype=bool)
        first[st.offsets[:-1]] = True
        dx = np.diff(st.x, prepend=st.x[:1])
        dy = np.diff(st.y, prepend=st.y[:1])
        dist = np.where(first, 0.0, np.hypot(dx, dy))
        dt = np.zeros(n)
        dt[1:] = (st.t_ms[1:] - st.t_ms[:-1]) / 1000.0
        dt[first] = 0.0
        sid = np.repeat(np.arange(e), np.diff(st.offsets).astype(np.int64))
        length = np.bincount(sid, weights=dist, minlength=e)
        duration = np.bincount(sid, weights=dt, minlength=e)
        with np.errstate(invalid="ignore"):
            brg = np.degrees(np.arctan2(dx, dy))
        moved = ~first & (dist > 0)
        pmoved = np.r_[False, moved[:-1]]
        turn = np.abs(np.mod(brg - np.r_[brg[:1], brg[:-1]] + 180.0, 360.0)
                      - 180.0)
        turn = np.where(moved & pmoved, turn, 0.0)
        hc = np.bincount(sid, weights=turn, minlength=e)
        dwell = np.bincount(
            sid, weights=np.where(dist <= dwell_eps_deg, dt, 0.0),
            minlength=e)
    return _assemble(st, length, duration, hc, dwell)


def _assemble(st: TrackState, length, duration, hc, dwell) -> dict:
    last = np.maximum(st.offsets[1:] - 1, 0).astype(np.int64)
    firsts = st.offsets[:-1].astype(np.int64)
    with np.errstate(divide="ignore", invalid="ignore"):
        speed = np.where(duration > 0, length / np.maximum(duration, 1e-12),
                         0.0)
    e = st.n_entities
    return {
        "track": np.asarray(st.entities, dtype=object),
        "rows": np.diff(st.offsets).astype(np.int64),
        "length_deg": length,
        "duration_s": duration,
        "avg_speed_deg_s": speed,
        "heading_change_deg": hc,
        "dwell_s": dwell,
        "first_ms": (st.t_ms[firsts] if e else np.empty(0, np.int64)),
        "last_ms": (st.t_ms[last] if e else np.empty(0, np.int64)),
        "last_x": (st.x[last] if e else np.empty(0)),
        "last_y": (st.y[last] if e else np.empty(0)),
        "last_fid": (st.table.fids[last] if e
                     else np.empty(0, dtype=object)),
    }
