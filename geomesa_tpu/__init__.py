"""geomesa_tpu — a TPU-native spatio-temporal indexing & query framework.

Re-materializes GeoMesa's three load-bearing seams (see SURVEY.md §1):

- **Top**: a Python query API over CQL-style filters (:mod:`geomesa_tpu.store`,
  :mod:`geomesa_tpu.filter`) — the GeoTools ``DataStore`` role.
- **Middle**: a pure-function index layer — space-filling curves, filter→range
  planning, cost-based strategy selection (:mod:`geomesa_tpu.curve`,
  :mod:`geomesa_tpu.index`, :mod:`geomesa_tpu.planning`).
- **Bottom**: pluggable execution backends — a brute-force CPU oracle for parity
  testing and a sharded columnar TPU backend with fused scan/refine/aggregate
  kernels merged over ICI collectives (:mod:`geomesa_tpu.store.backends`,
  :mod:`geomesa_tpu.parallel`, :mod:`geomesa_tpu.ops`).

Reference capability map: /root/reference (GeoMesa 2.4.0-SNAPSHOT). This is a
from-scratch TPU-first design, not a port — see SURVEY.md §7.
"""

import os as _os

if not _os.environ.get("GEOMESA_TPU_NO_JAX"):
    import jax as _jax

    # 64-bit mode: spatio-temporal keys are 62/63-bit Morton codes and
    # timestamps are epoch-millis int64; coordinates are f64 on the host side
    # of the seam. The device (TPU) hot path is explicitly typed int32/f32/bf16
    # throughout (see geomesa_tpu/store/backends.py) so MXU/VPU work never
    # silently widens.
    _jax.config.update("jax_enable_x64", True)
else:
    # GEOMESA_TPU_NO_JAX=1 keeps this import JAX-free for tooling that only
    # needs the pure-Python layers (tpulint in CI: scripts/lint.sh). This
    # __init__ is the one place that flips jax_enable_x64, so if some later
    # import in the same process DOES pull in jax, make the flag reach it
    # through jax's own env-var path — otherwise z-codes and epoch-millis
    # would silently truncate to 32 bits.
    _os.environ.setdefault("JAX_ENABLE_X64", "true")

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy top-level API so `import geomesa_tpu` stays light and avoids
    # circular imports between schema/store/planning.
    try:
        if name in ("FeatureType", "parse_spec"):
            from geomesa_tpu.schema import sft

            return getattr(sft, name)
        if name == "DataStore":
            from geomesa_tpu.store.datastore import DataStore

            return DataStore
    except ImportError as e:  # keep hasattr()/introspection well-behaved
        raise AttributeError(name) from e
    raise AttributeError(name)
