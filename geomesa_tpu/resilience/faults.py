"""Deterministic fault injection at the HTTP choke point.

Every remote client in the tree issues its requests through
:func:`geomesa_tpu.resilience.http.fetch` — ONE ``urlopen`` call site —
and that choke point consults the active :class:`FaultInjector` before
sending and after receiving. Tests and ``bench.py --chaos`` drive it
programmatically; operators (and the CI chaos smoke gate in
``scripts/lint.sh``) drive it with the ``GEOMESA_TPU_FAULTS`` environment
spec. No fault ever fires unless an injector with matching rules is
active, and the inactive path is one module-global read.

Spec grammar (see docs/resilience.md):

    GEOMESA_TPU_FAULTS = rule (";" rule)*
    rule               = field ("," field)*
    field              = key "=" value

    keys: kind   refuse | http | latency | truncate | corrupt   (required)
          match  substring of "METHOD url" this rule applies to (default all)
          rate   fire probability in [0,1], seeded draw        (default 1.0)
          seed   per-rule RNG seed                             (default 0)
          times  stop after this many fires                    (default ∞)
          after  skip the first N matching calls               (default 0)
          status HTTP status for kind=http                     (default 503)
          ms     added latency for kind=latency                (default 50)
          at     keep this many payload bytes for kind=truncate
                 (default: half the payload)

Example — 30% 503s on one member plus 50 ms on every journal call:

    GEOMESA_TPU_FAULTS="kind=http,status=503,rate=0.3,seed=7,match=:8081;\
kind=latency,ms=50,match=/api/journal"

Schedules are deterministic: each rule draws from its own seeded RNG in
match order, so a given (spec, request sequence) always injects the same
faults — chaos tests are reproducible, not flaky.

Locking: one leaf lock guards rule counters/RNGs (rules are consulted
from concurrent client threads). Latency sleeps happen OUTSIDE the lock.
"""

from __future__ import annotations

import io
import os
import threading
import time
import urllib.error

__all__ = [
    "FaultInjector",
    "FaultRule",
    "active",
    "crash_point",
    "from_env",
    "from_spec",
    "install",
    "uninstall",
]

_KINDS = ("refuse", "http", "latency", "truncate", "corrupt", "flip", "crash")


class FaultRule:
    """One match → fault mapping with a seeded, counted schedule."""

    def __init__(
        self,
        kind: str,
        match: str = "",
        rate: float = 1.0,
        seed: int = 0,
        times: int | None = None,
        after: int = 0,
        status: int = 503,
        latency_ms: float = 50.0,
        truncate_at: int | None = None,
    ):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; known: {_KINDS}")
        self.kind = kind
        self.match = match
        self.rate = float(rate)
        self.times = times
        self.after = int(after)
        self.status = int(status)
        self.latency_ms = float(latency_ms)
        self.truncate_at = truncate_at
        self._rng_seed = seed
        import random

        self._rng = random.Random(seed)
        self.seen = 0  # matching calls observed
        self.fired = 0  # faults actually injected

    def _decide_locked(self) -> bool:
        """Called with the injector lock held: count the match, draw the
        seeded schedule, honor after/times bounds."""
        self.seen += 1
        if self.seen <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.rate < 1.0 and self._rng.random() >= self.rate:
            return False
        self.fired += 1
        return True


class FaultInjector:
    """A set of :class:`FaultRule`\\ s consulted by the HTTP choke point.

    Build programmatically (``inj.rule("http", status=503, rate=0.3)``)
    or from the env spec (:func:`from_spec`). Activate for a scope with
    ``with inj.activate(): ...`` or process-wide with :func:`install`.
    """

    def __init__(self, rules=()):
        self._lock = threading.Lock()  # leaf: rule counters/RNG draws only
        self.rules: list[FaultRule] = list(rules)

    def rule(self, kind: str, **kw) -> "FaultInjector":
        """Append one rule; returns self for chaining."""
        self.rules.append(FaultRule(kind, **kw))
        return self

    # -- choke-point hooks ----------------------------------------------------
    def before_send(self, method: str, url: str) -> None:
        """Fire pre-send faults: added latency, refused connections, and
        injected HTTP error responses. Raises exactly what a real failed
        ``urlopen`` would raise, so client classification code cannot
        tell injected faults from organic ones."""
        key = f"{method} {url}"
        sleep_ms = 0.0
        err: Exception | None = None
        with self._lock:
            for r in self.rules:
                if r.kind in ("truncate", "corrupt", "flip", "crash"):
                    continue  # payload / device-state / process faults
                if r.match and r.match not in key:
                    continue
                if not r._decide_locked():
                    continue
                if r.kind == "latency":
                    sleep_ms += r.latency_ms
                elif err is None and r.kind == "refuse":
                    # what urlopen raises for a dead port: URLError
                    # wrapping the connect-phase OSError
                    err = urllib.error.URLError(
                        ConnectionRefusedError(
                            111, f"[fault] connection refused: {url}")
                    )
                elif err is None and r.kind == "http":
                    err = urllib.error.HTTPError(
                        url, r.status, f"[fault] injected {r.status}",
                        None,  # type: ignore[arg-type]
                        io.BytesIO(b'{"error": "injected fault"}'),
                    )
        if sleep_ms > 0:
            time.sleep(sleep_ms / 1000.0)  # outside the lock
        if err is not None:
            raise err

    def after_receive(self, method: str, url: str, data: bytes) -> bytes:
        """Apply payload faults (truncation / corruption) to a response
        that 'arrived' — the torn-Arrow-stream failure mode."""
        key = f"{method} {url}"
        out = data
        with self._lock:
            for r in self.rules:
                if r.kind not in ("truncate", "corrupt"):
                    continue
                if r.match and r.match not in key:
                    continue
                if not r._decide_locked():
                    continue
                if r.kind == "truncate":
                    at = r.truncate_at if r.truncate_at is not None else len(out) // 2
                    out = out[:at]
                else:  # corrupt: flip bytes in place, keep the length
                    buf = bytearray(out)
                    for i in range(0, len(buf), max(1, len(buf) // 16)):
                        buf[i] ^= 0xA5
                    out = bytes(buf)
        return out

    def device_flips(self, type_name: str) -> list[FaultRule]:
        """Fired ``kind=flip`` rules for one device-state load (the
        DEVICE-corruption fault: ``TpuBackend.load`` consults this and
        flips one staged column value per fired rule — the silent-wrong-
        answer failure mode the correctness auditor exists to catch;
        obs/audit.py). ``match`` filters by feature-type name; ``at``
        picks the flipped row (default 0); ``rate``/``times``/``after``
        schedule as for transport faults."""
        out: list[FaultRule] = []
        with self._lock:
            for r in self.rules:
                if r.kind != "flip":
                    continue
                if r.match and r.match not in type_name:
                    continue
                if not r._decide_locked():
                    continue
                out.append(r)
        return out

    def maybe_crash(self, point: str) -> None:
        """Fire ``kind=crash`` rules matching a named sync point: the
        process dies by SIGKILL — no atexit, no flush, no cleanup — the
        durability plane's kill-and-recover failure mode
        (docs/operations.md § Durability & recovery). ``match`` filters
        by crash-point name (``wal.post_append_pre_commit``,
        ``ckpt.pre_manifest_replace``, ``recover.mid_replay``, ...);
        ``rate``/``times``/``after`` schedule as for transport faults."""
        die = False
        with self._lock:
            for r in self.rules:
                if r.kind != "crash":
                    continue
                if r.match and r.match not in point:
                    continue
                if r._decide_locked():
                    die = True
                    break
        if die:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)  # outside the lock
            # unreachable on POSIX; belt-and-braces for exotic platforms
            os._exit(137)

    # -- lifecycle ------------------------------------------------------------
    def activate(self):
        """Context manager: install for the ``with`` block, restoring the
        previously-installed injector (or the env default) on exit."""
        return _Activation(self)

    def counts(self) -> list[tuple[str, int, int]]:
        """(kind, seen, fired) per rule — assertion surface for tests."""
        with self._lock:
            return [(r.kind, r.seen, r.fired) for r in self.rules]


class _Activation:
    def __init__(self, inj: FaultInjector):
        self._inj = inj
        self._prev: "tuple | None" = None

    def __enter__(self) -> FaultInjector:
        global _override
        with _install_lock:
            self._prev = _override
            _override = (self._inj,)
        return self._inj

    def __exit__(self, *exc) -> None:
        global _override
        with _install_lock:
            _override = self._prev


# -- spec parsing -------------------------------------------------------------

def from_spec(spec: str) -> FaultInjector:
    """Parse the ``GEOMESA_TPU_FAULTS`` grammar into an injector."""
    inj = FaultInjector()
    for i, rule_text in enumerate(s for s in spec.split(";") if s.strip()):
        fields: dict[str, str] = {}
        for part in rule_text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"fault rule field {part!r} is not key=value "
                    f"(rule {i}: {rule_text!r})")
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
        kind = fields.pop("kind", None)
        if kind is None:
            raise ValueError(f"fault rule {i} missing kind=: {rule_text!r}")
        kw: dict = {}
        if "match" in fields:
            kw["match"] = fields.pop("match")
        for key, cast, dest in (
            ("rate", float, "rate"), ("seed", int, "seed"),
            ("times", int, "times"), ("after", int, "after"),
            ("status", int, "status"), ("ms", float, "latency_ms"),
            ("at", int, "truncate_at"),
        ):
            if key in fields:
                kw[dest] = cast(fields.pop(key))
        if fields:
            raise ValueError(
                f"unknown fault rule keys {sorted(fields)} in {rule_text!r}")
        inj.rule(kind, **kw)
    return inj


# -- process-wide installation ------------------------------------------------
# `_override` holds the explicit override as a 1-tuple (tests, bench
# --chaos) or None for "no override"; when no override is active the env
# spec provides the ambient injector, parsed once per distinct spec
# value. One reference = one atomic swap, so readers never need the lock.
_install_lock = threading.Lock()
_override: "tuple[FaultInjector] | None" = None
_env_cache: tuple[str, FaultInjector] | None = None


def install(inj: FaultInjector | None) -> None:
    """Install a process-wide injector; ``install(None)`` reverts to the
    ``GEOMESA_TPU_FAULTS`` env default (an EMPTY injector pins a fault-free
    transport regardless of the environment)."""
    global _override
    with _install_lock:
        _override = None if inj is None else (inj,)


def uninstall() -> None:
    install(None)


def from_env() -> FaultInjector | None:
    """The env-spec injector, or None when ``GEOMESA_TPU_FAULTS`` is unset."""
    global _env_cache
    spec = os.environ.get("GEOMESA_TPU_FAULTS")
    if not spec:
        return None
    with _install_lock:
        if _env_cache is not None and _env_cache[0] == spec:
            return _env_cache[1]
    inj = from_spec(spec)  # parse outside the lock
    with _install_lock:
        if _env_cache is None or _env_cache[0] != spec:
            _env_cache = (spec, inj)
        return _env_cache[1]


def crash_point(name: str) -> None:
    """Named kill-point for the crash harnesses (``scripts/crash_smoke.py``,
    ``scripts/rebalance_smoke.py``): the durability-critical code paths
    (WAL group commit, checkpoint commit order, recovery replay) call
    this at their crash-consistency boundaries, and the shard migrator
    (serving/elastic.py) brackets every protocol step with ``elastic.*``
    points (``pre_ship``, ``mid_ship``, ``pre_dual``, ``mid_catchup``,
    ``pre_cutover``, ``pre_source_drop``); an active injector with a
    matching ``kind=crash`` rule SIGKILLs the process there. The
    inactive path is one global read — the same zero-cost posture as
    the transport hooks."""
    inj = active()
    if inj is not None:
        inj.maybe_crash(name)


def active() -> FaultInjector | None:
    """The injector the choke point should consult right now (explicit
    override first, env spec otherwise) — None on the fault-free path.

    Lock-free read: ``_override`` is a single reference only ever swapped
    whole under ``_install_lock``, so the per-request fast path is one
    global load, no lock."""
    ov = _override
    if ov is not None:
        return ov[0]
    return from_env()
