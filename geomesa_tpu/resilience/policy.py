"""Retry policies and circuit breakers for the remote/federation stack.

Role parity: the reference delegates single-store failure recovery to the
backing store's replicas (``ThreadManagement.scala`` kills runaway scans,
HBase/Accumulo replicas absorb region failures — SURVEY.md §5). The
*distributed* half (``MergedDataStoreView`` over remote slices, §2.20 P10)
has no such substrate here: one flaky HTTP member is one Python exception.
This module is that substrate — the per-call retry loop and the
per-endpoint failure-rate circuit breaker every remote client
(:class:`~geomesa_tpu.store.remote.RemoteDataStore`,
:class:`~geomesa_tpu.stream.remote_journal.RemoteJournal`,
:class:`~geomesa_tpu.stream.confluent.HttpSchemaRegistry`) threads its
requests through. See docs/resilience.md.

Locking: :class:`CircuitBreaker` and the :class:`RetryPolicy` token budget
each own one leaf lock (metrics-tier in docs/concurrency.md's hierarchy):
nothing blocking — no I/O, no sleep, no callbacks — ever runs under them.
Backoff sleeps happen strictly outside any lock.
"""

from __future__ import annotations

import random
import threading
import time
import urllib.error

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "CorruptPayloadError",
    "MEMBER_FAILURE_TYPES",
    "MemberDrainingError",
    "RateLimitedError",
    "RetryPolicy",
    "is_member_failure",
    "retryable",
]


class CircuitOpenError(ConnectionError):
    """Raised WITHOUT touching the network when an endpoint's breaker is
    open — the fail-fast path a federated fan-out uses to skip a member
    that has already proven unhealthy (partial-results mode) instead of
    burning its latency budget re-timing-out against it."""

    def __init__(self, endpoint: str, retry_after_s: float):
        super().__init__(
            f"circuit open for {endpoint} (retry in {retry_after_s:.2f}s)"
        )
        self.endpoint = endpoint
        self.retry_after_s = retry_after_s


class RateLimitedError(ConnectionError):
    """A member answered ``429 Too Many Requests`` — its admission
    controller (serving/admission.py) shed the request. Classified
    NON-retryable in :func:`retryable`: the server's ``Retry-After``
    (carried here as ``retry_after_s``) is an explicit back-off
    instruction, and a local retry loop hammering a shedding endpoint
    is a retry storm by construction. A ``ConnectionError`` subclass so
    partial-mode federations degrade on a shed member like any other
    member failure (:data:`MEMBER_FAILURE_TYPES`)."""

    def __init__(self, endpoint: str, retry_after_s: float):
        super().__init__(
            f"rate limited by {endpoint} "
            f"(retry after {retry_after_s:.2f}s)"
        )
        self.endpoint = endpoint
        self.retry_after_s = retry_after_s


class MemberDrainingError(ConnectionError):
    """A member answered ``503 Service Unavailable`` WITH a
    ``Retry-After`` header — the elastic federation's drain signal
    (docs/operations.md § Drain procedure): the member is alive and
    finishing in-flight work but wants no new requests while its shards
    migrate away. Distinct from a generic 5xx on every axis:

    - **reads** are retryable-with-backoff (:func:`retryable` returns
      True for idempotent calls): the shard map is about to move, and
      the retry — delayed by at least ``retry_after_s``, honored as a
      floor by :meth:`RetryPolicy.call` — lands on the new owner.
    - **writes** are NOT retryable here: the sharded view re-reads its
      router generation and re-routes the failed slice immediately
      instead of hammering the draining member.
    - it never counts against the circuit breaker
      (``resilience/http.py``): a drain is planned, cooperative
      unavailability — burning the breaker toward open would turn every
      membership change into a synthetic outage.

    A ``ConnectionError`` subclass so partial-mode federations degrade
    on a draining member like any other member failure
    (:data:`MEMBER_FAILURE_TYPES`)."""

    def __init__(self, endpoint: str, retry_after_s: float):
        super().__init__(
            f"member draining at {endpoint} "
            f"(retry after {retry_after_s:.2f}s)"
        )
        self.endpoint = endpoint
        self.retry_after_s = retry_after_s


class CorruptPayloadError(RuntimeError):
    """A remote member answered 200 but the payload failed to decode
    (truncated/corrupt Arrow IPC, garbage JSON). Typed so federation
    callers can degrade on it like any other member failure instead of
    surfacing an opaque pyarrow/json traceback."""


def _connect_failure(exc: BaseException) -> bool:
    """True when the failure happened BEFORE the request reached the
    server (connection refused / DNS / socket connect) — the only class a
    non-idempotent mutation may safely retry: the server never saw it.

    ``urllib`` wraps connect-phase OSErrors in a plain ``URLError``;
    ``HTTPError`` (a URLError subclass) means a response came back, so it
    is explicitly NOT a connect failure."""
    if isinstance(exc, urllib.error.HTTPError):
        return False
    if isinstance(exc, urllib.error.URLError):
        return True
    return isinstance(exc, ConnectionError)


def retryable(exc: BaseException, idempotent: bool) -> bool:
    """The retry classification gate.

    Idempotent calls (reads: ``query`` / ``stats_count`` / ``select_many``
    / journal polls) retry on any transport error or 5xx — re-running a
    read is always safe. Mutations retry ONLY on connect-before-send
    failures: a 5xx (or a socket that died mid-exchange) may have already
    applied the write, and replaying it could double-append."""
    if isinstance(exc, CircuitOpenError):
        return False  # fail fast: the breaker already decided
    if isinstance(exc, RateLimitedError):
        return False  # the endpoint TOLD us to back off (Retry-After)
    if isinstance(exc, MemberDrainingError):
        # a planned drain: reads retry (after the server's Retry-After,
        # honored as a delay floor in RetryPolicy.call — the shard map
        # is moving and the retry lands on the new owner); writes
        # re-route through a fresh router generation instead
        return idempotent
    if isinstance(exc, urllib.error.HTTPError) and exc.code == 429:
        # an admission shed: already non-retryable under both branches
        # below (<500 for reads, response-received for mutations), but
        # the classification is a CONTRACT — a retry storm against a
        # shedding endpoint defeats the shed (docs/serving.md)
        return False
    from geomesa_tpu.utils.timeouts import QueryTimeout

    if isinstance(exc, QueryTimeout):
        # a spent/blown deadline: retrying burns backoff sleeps and
        # budget tokens against the same dead budget
        return False
    if not idempotent:
        return _connect_failure(exc)
    if isinstance(exc, urllib.error.HTTPError):
        # 504 = the propagated deadline is spent at the remote; a retry
        # would burn round trips against the same dead budget
        return exc.code >= 500 and exc.code != 504
    # URLError (connect), ConnectionError, socket.timeout, raw OSError
    return isinstance(exc, (urllib.error.URLError, OSError))


# the federation's member-failure set: exceptions a `partial`-mode fan-out
# may degrade on (skip the member, serve the rest). Semantic errors —
# KeyError/ValueError/PermissionError mapped from 4xx — are NOT here: a
# missing schema or bad filter is the caller's bug on every member alike.
# CircuitOpenError/ConnectionError/HTTPError/URLError/timeout ⊂ OSError.
MEMBER_FAILURE_TYPES: tuple = (OSError, CorruptPayloadError, TimeoutError)


def is_member_failure(exc: BaseException) -> bool:
    return isinstance(exc, MEMBER_FAILURE_TYPES)


class RetryPolicy:
    """Exponential backoff with decorrelated jitter + a per-policy retry
    budget.

    - Backoff: the AWS "decorrelated jitter" schedule —
      ``sleep_n = min(cap, uniform(base, sleep_{n-1} * 3))`` — spreads
      synchronized retry storms across a federated fan-out.
    - Budget: a token bucket of retries per window shared by every call
      through this policy. When a member is hard-down, N queued queries
      must not each burn ``max_attempts`` round-trips; once the bucket is
      dry, calls fail on their first error (the breaker then opens and
      stops even that).
    - Idempotency: ``call(fn, idempotent=False)`` retries only
      connect-before-send failures (see :func:`retryable`).

    Deterministic in tests: pass ``seed`` (jitter) and ``clock``/``sleep``
    doubles. The instance is thread-safe; only the token bucket and the
    jitter RNG are shared state, both guarded by one leaf lock.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        budget: int = 64,
        budget_window_s: float = 10.0,
        seed: int | None = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.budget = budget
        self.budget_window_s = budget_window_s
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()  # leaf: guards rng + bucket only
        self._rng = random.Random(seed)
        self._tokens = float(budget)
        self._refill_at = clock()

    # -- budget ---------------------------------------------------------------
    def _take_token(self) -> bool:
        """One retry token, refilled at ``budget / window`` per second."""
        with self._lock:
            now = self._clock()
            dt = now - self._refill_at
            if dt > 0:
                self._tokens = min(
                    float(self.budget),
                    self._tokens + dt * (self.budget / self.budget_window_s),
                )
                self._refill_at = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def budget_remaining(self) -> int:
        with self._lock:
            return int(self._tokens)

    # -- backoff --------------------------------------------------------------
    def next_delay(self, prev_delay_s: float | None = None) -> float:
        """One decorrelated-jitter step; loop-style callers (the remote
        journal tailer) feed the previous delay back in."""
        lo = self.base_delay_s
        hi = max(lo, (prev_delay_s if prev_delay_s else lo) * 3.0)
        with self._lock:
            d = self._rng.uniform(lo, hi)
        return min(self.max_delay_s, d)

    # -- the retry loop -------------------------------------------------------
    def call(self, fn, *, idempotent: bool = True, on_retry=None):
        """Run ``fn()`` with retries. ``on_retry(attempt, delay_s, exc)``
        observes each scheduled retry (metrics/trace hook). The last
        error re-raises unchanged when attempts/budget run out."""
        delay: float | None = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                if attempt >= self.max_attempts:
                    raise
                if not retryable(exc, idempotent):
                    raise
                if not self._take_token():
                    raise  # budget dry: shed the retry, surface the error
                delay = self.next_delay(delay)
                # a draining member's Retry-After is a delay FLOOR (the
                # server knows when its cutover lands), capped by the
                # policy's own ceiling so a hostile header cannot park
                # the caller indefinitely
                retry_after = getattr(exc, "retry_after_s", None)
                if retry_after:
                    delay = max(
                        delay, min(float(retry_after), self.max_delay_s))
                if on_retry is not None:
                    on_retry(attempt, delay, exc)
                self._sleep(delay)  # outside every lock
        raise AssertionError("unreachable")  # pragma: no cover


class CircuitBreaker:
    """Per-endpoint three-state breaker: ``closed`` → ``open`` →
    ``half_open`` → (``closed`` | ``open``).

    Closed: outcomes land in a sliding window of the last ``window``
    calls; once at least ``min_volume`` outcomes are in and the failure
    rate reaches ``failure_rate``, the breaker opens. Open: every
    :meth:`before_call` raises :class:`CircuitOpenError` until
    ``cooldown_s`` passes, then the breaker half-opens. Half-open: up to
    ``probes`` trial calls go through; the first success closes the
    breaker (window reset), the first failure re-opens it (cooldown
    restarts).

    Thread-safe; one leaf lock, no blocking calls under it. ``clock`` is
    injectable so state transitions are testable without real sleeps.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        endpoint: str = "?",
        window: int = 20,
        min_volume: int = 5,
        failure_rate: float = 0.5,
        cooldown_s: float = 5.0,
        probes: int = 1,
        clock=time.monotonic,
    ):
        self.endpoint = endpoint
        self.window = window
        self.min_volume = min_volume
        self.failure_rate = failure_rate
        self.cooldown_s = cooldown_s
        self.probes = probes
        self._clock = clock
        self._lock = threading.Lock()  # leaf: state machine only
        self._state = self.CLOSED
        self._outcomes: list[bool] = []  # True = failure, bounded by window
        self._opened_at = 0.0
        self._inflight_probes = 0
        self.open_count = 0  # lifetime open transitions (metrics surface)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        # lazily promote open → half_open when the cooldown has passed; the
        # next before_call() will hand out probe slots
        if (
            self._state == self.OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._state = self.HALF_OPEN
            self._inflight_probes = 0
        return self._state

    def before_call(self) -> None:
        """Gate one call: raises :class:`CircuitOpenError` when open (or
        half-open with every probe slot taken)."""
        with self._lock:
            st = self._state_locked()
            if st == self.CLOSED:
                return
            if st == self.HALF_OPEN and self._inflight_probes < self.probes:
                self._inflight_probes += 1
                return
            remaining = self.cooldown_s - (self._clock() - self._opened_at)
            raise CircuitOpenError(self.endpoint, max(remaining, 0.0))

    def record(self, failure: bool) -> None:
        with self._lock:
            st = self._state_locked()
            if st == self.HALF_OPEN:
                if self._inflight_probes <= 0:
                    # a slow call issued BEFORE the trip completing now:
                    # stale signal, not a probe outcome — it must neither
                    # close the breaker nor restart the cooldown
                    return
                self._inflight_probes -= 1
                if failure:  # probe failed: re-open, cooldown restarts
                    self._trip_locked()
                else:  # probe succeeded: fresh window, endpoint healthy
                    self._state = self.CLOSED
                    self._outcomes.clear()
                return
            if st == self.OPEN:
                return  # late completion from before the trip: stale signal
            self._outcomes.append(failure)
            if len(self._outcomes) > self.window:
                del self._outcomes[0]
            n = len(self._outcomes)
            if n >= self.min_volume:
                rate = sum(self._outcomes) / n
                if rate >= self.failure_rate:
                    self._trip_locked()

    def _trip_locked(self) -> None:
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._outcomes.clear()
        self._inflight_probes = 0
        self.open_count += 1

    def record_success(self) -> None:
        self.record(False)

    def record_failure(self) -> None:
        self.record(True)
