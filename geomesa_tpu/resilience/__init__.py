"""geomesa_tpu.resilience — failure handling for the remote/federation stack.

Three layers (see docs/resilience.md):

- :mod:`~geomesa_tpu.resilience.policy` — :class:`RetryPolicy`
  (exponential backoff + decorrelated jitter, per-policy retry budget,
  idempotency-aware classification) and the per-endpoint three-state
  :class:`CircuitBreaker`.
- :mod:`~geomesa_tpu.resilience.http` — the single ``urlopen`` choke
  point every remote client uses, the shared server→client error-mapping
  request helper, and ``X-Geomesa-Deadline-Ms`` deadline propagation.
- :mod:`~geomesa_tpu.resilience.faults` — the deterministic
  :class:`FaultInjector` seam (``GEOMESA_TPU_FAULTS`` env spec or
  programmatic rules with seeded schedules) behind the chaos tests and
  ``bench.py --chaos``.

This package imports no jax and no store/stream modules: it sits below
the clients that use it, and ``GEOMESA_TPU_NO_JAX=1`` processes import it
freely. Its locks (breaker state, retry budget, injector counters) are
leaves of the lock hierarchy in docs/concurrency.md — nothing blocking
ever runs under them.
"""

from geomesa_tpu.resilience.policy import (  # noqa: F401 — public surface
    MEMBER_FAILURE_TYPES,
    CircuitBreaker,
    CircuitOpenError,
    CorruptPayloadError,
    RateLimitedError,
    RetryPolicy,
    is_member_failure,
    retryable,
)

__all__ = [
    "MEMBER_FAILURE_TYPES",
    "CircuitBreaker",
    "CircuitOpenError",
    "CorruptPayloadError",
    "RateLimitedError",
    "RetryPolicy",
    "is_member_failure",
    "retryable",
]
