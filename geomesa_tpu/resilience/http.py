"""The one HTTP seam every remote client goes through.

Four jobs, one call site:

- :func:`fetch` is the SINGLE ``urlopen`` in the tree's remote clients —
  the choke point where :mod:`~geomesa_tpu.resilience.faults` injects
  connection refusals, 5xx responses, added latency, and payload
  truncation/corruption. One seam means chaos coverage of every client
  (store, journal, schema registry) for free.
- :func:`request` wraps fetch with the resilience envelope: per-endpoint
  :class:`~geomesa_tpu.resilience.policy.CircuitBreaker` gating,
  :class:`~geomesa_tpu.resilience.policy.RetryPolicy` with idempotency
  classification, and end-to-end deadline propagation (the
  ``X-Geomesa-Deadline-Ms`` header carries the caller's REMAINING budget
  in milliseconds; a spent budget sheds locally without a round trip).
- :func:`map_http_error` is the shared server→client error inversion
  (the web layer maps ValueError→400, KeyError→404, PermissionError→403,
  QueryTimeout→504; clients invert it here) so GET and mutation paths
  surface identical exception types — the ``RemoteDataStore._get`` /
  ``_send`` divergence this replaces leaked raw ``HTTPError`` from reads.
- distributed-trace propagation (docs/observability.md): every traced
  exchange runs under an ``rpc`` span that injects ``X-Geomesa-Trace``,
  records attempts/retries/breaker-state/deadline-budget, and grafts the
  remote's returned span subtree (``X-Geomesa-Trace-Return``) so every
  federated query reads as ONE stitched tree. One choke point means
  every client (store, journal, schema registry) propagates for free.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request

from geomesa_tpu.obs import trace as _trace
from geomesa_tpu.obs import usage as _usage
from geomesa_tpu.resilience import faults
from geomesa_tpu.resilience.policy import (
    CircuitBreaker,
    MemberDrainingError,
    RateLimitedError,
    RetryPolicy,
)
from geomesa_tpu.utils.timeouts import Deadline, QueryTimeout

__all__ = ["DEADLINE_HEADER", "TENANT_HEADER", "fetch", "map_http_error",
           "request"]

# remaining deadline budget, in milliseconds, at the moment of send: each
# hop re-derives its own absolute deadline from the budget, so no wall
# clocks ever need to agree across hosts
DEADLINE_HEADER = "X-Geomesa-Deadline-Ms"

# tenant propagation (docs/observability.md § Usage metering): a
# federated RPC carries the ORIGINAL caller's tenant so the member's
# flight/usage records attribute to the end user, not to the federation
# frontend. One choke point = every remote client propagates for free.
TENANT_HEADER = _usage.TENANT_HEADER

# socket-timeout slack past the propagated deadline: the REMOTE is the
# authority on its own expiry (it sheds with a 504 we want to hear); the
# local socket only backstops a remote that stopped answering entirely
_DEADLINE_SOCKET_SLACK_S = 0.25


def fetch(req: urllib.request.Request, timeout_s: float) -> bytes:
    """The urlopen choke point: read one full response body, with fault
    hooks on both sides of the wire. Raises exactly what ``urlopen``
    raises (plus whatever the active injector fabricates)."""
    return _fetch(req, timeout_s)[0]


def _fetch(req: urllib.request.Request, timeout_s: float):
    """fetch plus the response headers — :func:`request` needs them for
    the ``X-Geomesa-Trace-Return`` span subtree."""
    inj = faults.active()
    method = req.get_method()
    if inj is not None:
        inj.before_send(method, req.full_url)
    with urllib.request.urlopen(req, timeout=timeout_s) as r:  # noqa: S310
        data = r.read()
        headers = r.headers
    if inj is not None:
        data = inj.after_receive(method, req.full_url, data)
    return data, headers


def map_http_error(e: urllib.error.HTTPError):
    """Invert the web layer's exception→status mapping. 5xx re-raises
    unchanged (server/proxy trouble is not a conflict/validation error —
    callers classify it as a member failure)."""
    if e.code >= 500:
        raise e
    try:
        msg = json.loads(e.read().decode()).get("error", str(e))
    except Exception:  # noqa: BLE001 — non-JSON error body
        msg = str(e)
    if e.code == 404:
        raise KeyError(msg) from None
    if e.code == 403:
        raise PermissionError(msg) from None
    raise ValueError(msg) from None


def _breaker_failure(exc: BaseException) -> bool:
    """What counts against an endpoint's health: transport errors and 5xx.
    A 4xx is the endpoint answering correctly (caller-side semantics); a
    declared drain (:class:`MemberDrainingError`) is the endpoint
    answering correctly too — planned, cooperative unavailability must
    not push the breaker toward open (a membership change is not an
    outage)."""
    if isinstance(exc, MemberDrainingError):
        return False
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500
    return isinstance(exc, (OSError, TimeoutError))


def _as_draining(exc: BaseException, url: str) -> MemberDrainingError | None:
    """503 WITH ``Retry-After`` is a draining member's declared signal
    (docs/operations.md § Drain procedure) — typed at the choke point so
    every client classifies it identically. A bare 503 (a proxy dying,
    an overloaded server with no plan) stays a generic 5xx."""
    if not isinstance(exc, urllib.error.HTTPError) or exc.code != 503:
        return None
    hdr = exc.headers.get("Retry-After") if exc.headers else None
    if not hdr:
        return None
    try:
        ra = float(hdr)
    except (TypeError, ValueError):
        return None
    return MemberDrainingError(url, ra)


def request(
    method: str,
    url: str,
    *,
    params: dict | None = None,
    body: dict | None = None,
    headers: dict | None = None,
    timeout_s: float = 30.0,
    retry: RetryPolicy | None = None,
    breaker: CircuitBreaker | None = None,
    idempotent: bool = True,
    deadline: Deadline | None = None,
    map_errors: bool = True,
    on_retry=None,
) -> bytes:
    """One resilient HTTP exchange; returns the raw response body.

    The retry loop re-gates the breaker and re-derives the deadline
    header on EVERY attempt (a retry after backoff has less budget left
    than the first try). With ``map_errors`` (the store-client contract)
    4xx responses surface as the local store's exception types and 504 as
    :class:`~geomesa_tpu.utils.timeouts.QueryTimeout`.

    Tracing (docs/observability.md § Distributed tracing): when the
    caller is traced, the whole exchange runs under one ``rpc`` span that
    (a) injects ``X-Geomesa-Trace`` so the remote member's spans join
    this trace, (b) records attempts/retries, breaker state, and the
    remaining deadline budget as span attributes — each scheduled retry
    is a span event — and (c) grafts the remote's returned span subtree
    (``X-Geomesa-Trace-Return``) underneath itself, so the caller sees
    ONE stitched tree per federated query. Untraced calls pay one
    no-op-span check.
    """
    full = url
    if params:
        full += "?" + urllib.parse.urlencode(params)
    data = None if body is None else json.dumps(body).encode()
    base_headers = dict(headers or {})
    if data is not None:
        base_headers.setdefault("Content-Type", "application/json")
    # tenant context → header (one ContextVar read per exchange; absent
    # outside a request/replay context). An explicit caller-set header
    # wins — the web layer's trust posture stays with the proxy.
    tenant = _usage.current_tenant(default=None)
    if tenant and TENANT_HEADER not in base_headers:
        base_headers[TENANT_HEADER] = tenant

    with _trace.span("rpc", method=method, endpoint=url) as rpc:
        traced = isinstance(rpc, _trace.Span)
        n_attempts = 0
        last_headers = None

        def attempt() -> bytes:
            nonlocal n_attempts, last_headers
            n_attempts += 1
            hdrs = dict(base_headers)
            if traced:
                tr = _trace.inject()  # current span IS the rpc span
                if tr:
                    hdrs[_trace.TRACE_HEADER] = tr
                rpc.set(attempts=n_attempts)
                if breaker is not None:
                    rpc.set(breaker=breaker.state)
            eff_timeout = timeout_s
            if deadline is not None:
                # shed BEFORE the breaker gate: a shed records no outcome,
                # so gating first could consume a half-open probe slot
                # that is then never released
                rem_s = deadline.remaining_s()
                if rem_s <= 0:
                    # no round trip for a query that cannot finish in time
                    # anyway (the server would 504 it)
                    raise QueryTimeout(
                        f"deadline spent before request to {url}")
                hdrs[DEADLINE_HEADER] = str(int(rem_s * 1000) or 1)
                eff_timeout = min(timeout_s, rem_s + _DEADLINE_SOCKET_SLACK_S)
                if traced:
                    rpc.set(deadline_remaining_ms=round(rem_s * 1000.0, 1))
            if breaker is not None:
                breaker.before_call()  # raises CircuitOpenError when open
            req = urllib.request.Request(
                full, data=data, method=method, headers=hdrs)
            try:
                out, resp_headers = _fetch(req, eff_timeout)
            except QueryTimeout:
                raise  # local shed: says nothing about endpoint health
            except Exception as exc:  # noqa: BLE001 — classified for the breaker
                drain = _as_draining(exc, url)
                if drain is not None:
                    if breaker is not None:
                        # the endpoint answered exactly as designed: a
                        # drain outcome is a SUCCESS for breaker health
                        breaker.record(False)
                    raise drain from exc
                if breaker is not None:
                    breaker.record(_breaker_failure(exc))
                if (
                    deadline is not None and deadline.expired()
                    and isinstance(exc, OSError)
                ):
                    # a transport error after the budget ran out IS the
                    # deadline: surface the uniform timeout type
                    raise QueryTimeout(
                        f"deadline expired during request to {url}") from exc
                raise
            if breaker is not None:
                breaker.record_success()
            last_headers = resp_headers
            return out

        def _on_retry(attempt_n: int, delay_s: float, exc) -> None:
            if traced:
                rpc.set(retries=attempt_n)
                rpc.event(
                    "retry", attempt=attempt_n,
                    delay_ms=round(delay_s * 1000.0, 2),
                    error=type(exc).__name__,
                )
            if on_retry is not None:
                on_retry(attempt_n, delay_s, exc)

        try:
            if retry is None:
                raw = attempt()
            else:
                raw = retry.call(attempt, idempotent=idempotent,
                                 on_retry=_on_retry)
        except urllib.error.HTTPError as e:
            if not map_errors:
                # raw-error callers (remote journal, schema registry)
                # classify HTTPError codes themselves — 429 included
                raise
            if e.code == 429:
                # the remote's admission controller shed this request
                # (serving/admission.py): surface the typed, honor-the-
                # Retry-After error — the retry loop above never retried
                # it (classified non-retryable), so a shedding member
                # costs ONE round trip, not a retry storm
                ra = None
                try:
                    hdr = e.headers.get("Retry-After") if e.headers else None
                    ra = float(hdr) if hdr else None
                except (TypeError, ValueError):
                    ra = None
                raise RateLimitedError(
                    url, 1.0 if ra is None else ra) from None
            if e.code == 504:
                # the remote shed/expired the work: the federation-wide
                # timeout surface, same type the local watchdog raises
                raise QueryTimeout(f"remote {url} exceeded deadline") from None
            map_http_error(e)
            raise AssertionError("unreachable")  # pragma: no cover
        if traced and last_headers is not None:
            enc = last_headers.get(_trace.TRACE_RETURN_HEADER)
            if enc:
                # the remote member's span subtree joins this trace as a
                # child of the rpc span (clock re-anchored inside it)
                _trace.graft_serialized(rpc, enc)
        return raw
