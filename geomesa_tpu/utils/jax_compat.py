"""Version-bridging imports for the JAX surface this package leans on.

The hot paths are written against the current stable spelling of each
API; older installed versions keep working through the fallbacks here so
the device layer has exactly one place that knows about JAX version
drift (every other module imports the symbol from here).
"""

from __future__ import annotations

__all__ = ["enable_x64", "shard_map"]

import inspect

try:  # jax >= 0.5 top-level spelling
    from jax import enable_x64
except ImportError:  # jax 0.4.x
    from jax.experimental import enable_x64

try:  # jax >= 0.5: promoted to the top-level namespace
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` with the current keyword surface; replication
    checking is requested as ``check_vma`` and translated to the older
    ``check_rep`` spelling when that is what the installed JAX accepts."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)
