"""File-based distributed locking for catalog mutation.

Role parity: ``geomesa-index-api/.../index/utils/DistributedLocking.scala:14``
(SURVEY.md §2.3): the reference wraps schema create/update/delete in a
Zookeeper (Curator) lock keyed by the catalog path so concurrent clients can't
corrupt shared metadata. Here the shared medium is the persisted catalog
directory, so the lock is an ``fcntl.flock`` on a lockfile inside it — correct
across processes on one host and over NFS mounts that support flock; the
multi-slice coordination story (SURVEY.md §5) goes through the job scheduler
instead of a lock service.
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import time

__all__ = ["catalog_lock", "LockTimeout"]


class LockTimeout(TimeoutError):
    pass


@contextlib.contextmanager
def catalog_lock(path: str, timeout_s: float = 30.0, poll_s: float = 0.05):
    """Exclusive advisory lock on ``<path>/.geomesa.lock``.

    ``path`` is created if missing (locking a catalog that doesn't exist yet
    is the schema-create case).
    """
    os.makedirs(path, exist_ok=True)
    lock_path = os.path.join(path, ".geomesa.lock")
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                if time.monotonic() >= deadline:
                    raise LockTimeout(
                        f"could not lock catalog {path!r} within {timeout_s}s"
                    ) from None
                time.sleep(poll_s)
        yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)
