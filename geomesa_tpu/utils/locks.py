"""File-based distributed locking for catalog mutation.

Role parity: ``geomesa-index-api/.../index/utils/DistributedLocking.scala:14``
(SURVEY.md §2.3): the reference wraps schema create/update/delete in a
Zookeeper (Curator) lock keyed by the catalog path so concurrent clients can't
corrupt shared metadata. Here the shared medium is the persisted catalog
directory and the lock is layered:

- :func:`lease_lock` — a CROSS-HOST expiring lease: ``O_CREAT|O_EXCL``
  creation of a lease file (atomic on local filesystems and on NFSv3+) with
  a wall-clock expiry; stale leases are broken by an atomic rename, so a
  crashed holder delays, never deadlocks, other hosts. This is the
  ZK-ephemeral-node analog (leases assume loosely synchronized clocks —
  the standard lease caveat).
- :func:`catalog_lock` — ``fcntl.flock`` (cheap, immediate same-host
  serialization) wrapping the lease (cross-host), in that fixed order.
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import json
import os
import socket
import threading
import time
import uuid

__all__ = [
    "catalog_lock", "lease_lock", "http_lease_lock", "LeaseService",
    "LockTimeout", "reap_dead_claims",
]


class LockTimeout(TimeoutError):
    pass


class LeaseService:
    """Server-side lease authority: named expiring leases over HTTP (the
    Zookeeper-ensemble role collapsed to one coordinator service — the
    reference's ``DistributedLocking.scala:14`` gets mutual exclusion from
    ZK; hosts with NO shared filesystem get it from this service via
    ``/api/lease`` on :mod:`geomesa_tpu.web.app`).

    All decisions happen in one process under one mutex, so correctness
    needs no clock agreement between clients — only the coordinator's
    clock times out abandoned leases (crash recovery: a dead holder
    delays, never deadlocks, other hosts — same posture as
    :func:`lease_lock`)."""

    def __init__(self):
        self._mu = threading.Lock()
        # name -> (token, holder, expires_unix)
        self._leases: dict[str, tuple[str, str, float]] = {}

    def acquire(self, name: str, holder: str, ttl_s: float) -> dict:
        now = time.time()
        with self._mu:
            cur = self._leases.get(name)
            if cur is not None and cur[2] > now:
                return {"ok": False, "holder": cur[1], "expires_unix": cur[2]}
            token = uuid.uuid4().hex
            self._leases[name] = (token, holder, now + ttl_s)
            return {"ok": True, "token": token}

    def renew(self, name: str, token: str, ttl_s: float) -> dict:
        with self._mu:
            cur = self._leases.get(name)
            if cur is None or cur[0] != token:
                return {"ok": False}
            self._leases[name] = (cur[0], cur[1], time.time() + ttl_s)
            return {"ok": True}

    def release(self, name: str, token: str) -> dict:
        with self._mu:
            cur = self._leases.get(name)
            # releasing an expired-and-retaken lease must not evict the
            # new holder: token mismatch is a no-op, not an error
            if cur is not None and cur[0] == token:
                del self._leases[name]
            return {"ok": True}


@contextlib.contextmanager
def http_lease_lock(base_url: str, name: str = "catalog",
                    ttl_s: float = 60.0, timeout_s: float = 30.0,
                    poll_s: float = 0.05):
    """Cross-host expiring lease from a coordinator's ``/api/lease``
    endpoint (:class:`LeaseService`) — mutual exclusion between hosts with
    NO shared filesystem. Same interface and caveats as
    :func:`lease_lock`: hold times must stay well under ``ttl_s``."""
    import urllib.request

    holder = f"{socket.gethostname()}:{os.getpid()}"
    base = base_url.rstrip("/")

    def _post(op: str, body: dict) -> dict:
        req = urllib.request.Request(
            f"{base}/api/lease/{op}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10.0) as r:
            return json.loads(r.read())

    deadline = time.monotonic() + timeout_s
    while True:
        out = _post("acquire", {"name": name, "holder": holder,
                                "ttl_s": ttl_s})
        if out.get("ok"):
            token = out["token"]
            break
        if time.monotonic() >= deadline:
            raise LockTimeout(
                f"could not acquire lease {name!r} from {base!r} within "
                f"{timeout_s}s (held by {out.get('holder')})"
            )
        time.sleep(poll_s)
    try:
        yield
    finally:
        with contextlib.suppress(OSError):
            _post("release", {"name": name, "token": token})


@contextlib.contextmanager
def lease_lock(path: str, name: str = "catalog", ttl_s: float = 60.0,
               timeout_s: float = 30.0, poll_s: float = 0.05,
               settle_s: float = 0.05):
    """Cross-host expiring lease via ORDERED CLAIM FILES under
    ``<path>/.geomesa.<name>.claims/`` — the ZK sequential-ephemeral-node
    recipe on a shared filesystem.

    Each contender writes a claim whose NAME freezes its creation order:
    the file is created first, its ctime (assigned by the one filesystem
    clock, so comparable across hosts) is read back, and the file is
    renamed to ``c-<ctime_ns>-<token>``. The lock belongs to the
    lexicographically smallest live claim. A later creator necessarily
    observes an earlier ctime and therefore can never preempt a decision
    already made — after ``settle_s`` (which covers clock-quantization
    ties) all racers see the same winner. Nothing is ever renamed or
    deleted out from under a live holder: crash recovery is reaping claims
    whose expiry passed (waiters refresh their expiry in place each poll;
    refreshing rewrites content, never the name, so order is stable).

    Caveats (standard lease semantics): hold times must stay well under
    ``ttl_s`` — a holder stalled longer can be reaped; expiry compares the
    shared wall clock, so host clocks must be loosely synchronized."""
    claims = os.path.join(path, f".geomesa.{name}.claims")
    os.makedirs(claims, exist_ok=True)
    token = uuid.uuid4().hex
    holder = f"{socket.gethostname()}:{os.getpid()}"

    def _payload() -> bytes:
        return json.dumps(
            {"holder": holder, "expires_unix": time.time() + ttl_s}
        ).encode()

    tmp = os.path.join(claims, f"tmp-{token}")
    with open(tmp, "wb") as f:
        f.write(_payload())
    t_ns = os.stat(tmp).st_ctime_ns
    mine = os.path.join(claims, f"c-{t_ns:020d}-{token}")
    os.rename(tmp, mine)
    try:
        time.sleep(settle_s)  # racing claims with tied ctimes become visible
        deadline = time.monotonic() + timeout_s
        my_key = os.path.basename(mine)
        while True:
            winner = my_key
            for fn in sorted(os.listdir(claims)):
                if not fn.startswith("c-") or fn == my_key:
                    continue
                p = os.path.join(claims, fn)
                try:
                    st = os.stat(p)
                except OSError:
                    continue  # reaped concurrently
                try:
                    with open(p, "rb") as f:
                        info = json.loads(f.read().decode())
                    expired = time.time() > float(info["expires_unix"])
                except (OSError, ValueError, KeyError, TypeError):
                    # torn refresh: live waiters rewrite every poll, so a
                    # genuinely dead claim has an OLD mtime
                    expired = time.time() - st.st_mtime > ttl_s
                if expired:
                    with contextlib.suppress(OSError):
                        os.unlink(p)
                    continue
                winner = min(winner, fn)
                break  # sorted listing: first live claim is the winner
            if winner == my_key:
                break
            if time.monotonic() >= deadline:
                raise LockTimeout(
                    f"could not acquire lease {claims!r} within {timeout_s}s"
                )
            time.sleep(poll_s)
            # refresh expiry in place — content swap, name (= order) stable
            rtmp = os.path.join(claims, f"tmp-{token}")
            with open(rtmp, "wb") as f:
                f.write(_payload())
            os.replace(rtmp, mine)
        yield
    finally:
        with contextlib.suppress(OSError):
            os.unlink(mine)


def reap_dead_claims(path: str, name: str = "catalog") -> int:
    """Remove lease claims held by DEAD processes of THIS host (pid probe
    via ``kill(pid, 0)``), regardless of expiry. A SIGKILLed checkpoint
    leaves its claim behind and every later :func:`catalog_lock` waits out
    the full TTL on it; crash recovery (``DataStore.open`` — which holds
    the exclusive WAL catalog lock, so no live writer can be racing) calls
    this to skip that dead time. Claims from other hosts (whose liveness
    we cannot probe) are left to the normal expiry path. Returns the
    claims reaped."""
    claims = os.path.join(path, f".geomesa.{name}.claims")
    host = socket.gethostname()
    reaped = 0
    try:
        names = os.listdir(claims)
    except OSError:
        return 0
    for fn in names:
        # never touch tmp- files: a LIVE contender may be mid-write on one
        # (claim creation / per-poll refresh) — only settled c- claims
        if not fn.startswith("c-"):
            continue
        p = os.path.join(claims, fn)
        try:
            with open(p, "rb") as f:
                raw = f.read().decode()
            info = json.loads(raw)
            holder = str(info.get("holder", ""))
            h_host, _, h_pid = holder.rpartition(":")
            pid = int(h_pid)
        except (OSError, ValueError, KeyError, TypeError):
            # unreadable/torn content is NOT evidence of death (a live
            # refresh may be racing); leave it to the normal expiry path
            continue
        if h_host != host:
            continue
        try:
            os.kill(pid, 0)  # raises if the holder is gone
        except ProcessLookupError:
            with contextlib.suppress(OSError):
                os.unlink(p)
                reaped += 1
        except OSError:
            continue
    return reaped


@contextlib.contextmanager
def catalog_lock(path: str, timeout_s: float = 30.0, poll_s: float = 0.05,
                 lease_ttl_s: float = 60.0):
    """Exclusive catalog mutation lock: same-host ``flock`` on
    ``<path>/.geomesa.lock`` wrapping a cross-host :func:`lease_lock`.

    ``path`` is created if missing (locking a catalog that doesn't exist yet
    is the schema-create case).

    When ``GEOMESA_COORDINATOR_URL`` is set the cross-host layer is
    :func:`http_lease_lock` against that coordinator instead of the
    filesystem lease — so a shared mount is an optimization, not a
    requirement, for multi-host catalog mutation.
    """
    os.makedirs(path, exist_ok=True)
    lock_path = os.path.join(path, ".geomesa.lock")
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                if time.monotonic() >= deadline:
                    raise LockTimeout(
                        f"could not lock catalog {path!r} within {timeout_s}s"
                    ) from None
                time.sleep(poll_s)
        coord = os.environ.get("GEOMESA_COORDINATOR_URL")
        # the lease names a LOGICAL catalog: hosts mounting one catalog at
        # different local paths must set GEOMESA_CATALOG_LOCK_NAME to the
        # shared name, else the host-local abspath would give each mount
        # its own lease (no exclusion at all)
        lock_name = (os.environ.get("GEOMESA_CATALOG_LOCK_NAME")
                     or os.path.abspath(path))
        cross_host = (
            http_lease_lock(
                coord, name=lock_name, ttl_s=lease_ttl_s,
                timeout_s=max(0.0, deadline - time.monotonic()) or 0.001,
                poll_s=poll_s,
            )
            if coord
            else lease_lock(
                path, ttl_s=lease_ttl_s,
                timeout_s=max(0.0, deadline - time.monotonic()) or 0.001,
                poll_s=poll_s,
            )
        )
        with cross_host:
            yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)
