"""Z3-prefixed feature-id generation.

Role parity: ``geomesa-utils/.../uuid/`` Z3 time-UUIDs (332 LoC — SURVEY.md
§2.18) used by ``GeoMesaFeatureWriter`` id generation
(``geotools/GeoMesaFeatureWriter.scala:81``): appended features get ids whose
leading bytes are the feature's coarse z3, so the ID index clusters
spatially/temporally alongside the Z3 index and id-range scans of co-located
features stay contiguous. Format here: 16 hex chars of shard+bin+z3 prefix,
a dash, then 16 random hex chars.
"""

from __future__ import annotations

import secrets

import numpy as np

from geomesa_tpu.curve.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curve.sfc import z3_sfc

__all__ = ["z3_fids", "Z3FidGenerator"]


def z3_fids(lons, lats, t_ms, period: TimePeriod = TimePeriod.WEEK) -> np.ndarray:
    """Vectorized z3-prefixed ids for (lon, lat, epoch-ms) arrays."""
    lons = np.asarray(lons, dtype=np.float64)
    lats = np.asarray(lats, dtype=np.float64)
    t_ms = np.asarray(t_ms, dtype=np.int64)
    binned = BinnedTime(period)
    bins, offs = binned.to_bin_and_offset(t_ms)
    z = z3_sfc(period).index(lons, lats, offs)
    out = np.empty(len(lons), dtype=object)
    for i in range(len(lons)):
        prefix = (int(bins[i]) & 0xFFFF) << 48 | (int(z[i]) >> 16)
        out[i] = f"{prefix:016x}-{secrets.token_hex(8)}"
    return out


class Z3FidGenerator:
    """Stateful generator for streaming writers (one call per feature)."""

    def __init__(self, period: TimePeriod = TimePeriod.WEEK):
        self.period = period
        self.binned = BinnedTime(period)
        self.sfc = z3_sfc(period)

    def fid(self, lon: float, lat: float, t_ms: int) -> str:
        (b,), (o,) = self.binned.to_bin_and_offset(np.array([t_ms]))
        z = int(self.sfc.index(np.array([lon]), np.array([lat]), np.array([o]))[0])
        prefix = (int(b) & 0xFFFF) << 48 | (z >> 16)
        return f"{prefix:016x}-{secrets.token_hex(8)}"
