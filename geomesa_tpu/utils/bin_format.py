"""BIN track-point format: compact 16/24-byte records.

Capability parity with ``geomesa-utils/.../utils/bin/BinaryOutputEncoder.scala:59-81``
(SURVEY.md §2.18): big-endian records ``[trackId i32][dtg_secs i32][lat f32]
[lon f32]`` (16 B) with an optional 8-byte label (24 B). Encoding is one
vectorized structured-array write per batch instead of the reference's
per-feature callback loop.
"""

from __future__ import annotations

import numpy as np

RECORD_SIZE = 16
LABELED_RECORD_SIZE = 24

_DTYPE = np.dtype(
    [("track", ">i4"), ("dtg", ">i4"), ("lat", ">f4"), ("lon", ">f4")]
)
_DTYPE_LABEL = np.dtype(
    [("track", ">i4"), ("dtg", ">i4"), ("lat", ">f4"), ("lon", ">f4"), ("label", ">i8")]
)


def _track_ids(values) -> np.ndarray:
    """Attribute values → stable int32 track ids (hash, like the reference's
    ``trackId.hashCode``)."""
    return np.array(
        [np.int32(hash(v) & 0x7FFFFFFF) if v is not None else np.int32(0) for v in values],
        dtype=np.int32,
    )


def encode(
    lon: np.ndarray,
    lat: np.ndarray,
    dtg_millis: np.ndarray,
    track_values=None,
    label_values=None,
    sort_by_time: bool = False,
) -> bytes:
    """Vectorized encode of N points to BIN bytes."""
    n = len(lon)
    dtype = _DTYPE_LABEL if label_values is not None else _DTYPE
    out = np.empty(n, dtype=dtype)
    out["track"] = _track_ids(track_values) if track_values is not None else 0
    out["dtg"] = (np.asarray(dtg_millis, dtype=np.int64) // 1000).astype(np.int32)
    out["lat"] = np.asarray(lat, dtype=np.float32)
    out["lon"] = np.asarray(lon, dtype=np.float32)
    if label_values is not None:
        out["label"] = _track_ids(label_values).astype(np.int64)
    if sort_by_time:
        out = out[np.argsort(out["dtg"], kind="stable")]
    return out.tobytes()


def decode(data: bytes, labeled: bool = False) -> dict[str, np.ndarray]:
    """BIN bytes → column dict (for tests and client-side merging)."""
    dtype = _DTYPE_LABEL if labeled else _DTYPE
    arr = np.frombuffer(data, dtype=dtype)
    out = {
        "track": arr["track"].astype(np.int32),
        "dtg_secs": arr["dtg"].astype(np.int32),
        "lat": arr["lat"].astype(np.float32),
        "lon": arr["lon"].astype(np.float32),
    }
    if labeled:
        out["label"] = arr["label"].astype(np.int64)
    return out


def merge_sorted(chunks: list[bytes], labeled: bool = False) -> bytes:
    """Merge time-sorted BIN chunks into one time-sorted stream (the
    ``BinSorter`` role, ``index/utils/bin/BinSorter.scala``)."""
    dtype = _DTYPE_LABEL if labeled else _DTYPE
    data = b"".join(chunks)
    if not data:
        return b""
    # concatenate at the byte level: np.concatenate would silently convert the
    # big-endian fields to native order, corrupting the re-serialized stream
    merged = np.frombuffer(data, dtype=dtype)
    merged = merged[np.argsort(merged["dtg"], kind="stable")]
    return merged.tobytes()
