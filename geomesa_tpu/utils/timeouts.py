"""Query timeout watchdog.

Role parity: ``geomesa-index-api/.../index/utils/ThreadManagement.scala``
(SURVEY.md §2.3/§5): the reference registers every scan with a watchdog that
kills it past ``geomesa.query.timeout``. XLA device launches can't be killed
mid-kernel, but a runaway *query* (huge plan, giant residual refine, slow
host reduce) is interruptible at the Python layer: the scan runs on a worker
thread and the caller gives up — and flags the query as abandoned — when the
deadline passes (the worker's result is discarded when it eventually lands).
"""

from __future__ import annotations

import threading
import time

__all__ = ["Deadline", "QueryTimeout", "run_with_timeout", "Watchdog"]


class QueryTimeout(TimeoutError):
    pass


class Deadline:
    """An absolute point on the MONOTONIC clock a query must finish by.

    The end-to-end timeout unit of the federation stack: a caller makes
    one ``Deadline.after(2.0)`` and every hop — local scan workers
    (``Query.hints["deadline"]``), remote calls
    (:mod:`geomesa_tpu.resilience.http` ships the remaining budget as the
    ``X-Geomesa-Deadline-Ms`` header), and the web layer's shed check —
    measures against the SAME budget, so three 1-second hops under a
    2-second deadline fail at 2 seconds, not 3. Crossing the wire as
    *remaining milliseconds* (not a wall-clock timestamp) means hosts
    never need synchronized clocks; each hop re-anchors the budget on its
    own monotonic clock, losing only the (unmeasured) network transit.
    """

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = expires_at  # time.monotonic() seconds

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + ms / 1000.0)

    def remaining_s(self) -> float:
        return self.expires_at - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    def expired(self) -> bool:
        return self.remaining_s() <= 0

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"Deadline(remaining={self.remaining_s():.3f}s)"


_abandoned_lock = threading.Lock()
_abandoned_running = 0  # timed-out scans whose worker thread hasn't finished


def abandoned_running() -> int:
    """Scans that timed out but are still executing on their (daemon) worker
    thread — the watchdog's thread-exhaustion signal, surfaced in metrics."""
    return _abandoned_running


def run_with_timeout(fn, timeout_s: float | None, *args, **kwargs):
    """Run ``fn`` with a deadline; raises :class:`QueryTimeout` on expiry.

    With ``timeout_s`` None the call is inline (zero overhead) — the common
    case. Each timeout-opted call gets its own daemon worker thread: a wedged
    scan can't be killed, but it also can't starve later queries the way a
    fixed shared pool would (abandoned workers just linger until their scan
    returns, counted in :func:`abandoned_running`).
    """
    global _abandoned_running
    if timeout_s is None:
        return fn(*args, **kwargs)
    import contextvars

    finished = threading.Event()
    state = {"timed_out": False}
    box: list = [None, None]  # [result, exception]
    # the worker inherits the caller's context (trace spans propagate via
    # ContextVar — a timed-out query's scan spans must attach to ITS trace,
    # not float as orphan roots)
    ctx = contextvars.copy_context()

    def work():
        global _abandoned_running
        try:
            box[0] = ctx.run(fn, *args, **kwargs)
        except BaseException as e:  # propagated below if the caller still waits
            box[1] = e
        finally:
            with _abandoned_lock:  # set() under the lock: no waiter race
                if state["timed_out"]:
                    _abandoned_running -= 1
                finished.set()

    t = threading.Thread(target=work, name="geomesa-scan", daemon=True)
    t.start()
    if not finished.wait(timeout=timeout_s):
        with _abandoned_lock:
            if not finished.is_set():
                state["timed_out"] = True
                _abandoned_running += 1
        if state["timed_out"]:
            e = QueryTimeout(f"query exceeded timeout of {timeout_s}s")
            # THIS wrapper's worker is still running: nested wrappers
            # (web request → store scan) use the marker so one blown
            # deadline counts ONE abandoned entity, not one per level
            e.worker_abandoned = True
            raise e from None
    if box[1] is not None:
        if isinstance(box[1], QueryTimeout):
            # our worker finished; the timeout happened DEEPER (an inner
            # wrapper or a shed) and was already accounted there
            box[1].worker_abandoned = False
        raise box[1]
    return box[0]


class Watchdog:
    """Tracks in-flight queries: start/stop registration + abandoned count
    (the ``ThreadManagement`` bookkeeping; surfaced in metrics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: dict[int, str] = {}
        self._next = 0
        self.abandoned = 0

    def register(self, description: str) -> int:
        with self._lock:
            self._next += 1
            self._active[self._next] = description
            return self._next

    def complete(self, token: int, timed_out: bool = False) -> None:
        with self._lock:
            self._active.pop(token, None)
            if timed_out:
                self.abandoned += 1

    def active(self) -> list[str]:
        with self._lock:
            return list(self._active.values())
