"""Query timeout watchdog.

Role parity: ``geomesa-index-api/.../index/utils/ThreadManagement.scala``
(SURVEY.md §2.3/§5): the reference registers every scan with a watchdog that
kills it past ``geomesa.query.timeout``. XLA device launches can't be killed
mid-kernel, but a runaway *query* (huge plan, giant residual refine, slow
host reduce) is interruptible at the Python layer: the scan runs on a worker
thread and the caller gives up — and flags the query as abandoned — when the
deadline passes (the worker's result is discarded when it eventually lands).
"""

from __future__ import annotations

import concurrent.futures
import threading

__all__ = ["QueryTimeout", "run_with_timeout", "Watchdog"]


class QueryTimeout(TimeoutError):
    pass


_EXEC = concurrent.futures.ThreadPoolExecutor(
    max_workers=8, thread_name_prefix="geomesa-scan"
)


def run_with_timeout(fn, timeout_s: float | None, *args, **kwargs):
    """Run ``fn`` with a deadline; raises :class:`QueryTimeout` on expiry.

    With ``timeout_s`` None the call is inline (zero overhead) — the common
    case; the worker-thread hop only happens for queries that opted in.
    """
    if timeout_s is None:
        return fn(*args, **kwargs)
    fut = _EXEC.submit(fn, *args, **kwargs)
    try:
        return fut.result(timeout=timeout_s)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        raise QueryTimeout(f"query exceeded timeout of {timeout_s}s") from None


class Watchdog:
    """Tracks in-flight queries: start/stop registration + abandoned count
    (the ``ThreadManagement`` bookkeeping; surfaced in metrics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: dict[int, str] = {}
        self._next = 0
        self.abandoned = 0

    def register(self, description: str) -> int:
        with self._lock:
            self._next += 1
            self._active[self._next] = description
            return self._next

    def complete(self, token: int, timed_out: bool = False) -> None:
        with self._lock:
            self._active.pop(token, None)
            if timed_out:
                self.abandoned += 1

    def active(self) -> list[str]:
        with self._lock:
            return list(self._active.values())
