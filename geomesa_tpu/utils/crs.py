"""Coordinate reference system kit (result reprojection).

Role parity: ``geomesa-index-api/.../index/utils/Reprojection.scala`` (SURVEY.md
§2.3) — the reference reprojects query results client-side through GeoTools'
CRS machinery. Here a small registry of analytic projections covers the
codes geospatial clients actually request (VERDICT r3 item 7):

- ``CRS:84`` / ``EPSG:4326`` — WGS84 geographic lon/lat (internal datum)
- ``EPSG:3857`` — spherical web-mercator (meters)
- ``EPSG:326xx`` / ``EPSG:327xx`` — WGS84 UTM zones 1-60 N/S, via the
  Krüger flattening series (the standard 3rd-order n-series: ~0.1 mm
  round-trip error inside a zone)
- proj-style strings — ``+proj=longlat``, ``+proj=webmerc``,
  ``+proj=utm +zone=NN [+south]``

All transforms are vectorized over numpy arrays and route through lon/lat,
so any supported pair composes. ``reproject_table`` reprojects a
FeatureTable's default geometry column (the export / WFS ``srsName`` path).
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    _Multi,
)

__all__ = [
    "transform_coords", "transform_geometry", "reproject_table",
    "CRS_CODES", "get_crs", "utm_zone_for",
]

_R = 6378137.0  # spherical mercator earth radius (EPSG:3857)
_MAX_LAT = 85.06  # web-mercator clamp

# WGS84 ellipsoid + Krüger series constants (3rd order in n)
_A_WGS84 = 6378137.0
_F_WGS84 = 1.0 / 298.257223563
_K0_UTM = 0.9996
_N = _F_WGS84 / (2.0 - _F_WGS84)
_A_KR = _A_WGS84 / (1.0 + _N) * (1.0 + _N**2 / 4.0 + _N**4 / 64.0)
_ALPHA = (
    _N / 2.0 - 2.0 * _N**2 / 3.0 + 5.0 * _N**3 / 16.0,
    13.0 * _N**2 / 48.0 - 3.0 * _N**3 / 5.0,
    61.0 * _N**3 / 240.0,
)
_BETA = (
    _N / 2.0 - 2.0 * _N**2 / 3.0 + 37.0 * _N**3 / 96.0,
    _N**2 / 48.0 + _N**3 / 15.0,
    17.0 * _N**3 / 480.0,
)
_DELTA = (
    2.0 * _N - 2.0 * _N**2 / 3.0 - 2.0 * _N**3,
    7.0 * _N**2 / 3.0 - 8.0 * _N**3 / 5.0,
    56.0 * _N**3 / 15.0,
)

# legacy constant kept for callers that introspect "the always-supported
# pair"; the registry accepts far more — any code get_crs() resolves
# (4326/CRS:84/3857, UTM EPSG:326xx/327xx, proj strings, urn forms)
CRS_CODES = ("EPSG:4326", "EPSG:3857")


def _to_3857(xs, ys):
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.clip(np.asarray(ys, dtype=np.float64), -_MAX_LAT, _MAX_LAT)
    mx = np.radians(xs) * _R
    my = np.log(np.tan(np.pi / 4.0 + np.radians(ys) / 2.0)) * _R
    return mx, my


def _to_4326(xs, ys):
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    lon = np.degrees(xs / _R)
    lat = np.degrees(2.0 * np.arctan(np.exp(ys / _R)) - np.pi / 2.0)
    return lon, lat


def _tm_forward(lon, lat, lon0: float):
    """WGS84 transverse mercator (Krüger series) → (easting-from-CM·k0·A,
    northing·k0·A), i.e. unscaled (η, ξ) premultiplied."""
    phi = np.radians(np.asarray(lat, np.float64))
    lam = np.radians(np.asarray(lon, np.float64) - lon0)
    s2n = 2.0 * np.sqrt(_N) / (1.0 + _N)
    t = np.sinh(
        np.arctanh(np.sin(phi)) - s2n * np.arctanh(s2n * np.sin(phi))
    )
    xi_p = np.arctan2(t, np.cos(lam))
    eta_p = np.arctanh(np.sin(lam) / np.sqrt(1.0 + t * t))
    xi = xi_p.copy()
    eta = eta_p.copy()
    for j, a in enumerate(_ALPHA, start=1):
        xi += a * np.sin(2 * j * xi_p) * np.cosh(2 * j * eta_p)
        eta += a * np.cos(2 * j * xi_p) * np.sinh(2 * j * eta_p)
    return _K0_UTM * _A_KR * eta, _K0_UTM * _A_KR * xi


def _tm_inverse(E, N, lon0: float):
    xi = np.asarray(N, np.float64) / (_K0_UTM * _A_KR)
    eta = np.asarray(E, np.float64) / (_K0_UTM * _A_KR)
    xi_p = xi.copy()
    eta_p = eta.copy()
    for j, b in enumerate(_BETA, start=1):
        xi_p -= b * np.sin(2 * j * xi) * np.cosh(2 * j * eta)
        eta_p -= b * np.cos(2 * j * xi) * np.sinh(2 * j * eta)
    chi = np.arcsin(np.sin(xi_p) / np.cosh(eta_p))
    phi = chi.copy()
    for j, d in enumerate(_DELTA, start=1):
        phi += d * np.sin(2 * j * chi)
    lam = np.arctan2(np.sinh(eta_p), np.cos(xi_p))
    return lon0 + np.degrees(lam), np.degrees(phi)


class _Crs:
    """One projection: to/from WGS84 lon/lat (vectorized)."""

    def __init__(self, code: str, to_lonlat, from_lonlat):
        self.code = code
        self.to_lonlat = to_lonlat
        self.from_lonlat = from_lonlat


def _lonlat_crs(code: str) -> _Crs:
    ident = lambda xs, ys: (  # noqa: E731
        np.asarray(xs, np.float64), np.asarray(ys, np.float64)
    )
    return _Crs(code, ident, ident)


def _utm_crs(code: str, zone: int, south: bool) -> _Crs:
    if not 1 <= zone <= 60:
        raise ValueError(f"UTM zone must be 1-60: {zone}")
    lon0 = -183.0 + 6.0 * zone  # zone central meridian
    n0 = 10_000_000.0 if south else 0.0

    def from_lonlat(lon, lat):
        e, n = _tm_forward(lon, lat, lon0)
        return e + 500_000.0, n + n0

    def to_lonlat(E, N):
        return _tm_inverse(
            np.asarray(E, np.float64) - 500_000.0,
            np.asarray(N, np.float64) - n0,
            lon0,
        )

    return _Crs(code, to_lonlat, from_lonlat)


_PROJ_UTM = re.compile(r"\+proj=utm\b")
_PROJ_ZONE = re.compile(r"\+zone=(\d+)")


def get_crs(code: str) -> _Crs:
    """Resolve a CRS code (``EPSG:nnnn``, ``CRS:84``, ``urn:ogc:def:crs:``
    forms, or a proj-style ``+proj=...`` string) to its projection."""
    raw = code.strip()
    low = raw.lower()
    if low.startswith("+"):
        if "+proj=longlat" in low or "+proj=latlong" in low:
            return _lonlat_crs(raw)
        if "+proj=webmerc" in low or "+proj=merc" in low:
            return _Crs(raw, _to_4326, _to_3857)
        if _PROJ_UTM.search(low):
            zm = _PROJ_ZONE.search(low)
            if not zm:
                raise ValueError(f"proj utm needs +zone=: {code!r}")
            return _utm_crs(raw, int(zm.group(1)), "+south" in low)
        raise ValueError(f"unsupported proj string {code!r}")
    # urn:ogc:def:crs:EPSG::4326 / urn:ogc:def:crs:OGC:1.3:CRS84
    if low.startswith("urn:"):
        tail = raw.split(":")[-1]
        if tail.upper() in ("CRS84", "84"):
            return _lonlat_crs(raw)
        raw = f"EPSG:{tail}"
        low = raw.lower()
    if low in ("crs:84", "ogc:crs84", "epsg:4326", "wgs84", "4326"):
        return _lonlat_crs(code)
    m = re.match(r"epsg:(\d+)$", low)
    if not m:
        raise ValueError(f"unsupported CRS {code!r}")
    num = int(m.group(1))
    if num == 4326:
        return _lonlat_crs(code)
    if num == 3857:
        return _Crs(code, _to_4326, _to_3857)
    if 32601 <= num <= 32660:
        return _utm_crs(code, num - 32600, south=False)
    if 32701 <= num <= 32760:
        return _utm_crs(code, num - 32700, south=True)
    raise ValueError(f"unsupported CRS {code!r}")


def utm_zone_for(lon: float, lat: float) -> str:
    """EPSG code of the UTM zone containing a lon/lat point."""
    zone = int(np.clip((np.floor((lon + 180.0) / 6.0) % 60) + 1, 1, 60))
    return f"EPSG:{32600 + zone if lat >= 0 else 32700 + zone}"


def transform_coords(xs, ys, source: str, target: str):
    """Transform coordinate arrays between any two supported CRS (routes
    through WGS84 lon/lat, so every registered pair composes)."""
    if source.strip().upper() == target.strip().upper():
        return np.asarray(xs, np.float64), np.asarray(ys, np.float64)
    src = get_crs(source)
    dst = get_crs(target)
    lon, lat = src.to_lonlat(xs, ys)
    return dst.from_lonlat(lon, lat)


def transform_geometry(g: Geometry, source: str, target: str) -> Geometry:
    if isinstance(g, Point):
        x, y = transform_coords([g.x], [g.y], source, target)
        return Point(float(x[0]), float(y[0]))
    if isinstance(g, LineString):
        x, y = transform_coords(g.coords[:, 0], g.coords[:, 1], source, target)
        return LineString(np.stack([x, y], axis=1))
    if isinstance(g, Polygon):
        def ring(r):
            x, y = transform_coords(r[:, 0], r[:, 1], source, target)
            return np.stack([x, y], axis=1)

        return Polygon(ring(g.shell), tuple(ring(h) for h in g.holes))
    if isinstance(g, _Multi):
        return type(g)(tuple(transform_geometry(p, source, target) for p in g.parts))
    raise TypeError(type(g).__name__)


def reproject_table(table, target: str, source: str = "EPSG:4326"):
    """Reproject a FeatureTable's default geometry column (new table)."""
    from geomesa_tpu.schema.columnar import FeatureTable, GeometryColumn

    gf = table.sft.geom_field
    if gf is None or source.upper() == target.upper():
        return table
    col = table.columns[gf]
    if isinstance(col, GeometryColumn) and col.x is not None:
        x, y = transform_coords(col.x, col.y, source, target)
        new_col = GeometryColumn(col.type, None, col.valid, x=x, y=y, bounds=None)
    else:
        geoms = col.geometries()
        out = np.empty(len(geoms), dtype=object)
        bounds = np.empty((len(geoms), 4), dtype=np.float64)
        for i, g in enumerate(geoms):
            if g is None:
                out[i] = None
                bounds[i] = np.nan
            else:
                out[i] = transform_geometry(g, source, target)
                bounds[i] = out[i].bbox
        new_col = GeometryColumn(col.type, out, col.valid, bounds=bounds)
    cols = {**table.columns, gf: new_col}
    return FeatureTable(table.sft, table.fids, cols)
