"""Coordinate reference system transforms (result reprojection).

Role parity: ``geomesa-index-api/.../index/utils/Reprojection.scala`` (SURVEY.md
§2.3) — reproject query results client-side. We implement the pair that covers
the reference's actual usage (GeoServer map output): EPSG:4326 lon/lat ↔
EPSG:3857 spherical web-mercator, vectorized over numpy arrays, plus
whole-table reprojection of the default geometry column.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    _Multi,
)

__all__ = ["transform_coords", "transform_geometry", "reproject_table", "CRS_CODES"]

_R = 6378137.0  # spherical mercator earth radius (EPSG:3857)
_MAX_LAT = 85.06  # web-mercator clamp

CRS_CODES = ("EPSG:4326", "EPSG:3857")


def _to_3857(xs, ys):
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.clip(np.asarray(ys, dtype=np.float64), -_MAX_LAT, _MAX_LAT)
    mx = np.radians(xs) * _R
    my = np.log(np.tan(np.pi / 4.0 + np.radians(ys) / 2.0)) * _R
    return mx, my


def _to_4326(xs, ys):
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    lon = np.degrees(xs / _R)
    lat = np.degrees(2.0 * np.arctan(np.exp(ys / _R)) - np.pi / 2.0)
    return lon, lat


def transform_coords(xs, ys, source: str, target: str):
    """Transform coordinate arrays between supported CRS codes."""
    source, target = source.upper(), target.upper()
    for crs in (source, target):
        if crs not in CRS_CODES:
            raise ValueError(f"unsupported CRS {crs!r}; supported: {CRS_CODES}")
    if source == target:
        return np.asarray(xs, np.float64), np.asarray(ys, np.float64)
    return _to_3857(xs, ys) if target == "EPSG:3857" else _to_4326(xs, ys)


def transform_geometry(g: Geometry, source: str, target: str) -> Geometry:
    if isinstance(g, Point):
        x, y = transform_coords([g.x], [g.y], source, target)
        return Point(float(x[0]), float(y[0]))
    if isinstance(g, LineString):
        x, y = transform_coords(g.coords[:, 0], g.coords[:, 1], source, target)
        return LineString(np.stack([x, y], axis=1))
    if isinstance(g, Polygon):
        def ring(r):
            x, y = transform_coords(r[:, 0], r[:, 1], source, target)
            return np.stack([x, y], axis=1)

        return Polygon(ring(g.shell), tuple(ring(h) for h in g.holes))
    if isinstance(g, _Multi):
        return type(g)(tuple(transform_geometry(p, source, target) for p in g.parts))
    raise TypeError(type(g).__name__)


def reproject_table(table, target: str, source: str = "EPSG:4326"):
    """Reproject a FeatureTable's default geometry column (new table)."""
    from geomesa_tpu.schema.columnar import FeatureTable, GeometryColumn

    gf = table.sft.geom_field
    if gf is None or source.upper() == target.upper():
        return table
    col = table.columns[gf]
    if isinstance(col, GeometryColumn) and col.x is not None:
        x, y = transform_coords(col.x, col.y, source, target)
        new_col = GeometryColumn(col.type, None, col.valid, x=x, y=y, bounds=None)
    else:
        geoms = col.geometries()
        out = np.empty(len(geoms), dtype=object)
        bounds = np.empty((len(geoms), 4), dtype=np.float64)
        for i, g in enumerate(geoms):
            if g is None:
                out[i] = None
                bounds[i] = np.nan
            else:
                out[i] = transform_geometry(g, source, target)
                bounds[i] = out[i].bbox
        new_col = GeometryColumn(col.type, out, col.valid, bounds=bounds)
    cols = {**table.columns, gf: new_col}
    return FeatureTable(table.sft, table.fids, cols)
