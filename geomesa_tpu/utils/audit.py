"""Query audit log (the ``geomesa-utils`` audit + ``QueryEvent`` role).

Role parity: ``geomesa-index-api/.../index/audit/QueryEvent.scala`` and
``geomesa-utils/.../utils/audit/AuditedEvent.scala`` (SURVEY.md §5): per-query
records of user, filter, hints, plan/scan timings, and hit counts, written
through a pluggable ``AuditWriter``.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field


@dataclass
class QueryEvent:
    """One audited query (``QueryEvent.scala:13``)."""

    store_type: str
    type_name: str
    date: int  # epoch millis
    user: str
    filter: str
    hints: str
    plan_time_ms: float
    scan_time_ms: float
    hits: int
    deleted: bool = False
    # obs join keys: the trace/span this query ran under (empty when
    # tracing was off) — audit records join to Perfetto timelines on these
    trace_id: str = ""
    span_id: str = ""

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)


class AuditWriter:
    """Sink for audited events (``AuditWriter`` role)."""

    def write_event(self, event: QueryEvent) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryAuditWriter(AuditWriter):
    """Keeps events in a list; the default for tests and notebooks."""

    def __init__(self):
        self.events: list[QueryEvent] = []

    def write_event(self, event: QueryEvent) -> None:
        self.events.append(event)

    def query_events(self, type_name: str | None = None) -> list[QueryEvent]:
        return [
            e for e in self.events if type_name is None or e.type_name == type_name
        ]


class JsonlAuditWriter(AuditWriter):
    """Appends one JSON line per event (the audit-table role, greppable)."""

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path, "a", encoding="utf-8")

    def write_event(self, event: QueryEvent) -> None:
        self._fh.write(event.to_json() + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def now_millis() -> int:
    return int(time.time() * 1000)
