"""Local in-memory spatial indexes for live feature caches.

Role parity: ``geomesa-utils/.../utils/index/`` (SURVEY.md §2.18) —
``SpatialIndex`` trait with ``BucketIndex`` (fixed grid of buckets) and
``SizeSeparatedBucketIndex`` (tiered grids so large geometries don't smear
across thousands of cells). These back the streaming store's live cache
(``KafkaFeatureCache`` role, §2.10); the TPU columnar path does NOT use them —
they exist for low-latency point lookups on the host over mutating data.
"""

from __future__ import annotations

from typing import Any, Iterator

__all__ = ["SpatialIndex", "BucketIndex", "SizeSeparatedBucketIndex"]


class SpatialIndex:
    """Mutable (envelope, id) → value index (``SpatialIndex`` trait role)."""

    def insert(self, bounds: tuple[float, float, float, float], fid: str, value: Any) -> None:
        raise NotImplementedError

    def remove(self, bounds: tuple[float, float, float, float], fid: str) -> Any:
        raise NotImplementedError

    def get(self, bounds: tuple[float, float, float, float], fid: str) -> Any:
        raise NotImplementedError

    def query(self, bounds: tuple[float, float, float, float]) -> Iterator[Any]:
        raise NotImplementedError

    def values(self) -> Iterator[Any]:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class BucketIndex(SpatialIndex):
    """Fixed lon/lat grid of buckets (``BucketIndex.scala`` role).

    Each entry is stored in every bucket its envelope overlaps; queries union
    the buckets covering the query envelope. Best for point data (one bucket
    per entry).
    """

    def __init__(
        self,
        x_buckets: int = 360,
        y_buckets: int = 180,
        extents: tuple[float, float, float, float] = (-180.0, -90.0, 180.0, 90.0),
    ):
        self.nx = x_buckets
        self.ny = y_buckets
        self.xmin, self.ymin, self.xmax, self.ymax = extents
        self.dx = (self.xmax - self.xmin) / x_buckets
        self.dy = (self.ymax - self.ymin) / y_buckets
        self._buckets: dict[tuple[int, int], dict[str, Any]] = {}
        self._count = 0

    def _cell_range(self, bounds):
        bxmin, bymin, bxmax, bymax = bounds
        i0 = min(max(int((bxmin - self.xmin) / self.dx), 0), self.nx - 1)
        i1 = min(max(int((bxmax - self.xmin) / self.dx), 0), self.nx - 1)
        j0 = min(max(int((bymin - self.ymin) / self.dy), 0), self.ny - 1)
        j1 = min(max(int((bymax - self.ymin) / self.dy), 0), self.ny - 1)
        return i0, i1, j0, j1

    def insert(self, bounds, fid, value) -> None:
        i0, i1, j0, j1 = self._cell_range(bounds)
        fresh = False
        for i in range(i0, i1 + 1):
            for j in range(j0, j1 + 1):
                cell = self._buckets.setdefault((i, j), {})
                if fid not in cell:
                    fresh = True
                cell[fid] = value
        if fresh:
            self._count += 1

    def remove(self, bounds, fid) -> Any:
        i0, i1, j0, j1 = self._cell_range(bounds)
        out = None
        hit = False
        for i in range(i0, i1 + 1):
            for j in range(j0, j1 + 1):
                cell = self._buckets.get((i, j))
                if cell and fid in cell:
                    out = cell.pop(fid)
                    hit = True
                    if not cell:
                        del self._buckets[(i, j)]
        if hit:
            self._count -= 1
        return out

    def get(self, bounds, fid) -> Any:
        i0, i1, j0, j1 = self._cell_range(bounds)
        for i in range(i0, i1 + 1):
            for j in range(j0, j1 + 1):
                cell = self._buckets.get((i, j))
                if cell and fid in cell:
                    return cell[fid]
        return None

    def query(self, bounds) -> Iterator[Any]:
        i0, i1, j0, j1 = self._cell_range(bounds)
        seen: set[str] = set()  # dedupe multi-cell entries by fid
        for i in range(i0, i1 + 1):
            for j in range(j0, j1 + 1):
                cell = self._buckets.get((i, j))
                if not cell:
                    continue
                for fid, v in cell.items():
                    if fid not in seen:
                        seen.add(fid)
                        yield v

    def values(self) -> Iterator[Any]:
        seen: set[str] = set()
        for cell in self._buckets.values():
            for fid, v in cell.items():
                if fid not in seen:
                    seen.add(fid)
                    yield v

    def size(self) -> int:
        return self._count

    def clear(self) -> None:
        self._buckets.clear()
        self._count = 0


class SizeSeparatedBucketIndex(SpatialIndex):
    """Tiered grids by geometry extent (``SizeSeparatedBucketIndex.scala``).

    An envelope goes into the coarsest tier whose cell size covers it, so big
    polygons land in few coarse cells instead of thousands of fine ones.
    """

    # tier cell sizes in degrees, fine → coarse
    TIERS = (1.0, 4.0, 16.0, 64.0, 360.0)

    def __init__(self):
        self._tiers = [
            BucketIndex(max(int(360 / t), 1), max(int(180 / t), 1)) for t in self.TIERS
        ]

    def _tier_for(self, bounds) -> BucketIndex:
        w = bounds[2] - bounds[0]
        h = bounds[3] - bounds[1]
        ext = max(w, h)
        for size, tier in zip(self.TIERS, self._tiers):
            if ext <= size:
                return tier
        return self._tiers[-1]

    def insert(self, bounds, fid, value) -> None:
        self._tier_for(bounds).insert(bounds, fid, value)

    def remove(self, bounds, fid) -> Any:
        return self._tier_for(bounds).remove(bounds, fid)

    def get(self, bounds, fid) -> Any:
        return self._tier_for(bounds).get(bounds, fid)

    def query(self, bounds) -> Iterator[Any]:
        for tier in self._tiers:
            yield from tier.query(bounds)

    def values(self) -> Iterator[Any]:
        for tier in self._tiers:
            yield from tier.values()

    def size(self) -> int:
        return sum(t.size() for t in self._tiers)

    def clear(self) -> None:
        for t in self._tiers:
            t.clear()
