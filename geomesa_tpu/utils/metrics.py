"""Metrics registry with pluggable reporters.

Role parity: ``geomesa-metrics`` (Dropwizard registry + Ganglia/Graphite/
CloudWatch/delimited-file reporters, SURVEY.md §2.19). We keep the registry
shape — named counters, histograms, and timers, snapshot-able and mergeable —
with a pluggable sink SPI wired from declarative config (the
``MetricsConfig.scala`` role): delimited file, Graphite TCP, StatsD UDP,
and CloudWatch Embedded Metric Format (a JSON log line the CloudWatch
agent ships — emission stays a local write in a zero-egress build).
Custom sinks register via :func:`register_sink`.
"""

from __future__ import annotations

import math
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Counter:
    count: int = 0

    def __post_init__(self):
        import threading

        self._lock = threading.Lock()  # threaded servers inc concurrently

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.count += n


@dataclass
class Histogram:
    """Streaming histogram: count/mean/min/max/variance (Welford) plus a
    bounded reservoir (Vitter's algorithm R, ``RESERVOIR_SIZE`` samples) so
    p50/p95/p99 are available at any stream length in O(1) memory."""

    RESERVOIR_SIZE = 1024

    count: int = 0
    mean: float = 0.0
    m2: float = 0.0
    min: float = math.inf
    max: float = -math.inf

    def __post_init__(self):
        import random
        import threading

        # Welford is a multi-field read-modify-write: interleaved updates
        # from parallel requests corrupt mean/m2 without the lock
        self._lock = threading.Lock()
        # construction-time publication: no other thread can hold a
        # reference during __post_init__
        # tpulint: disable-next-line=C001
        self._reservoir: list[float] = []
        # deterministic per-instance stream: quantiles are reproducible in
        # tests without touching the global random state
        self._rng = random.Random(0x9E3779B9)

    def update(self, v: float) -> None:
        with self._lock:
            self.count += 1
            d = v - self.mean
            self.mean += d / self.count
            self.m2 += d * (v - self.mean)
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            # algorithm R: uniform sample over the whole stream
            if len(self._reservoir) < self.RESERVOIR_SIZE:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR_SIZE:
                    self._reservoir[j] = v

    @property
    def stddev(self) -> float:
        return math.sqrt(self.m2 / self.count) if self.count else 0.0

    def quantiles(self, qs=(0.5, 0.95, 0.99)) -> list[float]:
        """Reservoir quantiles (nearest-rank with linear interpolation).
        Only the COPY happens under the lock; the O(n log n) sort runs
        outside it so a metrics scrape never stalls hot-path update()."""
        with self._lock:
            sample = list(self._reservoir)
        sample.sort()
        if not sample:
            return [0.0] * len(qs)
        out = []
        top = len(sample) - 1
        for q in qs:
            pos = q * top
            lo = int(pos)
            hi = min(lo + 1, top)
            frac = pos - lo
            out.append(sample[lo] * (1.0 - frac) + sample[hi] * frac)
        return out


@dataclass
class Gauge:
    """Point-in-time value; ``fn``-backed gauges sample at snapshot time.

    Writes are locked like Counter/Histogram updates: ``set`` from parallel
    request threads and ``value`` reads from a background reporter must
    never observe a torn/stale mix (C001 lock discipline — covered by
    concurrent set/sample assertions in tests/test_obs.py)."""

    _value: float = 0.0
    fn: object = None  # optional zero-arg callable

    def __post_init__(self):
        import threading

        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, delta: float) -> float:
        """Atomic read-modify-write increment (a lock-free ``set(value +
        d)`` from two threads loses one update; this cannot)."""
        with self._lock:
            self._value += delta
            return self._value

    @property
    def value(self) -> float:
        if self.fn is not None:
            return float(self.fn())  # sampled outside the lock: fn owns its state
        with self._lock:
            return self._value


@dataclass
class Timer:
    hist: Histogram = field(default_factory=Histogram)

    @contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.hist.update((time.perf_counter() - t0) * 1000.0)  # ms


class MetricsRegistry:
    def __init__(self):
        self.counters: dict[str, Counter] = {}
        self.histograms: dict[str, Histogram] = {}
        self.timers: dict[str, Timer] = {}
        self.gauges: dict[str, Gauge] = {}

    # accessors check membership before constructing the default: hot
    # telemetry paths (obs.jaxmon per-dispatch counters) resolve by name
    # every call, and an eager `setdefault(name, Counter())` would build
    # and discard a metric + lock per hit. On a racing miss two defaults
    # may construct; setdefault keeps exactly one (the returned winner).
    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        return c if c is not None else self.counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        return g if g is not None else self.gauges.setdefault(name, Gauge())

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        return (
            h if h is not None
            else self.histograms.setdefault(name, Histogram())
        )

    def timer(self, name: str) -> Timer:
        t = self.timers.get(name)
        return t if t is not None else self.timers.setdefault(name, Timer())

    # -- reporters ----------------------------------------------------------
    def snapshot(self) -> dict:
        # iterate over COPIES: a background reporter (PeriodicReporter)
        # snapshots while application threads register new metrics, and a
        # mid-iteration dict insert would kill that interval's report
        out: dict[str, dict] = {}
        for k, c in list(self.counters.items()):
            out[k] = {"type": "counter", "count": c.count}
        for k, g in list(self.gauges.items()):
            out[k] = {"type": "gauge", "value": g.value}
        for k, h in list(self.histograms.items()):
            p50, p95, p99 = h.quantiles()
            out[k] = {
                "type": "histogram",
                "count": h.count,
                "mean": h.mean,
                "min": h.min if h.count else 0.0,
                "max": h.max if h.count else 0.0,
                "stddev": h.stddev,
                "p50": p50,
                "p95": p95,
                "p99": p99,
            }
        for k, t in list(self.timers.items()):
            h = t.hist
            p50, p95, p99 = h.quantiles()
            out[k] = {
                "type": "timer",
                "count": h.count,
                "mean_ms": h.mean,
                "min_ms": h.min if h.count else 0.0,
                "max_ms": h.max if h.count else 0.0,
                "p50_ms": p50,
                "p95_ms": p95,
                "p99_ms": p99,
            }
        return out

    def report_prometheus(self, prefix: str = "geomesa") -> str:
        """Prometheus text exposition of this registry (counters as
        ``_total``, histograms/timers as summaries with p50/p95/p99
        quantile labels) — the exposition behind
        ``GET /api/metrics?format=prometheus``."""
        from geomesa_tpu.obs.export import prometheus_text

        return prometheus_text(self, prefix=prefix)

    def report_graphite(self, prefix: str = "geomesa") -> str:
        """Graphite plaintext-protocol dump (``GraphiteReporter`` role)."""
        ts = int(time.time())
        lines = []
        for name, vals in self.snapshot().items():
            for k, v in vals.items():
                if k == "type":
                    continue
                lines.append(f"{prefix}.{name}.{k} {v} {ts}")
        return "\n".join(lines)

    def report_delimited(self, path: str, delimiter: str = ",") -> None:
        """Append a snapshot as delimited rows (``DelimitedFileReporter``)."""
        ts = int(time.time())
        with open(path, "a", encoding="utf-8") as fh:
            for name, vals in self.snapshot().items():
                typ = vals.pop("type")
                for k, v in vals.items():
                    fh.write(delimiter.join([str(ts), typ, name, k, str(v)]) + "\n")

    # -- external network sinks (geomesa-metrics reporter-config role:
    # MetricsConfig.scala wires Ganglia/Graphite/CloudWatch reporters from
    # config; here the two wire protocols those sinks actually speak) ------
    def push_graphite(self, host: str, port: int, prefix: str = "geomesa",
                      timeout_s: float = 5.0) -> int:
        """Push one snapshot to a Carbon/Graphite endpoint over TCP
        (plaintext protocol — the ``GraphiteReporter`` network role).
        Returns bytes sent; raises OSError on connection failure (callers
        like :class:`PeriodicReporter` decide the retry policy)."""
        import socket

        payload = (self.report_graphite(prefix) + "\n").encode()
        with socket.create_connection((host, port), timeout=timeout_s) as s:
            s.sendall(payload)
        return len(payload)

    def push_statsd(self, host: str, port: int, prefix: str = "geomesa") -> int:
        """Snapshot values as StatsD ``|g`` (gauge) UDP datagrams — the
        ingestion path CloudWatch agent / gmond / Telegraf all accept.

        Everything ships as a gauge of the CURRENT value: this registry's
        counters are cumulative totals, and re-sending a total as a StatsD
        ``|c`` increment every tick would make aggregators overcount a flat
        counter forever (``|c`` is a per-flush-window delta). Fire-and-
        forget (UDP); returns datagrams sent."""
        import socket

        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        n = 0
        try:
            for name, vals in self.snapshot().items():
                for k, v in vals.items():
                    if k == "type":
                        continue
                    sock.sendto(
                        f"{prefix}.{name}.{k}:{v}|g".encode(), (host, port)
                    )
                    n += 1
        finally:
            sock.close()
        return n


class PeriodicReporter:
    """Background scheduled reporter (Dropwizard ``ScheduledReporter`` role).

    Every ``interval_s`` the daemon thread appends a snapshot via
    ``report_delimited(path)`` (or calls ``fn(registry)`` for a custom sink —
    the Ganglia/CloudWatch plug point). ``stop()`` wakes the thread and
    flushes one final report so short-lived processes never lose metrics.
    """

    def __init__(self, registry: MetricsRegistry, interval_s: float = 60.0,
                 path: str | None = None, fn=None, delimiter: str = ","):
        if (path is None) == (fn is None):
            raise ValueError("pass exactly one of path= or fn=")
        self.registry = registry
        self.interval_s = interval_s
        self._emit = fn if fn is not None else (
            lambda reg: reg.report_delimited(path, delimiter)
        )
        import threading

        self._stop = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._emit(self.registry)
            except Exception:  # noqa: BLE001 — a sink error must not kill the loop
                pass

    def start(self) -> "PeriodicReporter":
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stopped:
            return  # idempotent: explicit stop + __exit__ must not double-flush
        self._stopped = True
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            return  # a wedged sink still owns _emit: don't run it concurrently
        try:
            self._emit(self.registry)  # final flush
        except Exception:  # noqa: BLE001 — same tolerance as the loop
            pass

    def __enter__(self) -> "PeriodicReporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @classmethod
    def graphite(cls, registry: MetricsRegistry, host: str, port: int,
                 interval_s: float = 60.0, prefix: str = "geomesa"):
        """Scheduled Graphite network reporter (``MetricsConfig`` wiring
        role): pushes every ``interval_s`` over TCP; connection failures
        are tolerated per-tick (the loop's sink-error policy)."""
        return cls(
            registry, interval_s=interval_s,
            fn=lambda reg: reg.push_graphite(host, port, prefix=prefix),
        )

    @classmethod
    def statsd(cls, registry: MetricsRegistry, host: str, port: int,
               interval_s: float = 60.0, prefix: str = "geomesa"):
        """Scheduled StatsD (UDP) reporter — the CloudWatch-agent/gmond
        ingestion path."""
        return cls(
            registry, interval_s=interval_s,
            fn=lambda reg: reg.push_statsd(host, port, prefix=prefix),
        )


# ---------------------------------------------------------------------------
# Pluggable sink SPI (the MetricsConfig.scala role: reporters wired from
# declarative config — geomesa-metrics/.../config/MetricsConfig.scala)
# ---------------------------------------------------------------------------

def emf_snapshot(registry: MetricsRegistry, namespace: str = "geomesa",
                 dimensions: dict | None = None) -> dict:
    """One CloudWatch Embedded-Metric-Format record for the registry.

    EMF is the agentless CloudWatch ingestion path (a JSON line on stdout /
    a log file that the CloudWatch agent or Firelens ships) — the right
    cloud-sink shape for a zero-egress build: emission is a local write,
    shipping is the platform's job. Counter/gauge values become metrics;
    histograms/timers contribute their mean and count."""
    dims = dict(dimensions or {})
    metrics = []
    values: dict[str, float] = {}
    for name, vals in registry.snapshot().items():
        typ = vals.pop("type")
        if typ == "counter":
            metrics.append({"Name": name, "Unit": "Count"})
            values[name] = float(vals["count"])
        elif typ == "gauge":
            metrics.append({"Name": name, "Unit": "None"})
            values[name] = float(vals["value"])
        else:  # histogram / timer: mean + count + quantiles
            timer = typ == "timer"
            mean_key = "mean_ms" if timer else "mean"
            unit = "Milliseconds" if timer else "None"
            metrics.append({"Name": f"{name}.mean", "Unit": unit})
            values[f"{name}.mean"] = float(vals[mean_key])
            metrics.append({"Name": f"{name}.count", "Unit": "Count"})
            values[f"{name}.count"] = float(vals["count"])
            for q in ("p50", "p95", "p99"):
                key = f"{q}_ms" if timer else q
                if key in vals:
                    metrics.append({"Name": f"{name}.{q}", "Unit": unit})
                    values[f"{name}.{q}"] = float(vals[key])
    return {
        "_aws": {
            "Timestamp": int(time.time() * 1000),
            "CloudWatchMetrics": [{
                "Namespace": namespace,
                "Dimensions": [list(dims.keys())] if dims else [[]],
                "Metrics": metrics,
            }],
        },
        **dims,
        **values,
    }


def push_cloudwatch_emf(registry: MetricsRegistry, path: str,
                        namespace: str = "geomesa",
                        dimensions: dict | None = None) -> None:
    """Append one EMF JSON line to ``path`` (the CloudWatch log stream)."""
    import json as _json

    rec = emf_snapshot(registry, namespace=namespace, dimensions=dimensions)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(_json.dumps(rec) + "\n")


def _sink_delimited(registry, cfg):
    path = cfg["path"]
    delim = cfg.get("delimiter", ",")
    return lambda reg: reg.report_delimited(path, delim)


def _sink_graphite(registry, cfg):
    return lambda reg: reg.push_graphite(
        cfg["host"], int(cfg["port"]), prefix=cfg.get("prefix", "geomesa")
    )


def _sink_statsd(registry, cfg):
    return lambda reg: reg.push_statsd(
        cfg["host"], int(cfg["port"]), prefix=cfg.get("prefix", "geomesa")
    )


def _sink_cloudwatch_emf(registry, cfg):
    path = cfg["path"]
    ns = cfg.get("namespace", "geomesa")
    dims = cfg.get("dimensions")
    return lambda reg: push_cloudwatch_emf(
        reg, path, namespace=ns, dimensions=dims
    )


# sink type → factory(registry, cfg) → emit fn; extend via register_sink
SINK_FACTORIES = {
    "delimited": _sink_delimited,
    "graphite": _sink_graphite,
    "statsd": _sink_statsd,
    "cloudwatch-emf": _sink_cloudwatch_emf,
}


def register_sink(name: str, factory) -> None:
    """Register a custom sink type: ``factory(registry, cfg) -> emit_fn``
    (the SPI extension point — AccumuloReporter-style store sinks plug in
    here)."""
    SINK_FACTORIES[name] = factory


def reporter_from_config(registry: MetricsRegistry, cfg: dict) -> PeriodicReporter:
    """Build a scheduled reporter from one declarative sink config:
    ``{"type": ..., "interval_s": ..., <sink params>}``."""
    typ = cfg.get("type")
    factory = SINK_FACTORIES.get(typ)
    if factory is None:
        raise ValueError(
            f"unknown metrics sink {typ!r}; known: {sorted(SINK_FACTORIES)}"
        )
    emit = factory(registry, cfg)
    return PeriodicReporter(
        registry, interval_s=float(cfg.get("interval_s", 60.0)), fn=emit
    )


def reporters_from_config(registry: MetricsRegistry, configs) -> list:
    """The MetricsConfig entry point: a list of sink configs → started
    reporters (callers own stop())."""
    out = [reporter_from_config(registry, c) for c in configs]
    for r in out:
        r.start()
    return out
