"""Record-level security (the ``geomesa-security`` role, SURVEY.md §2.19)."""

from geomesa_tpu.security.visibility import (  # noqa: F401
    VisibilityExpression,
    evaluate_column,
    parse_visibility,
)
