"""Visibility expression parser/evaluator (record-level security).

Role parity: ``geomesa-security/.../security/VisibilityEvaluator.scala:50``
(SURVEY.md §2.19) — Accumulo-style visibility expressions like ``admin``,
``user|admin``, ``alpha&(beta|gamma)``, evaluated against a user's
authorization set. Per the reference, ``&`` binds tighter than ``|``
(``user|admin&test`` == ``user|(admin&test)``). Parse results are cached;
column evaluation vectorizes over the distinct visibility strings in a column
(typically a handful across millions of rows).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

__all__ = [
    "VisibilityExpression",
    "parse_visibility",
    "evaluate_column",
    "VisibilityParseError",
]

# same alphabet as Accumulo Authorizations (VisibilityEvaluator.scala:29-36)
_AUTH_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-:./"
)


class VisibilityParseError(ValueError):
    pass


class VisibilityExpression:
    def evaluate(self, auths: frozenset[str]) -> bool:
        raise NotImplementedError

    def expression(self) -> str:
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.expression()


class _None(VisibilityExpression):
    """Empty visibility: visible to everyone."""

    def evaluate(self, auths):
        return True

    def expression(self):
        return ""


VisibilityNone = _None()


@dataclass(frozen=True)
class _Value(VisibilityExpression):
    auth: str

    def evaluate(self, auths):
        return self.auth in auths

    def expression(self):
        return self.auth


@dataclass(frozen=True)
class _And(VisibilityExpression):
    children: tuple[VisibilityExpression, ...]

    def evaluate(self, auths):
        return all(c.evaluate(auths) for c in self.children)

    def expression(self):
        return "&".join(
            f"({c.expression()})" if isinstance(c, _Or) else c.expression()
            for c in self.children
        )


@dataclass(frozen=True)
class _Or(VisibilityExpression):
    children: tuple[VisibilityExpression, ...]

    def evaluate(self, auths):
        return any(c.evaluate(auths) for c in self.children)

    def expression(self):
        return "|".join(
            f"({c.expression()})" if isinstance(c, _Or) else c.expression()
            for c in self.children
        )


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def error(self, msg: str):
        raise VisibilityParseError(f"{msg} at position {self.i} in {self.s!r}")

    def peek(self) -> str | None:
        return self.s[self.i] if self.i < len(self.s) else None

    def parse(self) -> VisibilityExpression:
        e = self.or_expr()
        if self.i != len(self.s):
            self.error("unexpected trailing input")
        return e

    def or_expr(self) -> VisibilityExpression:
        terms = [self.and_expr()]
        while self.peek() == "|":
            self.i += 1
            terms.append(self.and_expr())
        return terms[0] if len(terms) == 1 else _Or(tuple(terms))

    def and_expr(self) -> VisibilityExpression:
        factors = [self.factor()]
        while self.peek() == "&":
            self.i += 1
            factors.append(self.factor())
        return factors[0] if len(factors) == 1 else _And(tuple(factors))

    def factor(self) -> VisibilityExpression:
        c = self.peek()
        if c == "(":
            self.i += 1
            e = self.or_expr()
            if self.peek() != ")":
                self.error("expected ')'")
            self.i += 1
            return e
        if c == '"':
            self.i += 1
            out = []
            while (c := self.peek()) not in ('"', None):
                if c == "\\":
                    self.i += 1
                    c = self.peek()
                    if c is None:
                        self.error("dangling escape")
                out.append(c)
                self.i += 1
            if self.peek() != '"':
                self.error("unterminated quote")
            self.i += 1
            if not out:
                self.error("empty quoted auth")
            return _Value("".join(out))
        start = self.i
        while (c := self.peek()) is not None and c in _AUTH_CHARS:
            self.i += 1
        if self.i == start:
            self.error("expected auth token")
        return _Value(self.s[start : self.i])


@lru_cache(maxsize=4096)
def parse_visibility(expr: str | None) -> VisibilityExpression:
    """Parse a visibility string; cached (``VisibilityEvaluator.parse``)."""
    if not expr:
        return VisibilityNone
    return _Parser(expr).parse()


def evaluate_column(visibilities, auths) -> np.ndarray:
    """Visibility mask for a column of expression strings vs an auth set.

    Vectorizes over distinct expressions (parse+evaluate once each, broadcast
    via inverse indices) — the analog of the reference's per-scan filter with
    its expression cache.
    """
    vis = np.asarray(visibilities, dtype=object)
    aset = frozenset(auths)
    flat = np.array(["" if v is None else str(v) for v in vis], dtype=object)
    uniq, inv = np.unique(flat, return_inverse=True)
    allowed = np.array([parse_visibility(u).evaluate(aset) for u in uniq], dtype=bool)
    return allowed[inv]


def apply_visibility(sft, table, vis_field: str, auths):
    """Record- OR attribute-level visibility over a feature table.

    A visibility cell without commas is one expression for the whole record
    (rows failing it are dropped). A comma-separated cell holds one
    expression PER ATTRIBUTE in schema order (the reference's
    ``SecurityUtils.FEATURE_VISIBILITY`` convention, enforced server-side by
    ``KryoVisibilityRowEncoder.scala:1``): attributes the caller's auths
    can't satisfy are redacted to null, and rows with NO visible attribute
    are dropped. Returns (table, kept_row_positions).
    """
    vis = table.columns[vis_field].values
    aset = frozenset(auths)
    flat = np.array(["" if v is None else str(v) for v in vis], dtype=object)
    uniq, inv = np.unique(flat, return_inverse=True)
    names = [a.name for a in sft.attributes]
    n_attr = len(names)

    # per distinct expression: visibility bool per attribute (record-level
    # expressions broadcast one verdict across every attribute)
    per_attr = np.empty((len(uniq), n_attr), dtype=bool)
    for u, expr in enumerate(uniq):
        if "," in expr:
            parts = [p.strip() for p in expr.split(",")]
            parts += [""] * (n_attr - len(parts))
            per_attr[u] = [
                parse_visibility(p).evaluate(aset) for p in parts[:n_attr]
            ]
        else:
            per_attr[u] = parse_visibility(expr).evaluate(aset)

    attr_vis = per_attr[inv]  # (n_rows, n_attr)
    keep = np.nonzero(attr_vis.any(axis=1))[0]
    table = table.take(keep)
    attr_vis = attr_vis[keep]

    # redact: merge per-attribute visibility into each column's validity
    from dataclasses import replace as _replace

    new_cols = {}
    for j, name in enumerate(names):
        col = table.columns[name]
        visible = attr_vis[:, j]
        if visible.all():
            new_cols[name] = col
            continue
        valid = visible if col.valid is None else (col.valid & visible)
        new_cols[name] = _replace(col, valid=valid)
    from geomesa_tpu.schema.columnar import FeatureTable

    return FeatureTable(table.sft, table.fids, new_cols), keep
