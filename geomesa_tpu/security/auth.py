"""Authorizations-provider SPI (the ``geomesa-security`` provider role).

Reference: ``geomesa-security/.../AuthorizationsProvider`` (SURVEY.md §2.19)
— a pluggable component that derives the calling user's visibility
authorizations from request context, so the serving layer (REST here;
GeoServer there) never trusts the client to name its own auths. Providers
return ``None`` for "unrestricted" (an admin/trusted context) or a list of
authorization tokens checked against feature visibility expressions
(:mod:`geomesa_tpu.security.visibility`).
"""

from __future__ import annotations


class AuthorizationsProvider:
    """SPI: request context → authorizations (None = unrestricted)."""

    def auths(self, context: dict) -> list[str] | None:
        raise NotImplementedError


class StaticAuthorizationsProvider(AuthorizationsProvider):
    """Fixed authorizations for every request (test / single-tenant use)."""

    def __init__(self, auths: list[str] | None):
        self._auths = None if auths is None else list(auths)

    def auths(self, context: dict) -> list[str] | None:
        return None if self._auths is None else list(self._auths)


class HeaderAuthorizationsProvider(AuthorizationsProvider):
    """Authorizations from a trusted reverse-proxy header (comma-separated).

    The proxy authenticates the user and asserts their auths in ``header``
    (default ``X-Geomesa-Auths``); a missing header means NO authorizations
    (only unlabeled features are visible), never unrestricted — absence of
    proof must fail closed.

    DEPLOYMENT REQUIREMENT: WSGI collapses ``-`` and ``_`` in header names
    to one environ key, so a client-sent ``X_Geomesa_Auths`` aliases the
    trusted header. The fronting proxy MUST drop underscore-spelled header
    variants (nginx does by default via ``ignore_invalid_headers``; Apache
    needs ``RequestHeader unset``) in addition to overriding the canonical
    spelling — otherwise clients can append their own auths."""

    def __init__(self, header: str = "X-Geomesa-Auths"):
        # WSGI spells header "X-Foo-Bar" as environ key "HTTP_X_FOO_BAR"
        self.header = header
        self._environ_key = "HTTP_" + header.upper().replace("-", "_")

    def auths(self, context: dict) -> list[str] | None:
        raw = context.get(self._environ_key, "")
        return [a.strip() for a in raw.split(",") if a.strip()]
