"""Schema-registry Avro stream messages (the Confluent interop role).

Role parity: ``geomesa-kafka-confluent`` (SURVEY.md §2.10) — stream change
messages whose feature payloads are Avro records tagged with a registry
schema id, so independently-evolving producers and consumers interoperate:
the consumer resolves the producer's WRITER schema (looked up by id) onto
its own reader schema using the evolution rules in
:mod:`geomesa_tpu.io.avro` (field reorder / add-with-null / drop).

Wire format (Confluent-compatible framing for the payload):

    [0x00 magic][4B big-endian schema id][1B kind][8B ts]
    put:    [avro feature record (writer schema; carries __fid__)]
    delete: [fid]
    clear:  (nothing further)

The in-process :class:`SchemaRegistry` plays the registry service: ids are
stable per schema JSON, shared by every serializer bound to it.
"""

from __future__ import annotations

import io
import json
import struct
import threading

from geomesa_tpu.geometry.wkb import from_wkb, to_wkb
from geomesa_tpu.io.avro import _decode_record, _decode_resolved, _encode_record, avro_schema
from geomesa_tpu.schema.sft import FeatureType
from geomesa_tpu.stream.messages import (
    _K_CLEAR,
    _K_DELETE,
    _K_PUT,
    Clear,
    Delete,
    Put,
    _Cursor,
    _pack_str,
)

__all__ = ["SchemaRegistry", "HttpSchemaRegistry", "AvroGeoMessageSerializer"]

_MAGIC = 0


class SchemaRegistry:
    """In-process schema registry: canonical-JSON schema ↔ int id.

    The service role of Confluent's registry — ``register`` is idempotent
    (same schema → same id), ids are dense from 1.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_id: dict[int, dict] = {}
        self._ids: dict[str, int] = {}
        self._subjects: dict[str, list[int]] = {}

    def register(self, subject: str, schema: dict) -> int:
        key = json.dumps(schema, sort_keys=True)
        with self._lock:
            sid = self._ids.get(key)
            if sid is None:
                sid = len(self._by_id) + 1
                self._ids[key] = sid
                self._by_id[sid] = schema
            versions = self._subjects.setdefault(subject, [])
            if sid not in versions:
                versions.append(sid)
            return sid

    def schema_by_id(self, sid: int) -> dict:
        schema = self._by_id.get(sid)
        if schema is None:
            raise KeyError(f"unknown schema id {sid}")
        return schema

    def versions(self, subject: str) -> list[int]:
        """Registered schema ids for a subject, oldest first."""
        return list(self._subjects.get(subject, []))


class HttpSchemaRegistry:
    """Client for a LIVE schema-registry service over the Confluent REST
    protocol (``POST /subjects/<s>/versions``, ``GET /schemas/ids/<id>``) —
    the ``geomesa-kafka-confluent`` client half
    (``/root/reference/geomesa-kafka/geomesa-kafka-confluent/``). Same
    surface as :class:`SchemaRegistry`, so
    :class:`AvroGeoMessageSerializer` binds to either; works against a
    real Confluent registry or :mod:`geomesa_tpu.web.app` serving one
    (``GeoMesaApp(..., schema_registry=...)``).

    Writer schemas are immutable once assigned an id, so ``schema_by_id``
    responses cache forever; ``register`` caches per canonical schema JSON
    (the service is idempotent on re-registration) — which also makes
    EVERY call here safe to retry: requests run through the shared
    resilience choke point (docs/resilience.md) with this client's
    ``retry`` policy and per-endpoint ``breaker``."""

    def __init__(self, base_url: str, timeout_s: float = 10.0,
                 retry=None, breaker=None):
        from geomesa_tpu.resilience.policy import CircuitBreaker, RetryPolicy

        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = (
            breaker if breaker is not None
            else CircuitBreaker(endpoint=self.base_url)
        )
        self._lock = threading.Lock()
        self._by_id: dict[int, dict] = {}
        self._ids: dict[tuple[str, str], int] = {}

    def _request(self, method: str, path: str, body: dict | None = None):
        from geomesa_tpu.resilience import http as rhttp

        # map_errors=False: schema_by_id translates the raw 404 itself;
        # idempotent=True: registration is idempotent server-side, so
        # even the POST replays safely on 5xx/connect errors
        raw = rhttp.request(
            method, self.base_url + path, body=body,
            headers={"Content-Type": "application/vnd.schemaregistry.v1+json"},
            timeout_s=self.timeout_s, retry=self.retry,
            breaker=self.breaker, idempotent=True, map_errors=False,
        )
        return json.loads(raw)

    def register(self, subject: str, schema: dict) -> int:
        import urllib.parse

        # cache key includes the SUBJECT: the same schema registered under
        # a second subject must still POST, or that subject is never
        # registered server-side (version listing would 404)
        key = (subject, json.dumps(schema, sort_keys=True))
        with self._lock:
            sid = self._ids.get(key)
        if sid is not None:
            return sid
        out = self._request(
            "POST",
            f"/subjects/{urllib.parse.quote(subject, safe='')}/versions",
            {"schema": json.dumps(schema)},
        )
        sid = int(out["id"])
        with self._lock:
            self._ids[key] = sid
            self._by_id[sid] = schema
        return sid

    def schema_by_id(self, sid: int) -> dict:
        with self._lock:
            cached = self._by_id.get(sid)
        if cached is not None:
            return cached
        import urllib.error

        try:
            out = self._request("GET", f"/schemas/ids/{int(sid)}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(f"unknown schema id {sid}") from None
            raise
        schema = json.loads(out["schema"])
        with self._lock:
            self._by_id[sid] = schema
        return schema

    def versions(self, subject: str) -> list[int]:
        import urllib.parse

        return [int(v) for v in self._request(
            "GET",
            f"/subjects/{urllib.parse.quote(subject, safe='')}/versions",
        )]


class AvroGeoMessageSerializer:
    """Schema-registry-backed message codec for one feature type.

    Drop-in for :class:`~geomesa_tpu.stream.messages.GeoMessageSerializer`
    (same serialize/deserialize surface), but puts ride as Avro records
    resolved across schema versions on read.
    """

    def __init__(self, sft: FeatureType, registry: SchemaRegistry):
        self.sft = sft
        self.registry = registry
        self.schema = avro_schema(sft)
        self.schema_id = registry.register(sft.name, self.schema)
        self._geom_fields = {
            a.name for a in sft.attributes if a.type.is_geometry
        }

    # -- write ----------------------------------------------------------------
    def serialize(self, msg: Put | Delete | Clear) -> bytes:
        head = struct.pack(">BI", _MAGIC, self.schema_id)
        if isinstance(msg, Clear):
            return head + struct.pack("<Bq", _K_CLEAR, msg.ts)
        if isinstance(msg, Delete):
            return head + struct.pack("<Bq", _K_DELETE, msg.ts) + _pack_str(msg.fid)
        body = io.BytesIO()
        rec = dict(msg.record)
        rec["__fid__"] = msg.fid  # fid rides inside the record (no prefix)
        for g in self._geom_fields:
            if rec.get(g) is not None:
                rec[g] = to_wkb(rec[g])
        _encode_record(body, self.schema, rec)
        return head + struct.pack("<Bq", _K_PUT, msg.ts) + body.getvalue()

    # -- read -----------------------------------------------------------------
    def deserialize(self, data: bytes) -> Put | Delete | Clear:
        magic, sid = struct.unpack_from(">BI", data, 0)
        if magic != _MAGIC:
            raise ValueError(f"bad magic byte {magic}")
        c = _Cursor(data)
        c.pos = 5
        kind, ts = c.unpack("<Bq")
        if kind == _K_CLEAR:
            return Clear(ts)
        if kind == _K_DELETE:
            return Delete(c.unpack_str(), ts)
        writer = (
            self.schema
            if sid == self.schema_id
            else self.registry.schema_by_id(sid)
        )
        buf = io.BytesIO(data[c.pos :])
        if writer is self.schema:
            rec = _decode_record(buf, self.schema)
        else:  # cross-version producer: resolve writer → our reader schema
            rec = _decode_resolved(buf, writer, self.schema)
        fid = str(rec.pop("__fid__", ""))
        for g in self._geom_fields:
            if isinstance(rec.get(g), (bytes, bytearray)):
                rec[g] = from_wkb(rec[g])
        return Put(fid, rec, ts)
