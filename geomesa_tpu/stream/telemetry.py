"""Process-wide streaming telemetry: per-topic lag / poll / scan gauges.

The streaming tier runs on background threads (journal tailer, consumer
groups, the device stream scanner) whose health is invisible to the
request path — this module is the one place they all report into, and
the web layer renders it on ``/api/metrics`` (JSON ``stream`` section)
and ``/api/metrics?format=prometheus``:

- ``geomesa_stream_lag{topic}`` — unconsumed bus messages behind the
  head (consumer groups / the journal tailer).
- ``geomesa_stream_scan_lag{topic}`` — rows the device scanner has
  accepted but not yet scanned. A SEPARATE gauge from ``lag``: the
  consumer and the scanner poll the same topic string, and one shared
  key would let an idle consumer's 0 overwrite a saturated scanner's
  backlog (the backpressure signal; docs/streaming.md § Backpressure).
- ``geomesa_stream_polls_total{topic,loop}`` /
  ``geomesa_stream_poll_rows_total{topic,loop}`` — poll-rate counters,
  labeled per polling LOOP (``consumer`` / ``tailer``): both loops poll
  the same topic string, and one shared key would double-count every
  record and make the rate read 2× the real throughput.
- ``geomesa_stream_poll_backoff_seconds{topic,loop}`` — the CURRENT idle
  backoff (0 under traffic; grows toward the cap while idle — the
  adaptive-backoff health check). Per loop for the same reason: a busy
  consumer must not zero the gauge of an idle tailer (last-writer-wins
  flapping would defeat the runbook's "at the cap means quiet" rule).
- ``geomesa_stream_callback_errors_total{topic}`` — subscriber callbacks
  that raised (mirrors the ``stream.callback_errors`` registry counter).
- ``geomesa_stream_scan_errors_total{topic}`` — chunks dropped because
  staging/scan/delivery raised (the scan thread stays alive; mirrors
  ``stream.scan_errors``).
- ``geomesa_stream_scan_rows_total`` / ``_scan_chunks_total`` /
  ``_transfer_wait_seconds_total`` / ``_h2d_bytes_total`` /
  ``_deliveries_total`` — the device scanner's pipeline accounting.

One leaf lock guards the table; nothing is called while it is held
(docs/concurrency.md).
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "set_lag", "set_scan_lag", "note_poll", "note_callback_error",
    "note_scan", "note_scan_error", "note_deliveries", "note_watermark",
    "report", "prometheus_lines", "prometheus_text", "reset",
]

_lock = threading.Lock()
_topics: dict[str, dict] = {}
# per-topic subscription-watermark cardinality bound: a churny topic
# must not grow the exposition without limit (oldest sid evicted)
_MAX_WATERMARK_SUBS = 64

_ZERO = {
    "lag": 0, "scan_lag": 0, "callback_errors": 0, "scan_chunks": 0,
    "scan_rows": 0, "transfer_wait_s": 0.0, "h2d_bytes": 0,
    "deliveries": 0, "scan_errors": 0,
}
_POLL_ZERO = {"polls": 0, "poll_rows": 0, "poll_backoff_s": 0.0}


def _t(topic: str) -> dict:
    st = _topics.get(topic)
    if st is None:
        st = dict(_ZERO)
        st["poll_loops"] = {}
        st["watermarks"] = {}
        _topics[topic] = st
    return st


def _loop(st: dict, loop: str) -> dict:
    ls = st["poll_loops"].get(loop)
    if ls is None:
        ls = dict(_POLL_ZERO)
        st["poll_loops"][loop] = ls
    return ls


def set_lag(topic: str, lag: int) -> None:
    """Bus-side lag: unconsumed messages (consumer groups, tailer)."""
    with _lock:
        _t(topic)["lag"] = int(lag)


def set_scan_lag(topic: str, lag: int) -> None:
    """Scanner-side lag: rows accepted but not yet scanned."""
    with _lock:
        _t(topic)["scan_lag"] = int(lag)


def note_poll(topic: str, drained: int, backoff_s: float = 0.0,
              loop: str = "consumer") -> None:
    """One poll round of one polling ``loop`` (``consumer``/``tailer``):
    ``drained`` rows dispatched, ``backoff_s`` the idle delay chosen for
    the NEXT round (0 under traffic)."""
    with _lock:
        ls = _loop(_t(topic), loop)
        ls["polls"] += 1
        ls["poll_rows"] += int(drained)
        ls["poll_backoff_s"] = float(backoff_s)


def note_callback_error(topic: str) -> None:
    with _lock:
        _t(topic)["callback_errors"] += 1


def note_scan(topic: str, rows: int, transfer_wait_s: float,
              h2d_bytes: int) -> None:
    with _lock:
        st = _t(topic)
        st["scan_chunks"] += 1
        st["scan_rows"] += int(rows)
        st["transfer_wait_s"] += float(transfer_wait_s)
        st["h2d_bytes"] += int(h2d_bytes)


def note_scan_error(topic: str) -> None:
    """A chunk whose staging/scan/delivery raised — dropped, rows marked
    scanned, the scan thread stays alive."""
    with _lock:
        _t(topic)["scan_errors"] += 1


def note_deliveries(topic: str, n: int) -> None:
    with _lock:
        _t(topic)["deliveries"] += int(n)


def note_watermark(topic: str, subscription, watermark_ms: int,
                   clock=time.time) -> None:
    """Per-(topic, subscription) delivery watermark: the newest EVENT
    time (epoch ms) delivered to this standing subscription. The
    freshness gauge (``geomesa_stream_freshness_ms``) is derived at
    report time as now − watermark — end-to-end event-time lag, the
    staleness signal the standing-query runbook reads
    (docs/streaming.md). Monotone per subscription: a late chunk never
    regresses it."""
    with _lock:
        wm = _t(topic)["watermarks"]
        key = str(subscription)
        prev = wm.get(key)
        if prev is not None and prev[0] >= watermark_ms:
            wm[key] = (prev[0], clock())
            return
        if prev is None and len(wm) >= _MAX_WATERMARK_SUBS:
            wm.pop(next(iter(wm)))
        wm[key] = (int(watermark_ms), clock())


def report() -> dict:
    """Snapshot of every topic's stream gauges (the JSON metrics block).
    Poll stats come back per loop under ``poll_loops`` plus flat compat
    aggregates: ``polls``/``poll_rows`` sum over loops, ``poll_backoff_s``
    is the max (an idle loop's backoff must not be masked by a busy one)."""
    now_ms = time.time() * 1000.0
    with _lock:
        out = {}
        for topic, st in _topics.items():
            d = {k: v for k, v in st.items()
                 if k not in ("poll_loops", "watermarks")}
            loops = {lp: dict(ls) for lp, ls in st["poll_loops"].items()}
            d["poll_loops"] = loops
            d["polls"] = sum(ls["polls"] for ls in loops.values())
            d["poll_rows"] = sum(ls["poll_rows"] for ls in loops.values())
            d["poll_backoff_s"] = max(
                (ls["poll_backoff_s"] for ls in loops.values()), default=0.0
            )
            # freshness derived at read time: now − event-time watermark
            d["watermarks"] = {
                sub: {"watermark_ms": wm,
                      "freshness_ms": round(max(now_ms - wm, 0.0), 1)}
                for sub, (wm, _at) in st["watermarks"].items()
            }
            out[topic] = d
        return out


def reset() -> None:
    """Drop all state (tests)."""
    with _lock:
        _topics.clear()


def _esc(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"')


_PROM = [
    ("lag", "geomesa_stream_lag", "gauge"),
    ("scan_lag", "geomesa_stream_scan_lag", "gauge"),
    ("callback_errors", "geomesa_stream_callback_errors_total", "counter"),
    ("scan_chunks", "geomesa_stream_scan_chunks_total", "counter"),
    ("scan_rows", "geomesa_stream_scan_rows_total", "counter"),
    ("transfer_wait_s", "geomesa_stream_transfer_wait_seconds_total",
     "counter"),
    ("h2d_bytes", "geomesa_stream_h2d_bytes_total", "counter"),
    ("deliveries", "geomesa_stream_deliveries_total", "counter"),
    ("scan_errors", "geomesa_stream_scan_errors_total", "counter"),
]


_PROM_POLL = [
    ("polls", "geomesa_stream_polls_total", "counter"),
    ("poll_rows", "geomesa_stream_poll_rows_total", "counter"),
    ("poll_backoff_s", "geomesa_stream_poll_backoff_seconds", "gauge"),
]


def prometheus_lines() -> list[str]:
    snap = report()
    if not snap:
        return []
    lines: list[str] = []
    for key, name, kind in _PROM:
        lines.append(f"# TYPE {name} {kind}")
        for topic in sorted(snap):
            v = snap[topic][key]
            lines.append(f'{name}{{topic="{_esc(topic)}"}} {v}')
    # poll metrics carry the polling-loop label (consumer vs tailer poll
    # the SAME topic — one shared series would double-count throughput
    # and flap the backoff gauge between unrelated loops)
    for key, name, kind in _PROM_POLL:
        emitted_type = False
        for topic in sorted(snap):
            for loop in sorted(snap[topic]["poll_loops"]):
                if not emitted_type:
                    lines.append(f"# TYPE {name} {kind}")
                    emitted_type = True
                v = snap[topic]["poll_loops"][loop][key]
                lines.append(
                    f'{name}{{topic="{_esc(topic)}",loop="{_esc(loop)}"}} {v}'
                )
    # per-(topic, subscription) delivery watermark + derived freshness
    # (bounded to _MAX_WATERMARK_SUBS subscriptions per topic)
    for key, name in (("watermark_ms", "geomesa_stream_watermark_ms"),
                      ("freshness_ms", "geomesa_stream_freshness_ms")):
        emitted_type = False
        for topic in sorted(snap):
            for sub in sorted(snap[topic]["watermarks"]):
                if not emitted_type:
                    lines.append(f"# TYPE {name} gauge")
                    emitted_type = True
                v = snap[topic]["watermarks"][sub][key]
                lines.append(
                    f'{name}{{topic="{_esc(topic)}",'
                    f'subscription="{_esc(sub)}"}} {v}'
                )
    return lines


def prometheus_text() -> str:
    lines = prometheus_lines()
    return "\n".join(lines) + "\n" if lines else ""
