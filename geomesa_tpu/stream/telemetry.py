"""Process-wide streaming telemetry: per-topic lag / poll / scan gauges.

The streaming tier runs on background threads (journal tailer, consumer
groups, the device stream scanner) whose health is invisible to the
request path — this module is the one place they all report into, and
the web layer renders it on ``/api/metrics`` (JSON ``stream`` section)
and ``/api/metrics?format=prometheus``:

- ``geomesa_stream_lag{topic}`` — unconsumed bus messages behind the
  head (consumer groups / the journal tailer).
- ``geomesa_stream_scan_lag{topic}`` — rows the device scanner has
  accepted but not yet scanned. A SEPARATE gauge from ``lag``: the
  consumer and the scanner poll the same topic string, and one shared
  key would let an idle consumer's 0 overwrite a saturated scanner's
  backlog (the backpressure signal; docs/streaming.md § Backpressure).
- ``geomesa_stream_polls_total{topic,loop}`` /
  ``geomesa_stream_poll_rows_total{topic,loop}`` — poll-rate counters,
  labeled per polling LOOP (``consumer`` / ``tailer``): both loops poll
  the same topic string, and one shared key would double-count every
  record and make the rate read 2× the real throughput.
- ``geomesa_stream_poll_backoff_seconds{topic,loop}`` — the CURRENT idle
  backoff (0 under traffic; grows toward the cap while idle — the
  adaptive-backoff health check). Per loop for the same reason: a busy
  consumer must not zero the gauge of an idle tailer (last-writer-wins
  flapping would defeat the runbook's "at the cap means quiet" rule).
- ``geomesa_stream_callback_errors_total{topic}`` — subscriber callbacks
  that raised (mirrors the ``stream.callback_errors`` registry counter).
- ``geomesa_stream_scan_errors_total{topic}`` — chunks dropped because
  staging/scan/delivery raised (the scan thread stays alive; mirrors
  ``stream.scan_errors``).
- ``geomesa_stream_scan_rows_total`` / ``_scan_chunks_total`` /
  ``_transfer_wait_seconds_total`` / ``_h2d_bytes_total`` /
  ``_deliveries_total`` — the device scanner's pipeline accounting.

One leaf lock guards the table; nothing is called while it is held
(docs/concurrency.md).
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "set_lag", "set_scan_lag", "note_poll", "note_callback_error",
    "note_scan", "note_scan_error", "note_deliveries", "note_watermark",
    "report", "prometheus_lines", "prometheus_text", "reset",
]

_lock = threading.Lock()
_topics: dict[str, dict] = {}
# per-topic watermark TABLE bound — a memory ceiling, not the exposition
# bound. The exposition (report()/prometheus) valves to the stream lens's
# top-K-by-cost ranking plus an `other` rollup (_valve_watermarks), so
# the surface stays bounded AND representative; the table itself holds up
# to this many subscriptions, evicting the cheapest (by lens cost) when
# a new one arrives at the ceiling.
_MAX_WATERMARK_SUBS = 4096

_ZERO = {
    "lag": 0, "scan_lag": 0, "callback_errors": 0, "scan_chunks": 0,
    "scan_rows": 0, "transfer_wait_s": 0.0, "h2d_bytes": 0,
    "deliveries": 0, "scan_errors": 0,
}
_POLL_ZERO = {"polls": 0, "poll_rows": 0, "poll_backoff_s": 0.0}


def _t(topic: str) -> dict:
    st = _topics.get(topic)
    if st is None:
        st = dict(_ZERO)
        st["poll_loops"] = {}
        st["watermarks"] = {}
        _topics[topic] = st
    return st


def _loop(st: dict, loop: str) -> dict:
    ls = st["poll_loops"].get(loop)
    if ls is None:
        ls = dict(_POLL_ZERO)
        st["poll_loops"][loop] = ls
    return ls


def set_lag(topic: str, lag: int) -> None:
    """Bus-side lag: unconsumed messages (consumer groups, tailer)."""
    with _lock:
        _t(topic)["lag"] = int(lag)


def set_scan_lag(topic: str, lag: int) -> None:
    """Scanner-side lag: rows accepted but not yet scanned."""
    with _lock:
        _t(topic)["scan_lag"] = int(lag)


def note_poll(topic: str, drained: int, backoff_s: float = 0.0,
              loop: str = "consumer") -> None:
    """One poll round of one polling ``loop`` (``consumer``/``tailer``):
    ``drained`` rows dispatched, ``backoff_s`` the idle delay chosen for
    the NEXT round (0 under traffic)."""
    with _lock:
        ls = _loop(_t(topic), loop)
        ls["polls"] += 1
        ls["poll_rows"] += int(drained)
        ls["poll_backoff_s"] = float(backoff_s)


def note_callback_error(topic: str) -> None:
    with _lock:
        _t(topic)["callback_errors"] += 1


def note_scan(topic: str, rows: int, transfer_wait_s: float,
              h2d_bytes: int) -> None:
    with _lock:
        st = _t(topic)
        st["scan_chunks"] += 1
        st["scan_rows"] += int(rows)
        st["transfer_wait_s"] += float(transfer_wait_s)
        st["h2d_bytes"] += int(h2d_bytes)


def note_scan_error(topic: str) -> None:
    """A chunk whose staging/scan/delivery raised — dropped, rows marked
    scanned, the scan thread stays alive."""
    with _lock:
        _t(topic)["scan_errors"] += 1


def note_deliveries(topic: str, n: int) -> None:
    with _lock:
        _t(topic)["deliveries"] += int(n)


def _cheapest_watermark_sub(topic: str):
    """The lens's cheapest-ranked subscription for ``topic`` (eviction
    candidate), or None when the lens has no ranking. Called strictly
    OUTSIDE ``_lock`` — the lens lock and this lock are both leaves and
    must never nest (docs/concurrency.md)."""
    try:
        from geomesa_tpu.obs import streamlens as _sl

        rank = _sl.get().cost_rank(topic)
    except Exception:  # noqa: BLE001 — telemetry must not fail on obs
        return None
    return rank[-1][0] if rank else None


def note_watermark(topic: str, subscription, watermark_ms: int,
                   clock=time.time) -> None:
    """Per-(topic, subscription) delivery watermark: the newest EVENT
    time (epoch ms) delivered to this standing subscription. The
    freshness gauge (``geomesa_stream_freshness_ms``) is derived at
    report time as now − watermark — end-to-end event-time lag, the
    staleness signal the standing-query runbook reads
    (docs/streaming.md). Monotone per subscription: a late chunk never
    regresses it. At the table ceiling a NEW subscription evicts the
    lens's cheapest-by-cost ranked one (FIFO fallback when the lens has
    no ranking) — the expensive subscriptions the scale report tracks
    keep their gauges."""
    key = str(subscription)
    now = clock()
    with _lock:
        wm = _t(topic)["watermarks"]
        prev = wm.get(key)
        if prev is not None and prev[0] >= watermark_ms:
            wm[key] = (prev[0], now)
            return
        if prev is not None or len(wm) < _MAX_WATERMARK_SUBS:
            wm[key] = (int(watermark_ms), now)
            return
    # ceiling overflow (rare: a NEW subscription at a full table) — pick
    # the victim outside the lock, then re-check and evict under it
    victim = _cheapest_watermark_sub(topic)
    with _lock:
        wm = _t(topic)["watermarks"]
        if key not in wm and len(wm) >= _MAX_WATERMARK_SUBS:
            if victim is None or victim not in wm or victim == key:
                victim = next(iter(wm))  # FIFO fallback
            wm.pop(victim, None)
        wm[key] = (int(watermark_ms), now)


def _exposition_top_k() -> int:
    try:
        from geomesa_tpu.obs import streamlens as _sl

        return _sl.TOP_K
    except Exception:  # noqa: BLE001
        return 64


def _cost_order(topic: str) -> list:
    """Subscriptions of ``topic`` most-expensive-first per the stream
    lens (empty when unavailable). Never called under ``_lock``."""
    try:
        from geomesa_tpu.obs import streamlens as _sl

        return [sub for sub, _cost in _sl.get().cost_rank(topic)]
    except Exception:  # noqa: BLE001
        return []


def _valve_watermarks(topic: str, raw: dict, now_ms: float) -> dict:
    """The watermark/freshness exposition valve: at most top-K-by-cost
    subscriptions individually plus one ``other`` rollup (oldest
    watermark / worst freshness / count of the rest) — bounded AND
    representative, replacing the old hard-64 silent drop. ``other``
    only appears on overflow, so low-cardinality topics read exactly as
    before."""

    def entry(wm: int) -> dict:
        return {"watermark_ms": wm,
                "freshness_ms": round(max(now_ms - wm, 0.0), 1)}

    top_k = _exposition_top_k()
    if len(raw) <= top_k:
        return {sub: entry(wm) for sub, (wm, _at) in raw.items()}
    pos = {s: i for i, s in enumerate(_cost_order(topic))}
    ranked = sorted(raw, key=lambda s: (pos.get(s, len(pos)), s))
    out = {sub: entry(raw[sub][0]) for sub in ranked[:top_k]}
    rest = ranked[top_k:]
    oldest = min(raw[s][0] for s in rest)
    out["other"] = dict(entry(oldest), count=len(rest))
    return out


def report(now_ms: float | None = None) -> dict:
    """Snapshot of every topic's stream gauges (the JSON metrics block).
    Poll stats come back per loop under ``poll_loops`` plus flat compat
    aggregates: ``polls``/``poll_rows`` sum over loops, ``poll_backoff_s``
    is the max (an idle loop's backoff must not be masked by a busy one).
    ``now_ms`` pins the freshness clock (the backlog sentinel passes its
    evaluation time so thresholds are deterministic under test clocks)."""
    if now_ms is None:
        now_ms = time.time() * 1000.0
    with _lock:
        out = {}
        raw_wm = {}
        for topic, st in _topics.items():
            d = {k: v for k, v in st.items()
                 if k not in ("poll_loops", "watermarks")}
            loops = {lp: dict(ls) for lp, ls in st["poll_loops"].items()}
            d["poll_loops"] = loops
            d["polls"] = sum(ls["polls"] for ls in loops.values())
            d["poll_rows"] = sum(ls["poll_rows"] for ls in loops.values())
            d["poll_backoff_s"] = max(
                (ls["poll_backoff_s"] for ls in loops.values()), default=0.0
            )
            raw_wm[topic] = dict(st["watermarks"])
            out[topic] = d
    # freshness derived at read time (now − event-time watermark); the
    # valve ranks via the lens OUTSIDE the telemetry lock (leaf locks
    # never nest)
    for topic, d in out.items():
        d["watermarks"] = _valve_watermarks(topic, raw_wm[topic], now_ms)
    return out


def reset() -> None:
    """Drop all state (tests)."""
    with _lock:
        _topics.clear()


def _esc(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"')


_PROM = [
    ("lag", "geomesa_stream_lag", "gauge"),
    ("scan_lag", "geomesa_stream_scan_lag", "gauge"),
    ("callback_errors", "geomesa_stream_callback_errors_total", "counter"),
    ("scan_chunks", "geomesa_stream_scan_chunks_total", "counter"),
    ("scan_rows", "geomesa_stream_scan_rows_total", "counter"),
    ("transfer_wait_s", "geomesa_stream_transfer_wait_seconds_total",
     "counter"),
    ("h2d_bytes", "geomesa_stream_h2d_bytes_total", "counter"),
    ("deliveries", "geomesa_stream_deliveries_total", "counter"),
    ("scan_errors", "geomesa_stream_scan_errors_total", "counter"),
]


_PROM_POLL = [
    ("polls", "geomesa_stream_polls_total", "counter"),
    ("poll_rows", "geomesa_stream_poll_rows_total", "counter"),
    ("poll_backoff_s", "geomesa_stream_poll_backoff_seconds", "gauge"),
]


def prometheus_lines() -> list[str]:
    snap = report()
    if not snap:
        return []
    lines: list[str] = []
    for key, name, kind in _PROM:
        lines.append(f"# TYPE {name} {kind}")
        for topic in sorted(snap):
            v = snap[topic][key]
            lines.append(f'{name}{{topic="{_esc(topic)}"}} {v}')
    # poll metrics carry the polling-loop label (consumer vs tailer poll
    # the SAME topic — one shared series would double-count throughput
    # and flap the backoff gauge between unrelated loops)
    for key, name, kind in _PROM_POLL:
        emitted_type = False
        for topic in sorted(snap):
            for loop in sorted(snap[topic]["poll_loops"]):
                if not emitted_type:
                    lines.append(f"# TYPE {name} {kind}")
                    emitted_type = True
                v = snap[topic]["poll_loops"][loop][key]
                lines.append(
                    f'{name}{{topic="{_esc(topic)}",loop="{_esc(loop)}"}} {v}'
                )
    # per-(topic, subscription) delivery watermark + derived freshness —
    # valved by report() to the lens's top-K-by-cost subscriptions plus
    # the `other` rollup (subscription="other", only on overflow)
    for key, name in (("watermark_ms", "geomesa_stream_watermark_ms"),
                      ("freshness_ms", "geomesa_stream_freshness_ms")):
        emitted_type = False
        for topic in sorted(snap):
            for sub in sorted(snap[topic]["watermarks"]):
                if not emitted_type:
                    lines.append(f"# TYPE {name} gauge")
                    emitted_type = True
                v = snap[topic]["watermarks"][sub][key]
                lines.append(
                    f'{name}{{topic="{_esc(topic)}",'
                    f'subscription="{_esc(sub)}"}} {v}'
                )
    return lines


def prometheus_text() -> str:
    lines = prometheus_lines()
    return "\n".join(lines) + "\n" if lines else ""
