"""Device stream scanner: the productized bench-8 double-buffered pipeline.

The 1B-row streaming sweep (bench 8) proved the pattern — a bounded queue
of host-resident chunks, ``device_put`` of chunk c+1 issued BEHIND the
fused scan of chunk c so the H2D transfer overlaps compute, transfer-wait
measured (never subtracted) — but the pattern lived inline in the bench.
:class:`DeviceStreamScanner` is that pipeline as a subsystem: it owns the
scan thread, the double buffer, the per-subscription hit delivery of a
:class:`~geomesa_tpu.stream.matrix.SubscriptionMatrix`, transfer-wait
accounting (``stream/telemetry.py``), and a deterministic, idempotent
shutdown (sanitizer-verified; docs/streaming.md § Shutdown).

Two feeding modes share the pipeline:

- :meth:`submit_chunk` — pre-built column chunks through a BOUNDED queue;
  the producer blocks when ``max_pending_chunks`` are in flight (the
  bench-8 reader-thread contract: backpressure by blocking).
- :meth:`submit_rows` — row fragments (the bus-fed path): the scan thread
  cuts full chunks as they fill and flushes a partial chunk after
  ``flush_interval_s`` of quiet, padded to the fixed chunk shape so the
  compiled step never sees a new signature. This path never blocks the
  bus callback; backpressure is observational via :meth:`lag` (and the
  journal consumer's ``lag()`` upstream).

:class:`SubscriptionHub` bridges a message-bus topic onto the scanner:
it decodes ``Put`` messages, normalizes (lon, lat, dtg) into the
int-domain scan columns, and keeps per-chunk fid tags so deliveries can
name the matching features.

Locking (docs/concurrency.md): the scanner condition lock and the matrix
lock are LEAVES — chunk staging, the scan dispatch, and subscriber
callbacks all run strictly outside them.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from geomesa_tpu.obs import streamlens as _streamlens
from geomesa_tpu.obs import trace as _trace
from geomesa_tpu.stream.matrix import (
    HitBatch,
    SubscriptionMatrix,
    envelope_hits,
    merge_positions,
)
from geomesa_tpu.stream import telemetry

__all__ = ["DeviceStreamScanner", "SubscriptionHub", "HubRegistry"]


class _Chunk:
    __slots__ = ("seq", "base", "rows", "cols", "tags", "env",
                 "t_first", "t_cut", "t_stage0", "t_staged", "t_scan0",
                 "wait_s", "span")

    def __init__(self, seq, base, rows, cols, tags, env=None,
                 t_first=None, span=None):
        self.seq = seq
        self.base = base
        self.rows = rows  # true rows (cols are padded to the fixed shape)
        self.cols = cols  # (x, y, bins, offs) np int32, len == chunk_rows
        self.tags = tags  # per-true-row tags (fids) or None
        # wide (extended-geometry) rows: [(local_idx, ix1, ix2, iy1, iy2)]
        # — their x/y columns hold the -1 sentinel (no packed box matches a
        # negative coordinate, so the device pass never counts them) and
        # the scan thread refines them host-side via envelope_hits
        self.env = env
        # stage stamps (perf_counter seconds) — the stream lens's
        # queue-wait / pad-flush / h2d / scan decomposition source
        # (docs/streaming.md § Stream lens): t_first = oldest row's
        # submit time (≈ bus append on the bus-fed path), t_cut = chunk
        # cut from the fragment buffer, then staging/scan stamps from
        # the pipeline; wait_s = measured transfer wait attributed to
        # THIS chunk's staging by the double buffer
        self.t_cut = time.perf_counter()
        self.t_first = t_first if t_first is not None else self.t_cut
        self.t_stage0 = 0.0
        self.t_staged = 0.0
        self.t_scan0 = 0.0
        self.wait_s = 0.0
        # the submitting context's live span (None untraced): the chunk's
        # stage spans stitch under it retroactively after delivery, and
        # its trace_id becomes the delivery-histogram exemplar
        self.span = span


class DeviceStreamScanner:
    """Double-buffered streaming scan of a subscription matrix."""

    def __init__(self, matrix: SubscriptionMatrix, chunk_rows: int = 65536,
                 max_pending_chunks: int = 2, flush_interval_s: float = 0.05,
                 topic: str = "stream", keep_tags: bool = True,
                 allowed_lateness_ms: float = 30_000.0):
        from geomesa_tpu.ops.pallas_kernels import LANES
        from geomesa_tpu.parallel.mesh import data_shards

        self.matrix = matrix
        shards = data_shards(matrix.mesh)
        unit = shards * LANES
        # fixed chunk shape: shard- and lane-aligned so the compiled step
        # sees ONE signature for full and partial (padded) chunks alike
        self.chunk_rows = ((max(chunk_rows, unit) + unit - 1) // unit) * unit
        if matrix.topk > self.chunk_rows // shards:
            raise ValueError("topk exceeds per-shard rows of one chunk")
        self.max_pending_chunks = max(1, max_pending_chunks)
        self.flush_interval_s = flush_interval_s
        self.topic = topic
        self.keep_tags = keep_tags
        # event-time watermark support (stream/telemetry.py freshness
        # gauges): (bin, offset) rows convert back to epoch ms when the
        # matrix knows its schema's Z3 interval; packed-payload matrices
        # (bench) skip watermarks
        self._binned = None
        sft = getattr(matrix, "sft", None)
        if sft is not None and getattr(sft, "dtg_field", None):
            from geomesa_tpu.curve.binned_time import BinnedTime

            self._binned = BinnedTime(sft.z3_interval)
        self.allowed_lateness_ms = allowed_lateness_ms
        # per-subscription delivered event-time watermark — scan-thread
        # private (lateness is judged and advanced only in _deliver), so
        # no lock guards it
        self._wm: dict[int, int] = {}
        # flight-recorder type name for stream anomalies (A_STREAM_ERROR)
        self._type_name = (
            getattr(getattr(matrix, "sft", None), "name", None) or topic
        )
        self._lock = threading.Lock()  # leaf: buffers, queue, stats
        self._cv = threading.Condition(self._lock)
        # (x, y, bins, offs, tags, env, t_in, span) fragments
        self._frags: list[tuple] = []
        self._buffered = 0
        self._chunks: deque[_Chunk] = deque()
        self._seq = 0
        self._rows_in = 0  # rows accepted (global stream positions)
        self._rows_scanned = 0
        self._chunks_scanned = 0
        self._totals: dict[int, int] = {}  # sid → delivered total (scan thread)
        self._stats = {
            "chunks": 0, "rows": 0, "h2d_bytes": 0, "transfer_wait_s": 0.0,
            "scan_s": 0.0, "deliveries": 0, "callback_errors": 0,
            "scan_errors": 0,
        }
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"geomesa-stream-scan-{topic}",
        )
        self._thread.start()

    # -- feeding --------------------------------------------------------------
    def submit_rows(self, x, y, bins, offs, tags=None,
                    envelopes=None) -> None:
        """Append rows (np int32 columns) to the scan stream. Never blocks —
        the bus dispatch thread must not stall; watch :meth:`lag`.

        ``envelopes``: optional per-row ``None | (ix1, ix2, iy1, iy2)``
        normalized int envelopes for EXTENDED geometries. Wide rows get
        the -1 x/y sentinel (the device pass never matches them) and are
        refined host-side against each subscription's payload
        (:func:`~geomesa_tpu.stream.matrix.envelope_hit`) at delivery —
        bbox overlap, not center containment."""
        n = len(x)
        if n == 0:
            return
        if len(y) != n or len(bins) != n or len(offs) != n:
            raise ValueError("column length mismatch")
        if tags is not None and len(tags) != n:
            raise ValueError("tags length mismatch")
        x = np.asarray(x, np.int32)
        y = np.asarray(y, np.int32)
        env = None
        if envelopes is not None:
            if len(envelopes) != n:
                raise ValueError("envelopes length mismatch")
            wide = [i for i, e in enumerate(envelopes) if e is not None]
            if wide:
                x = x.copy()
                y = y.copy()
                x[wide] = -1
                y[wide] = -1
                env = list(envelopes)
        frag = (
            x, y,
            np.asarray(bins, np.int32), np.asarray(offs, np.int32),
            list(tags) if (tags is not None and self.keep_tags) else None,
            env,
            time.perf_counter(),  # submit stamp → the chunk's t_first
            _trace.current() if _trace.active() else None,
        )
        with self._cv:
            if self._closed:
                return
            self._frags.append(frag)
            self._buffered += n
            self._rows_in += n
            while self._buffered >= self.chunk_rows:
                self._cut_locked(self.chunk_rows)
            self._cv.notify_all()

    def submit_chunk(self, x, y, bins, offs, tags=None,
                     block: bool = True) -> bool:
        """Submit one pre-built chunk through the bounded pipeline queue.
        With ``block=True`` the caller waits while ``max_pending_chunks``
        chunks are already in flight — the reader-thread backpressure
        contract. Returns False if the scanner is closed."""
        t_in = time.perf_counter()
        sp = _trace.current() if _trace.active() else None
        with self._cv:
            if self._closed:
                return False
            if self._buffered:
                # row-mode fragments flush first so stream positions stay
                # in submission order
                self._cut_locked(min(self._buffered, self.chunk_rows))
            while (
                block
                and len(self._chunks) >= self.max_pending_chunks
                and not self._closed
            ):
                # Condition.wait RELEASES the lock while blocked — this
                # is the bounded-queue backpressure rendezvous itself
                # tpurace: disable-next-line=R003
                self._cv.wait(0.05)
            if self._closed:
                return False
            self._append_chunk_locked(x, y, bins, offs, tags,
                                      t_first=t_in, span=sp)
            self._cv.notify_all()
        return True

    def _append_chunk_locked(self, x, y, bins, offs, tags,
                             t_first=None, span=None) -> None:
        n = len(x)
        cols = []
        for a in (x, y, bins, offs):
            a = np.asarray(a, np.int32)
            if n < self.chunk_rows:
                a = np.concatenate(
                    [a, np.zeros(self.chunk_rows - n, np.int32)]
                )
            elif n > self.chunk_rows:
                raise ValueError(
                    f"chunk of {n} rows exceeds chunk_rows={self.chunk_rows}"
                )
            cols.append(a)
        self._chunks.append(_Chunk(
            self._seq, self._rows_in,
            n, tuple(cols),
            list(tags) if (tags is not None and self.keep_tags) else None,
            t_first=t_first, span=span,
        ))
        self._seq += 1
        self._rows_in += n

    def _cut_locked(self, take: int) -> None:
        """Concatenate buffered fragments and emit the first ``take`` rows
        as one chunk (padded to the fixed shape); the remainder stays
        buffered. Caller holds the lock; numpy concat only — no I/O."""
        xs, ys, bs, os_, tags, envs = [], [], [], [], [], []
        # materialize the per-row tag/env lists only when some fragment
        # actually carries them — the common bus-fed chunk (no fid tags
        # kept, no extended geometries) must not allocate and discard two
        # chunk_rows-length Python lists per cut while holding the lock
        have_tags = any(f[4] is not None for f in self._frags)
        have_env = any(f[5] is not None for f in self._frags)
        for fx, fy, fb, fo, ft, fe, _ti, _sp in self._frags:
            xs.append(fx)
            ys.append(fy)
            bs.append(fb)
            os_.append(fo)
            if have_tags:
                tags.extend(ft if ft is not None else [None] * len(fx))
            if have_env:
                envs.extend(fe if fe is not None else [None] * len(fx))
        # the chunk inherits the OLDEST fragment's submit stamp (latency
        # is measured from the first still-waiting row) and the first
        # traced fragment's span; the remainder keeps the newest
        # fragment's stamp — its rows arrived last
        t_first = self._frags[0][6]
        span = next((f[7] for f in self._frags if f[7] is not None), None)
        rest_t, rest_sp = self._frags[-1][6], self._frags[-1][7]
        x = np.concatenate(xs)
        y = np.concatenate(ys)
        b = np.concatenate(bs)
        o = np.concatenate(os_)
        rest = len(x) - take
        self._frags = (
            [(x[take:], y[take:], b[take:], o[take:],
              tags[take:] if have_tags else None,
              envs[take:] if have_env else None,
              rest_t, rest_sp)] if rest else []
        )
        self._buffered = rest
        base = self._rows_in - rest - take
        cols = []
        for a in (x[:take], y[:take], b[:take], o[:take]):
            if take < self.chunk_rows:
                a = np.concatenate(
                    [a, np.zeros(self.chunk_rows - take, np.int32)]
                )
            cols.append(a)
        env = (
            [(i, *e) for i, e in enumerate(envs[:take]) if e is not None]
            if have_env else None
        )
        self._chunks.append(_Chunk(
            self._seq, base, take, tuple(cols),
            tags[:take] if have_tags else None,
            env or None,
            t_first=t_first, span=span,
        ))
        self._seq += 1

    # -- pipeline -------------------------------------------------------------
    def _next_chunk(self):
        """Block until a chunk is available, a quiet partial buffer is due
        for flush, or shutdown. Returns None to exit."""
        deadline = None
        with self._cv:
            while True:
                # stop FIRST: close() promises "after the in-flight chunk",
                # so queued-but-unstarted chunks are dropped (drain() first
                # for a graceful flush) — otherwise a deep backlog could
                # outlive close()'s bounded join and leave the thread alive
                if self._stop.is_set():
                    return None
                if self._chunks:
                    chunk = self._chunks.popleft()
                    self._cv.notify_all()  # wake bounded submitters
                    return chunk
                if self._buffered:
                    now = time.monotonic()
                    if deadline is None:
                        deadline = now + self.flush_interval_s
                    if now >= deadline:
                        self._cut_locked(self._buffered)
                        continue
                    # CV wait releases the lock (flush-deadline sleep)
                    # tpurace: disable-next-line=R003
                    self._cv.wait(deadline - now)
                else:
                    deadline = None
                    # CV wait releases the lock (idle work-arrival wait)
                    # tpurace: disable-next-line=R003
                    self._cv.wait(self.flush_interval_s)

    def _stage(self, chunk: _Chunk):
        """Async device_put of one chunk's columns (sharded over the data
        axis) — accounted as STREAM staging (``jax.transfer.h2d_bytes.
        stream``), never against a concurrently profiled query."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from geomesa_tpu.obs.jaxmon import count_h2d
        from geomesa_tpu.parallel.mesh import DATA_AXIS

        chunk.t_stage0 = time.perf_counter()
        nbytes = count_h2d(*chunk.cols, label="stream")
        sh = NamedSharding(self.matrix.mesh, P(DATA_AXIS))
        dev = tuple(jax.device_put(a, sh) for a in chunk.cols)
        chunk.t_staged = time.perf_counter()
        with self._lock:
            self._stats["h2d_bytes"] += nbytes
        return dev + (jnp.int32(chunk.rows),), chunk

    def _drop_failed(self, chunk: _Chunk) -> None:
        """A chunk whose staging/scan/delivery raised: count it, mark its
        rows scanned (drain must terminate; one poisoned chunk must not
        wedge the pipeline), and keep the scan thread ALIVE — a dead scan
        thread would silently stop every standing query of the topic, the
        same failure mode the tailer's swallowed callbacks had."""
        from geomesa_tpu.obs import flight, jaxmon

        jaxmon.registry().counter("stream.scan_errors").inc()
        telemetry.note_scan_error(self.topic)
        _streamlens.get().note_dropped(self.topic, chunk.rows)
        # a poisoned chunk is a delivery-correctness event, not just a
        # counter: every active subscription of the topic silently missed
        # these rows (the recorder's dump throttle bounds a drop storm)
        flight.record(
            "stream.scan", self._type_name, source="stream",
            plan=(f"poisoned chunk dropped: seq={chunk.seq} "
                  f"base={chunk.base} rows={chunk.rows} "
                  f"subscriptions={self.matrix.active_count()}"),
            rows=chunk.rows, plan_signature="stream.scan",
            anomalies=(flight.A_STREAM_ERROR,),
        )
        with self._lock:
            self._stats["scan_errors"] += 1
        # _cv wraps the same lock; separate block so progress counters and
        # the stats table each sit under their canonical guard
        with self._cv:
            self._chunks_scanned += 1
            self._rows_scanned += chunk.rows
            self._cv.notify_all()

    def _run(self) -> None:
        import jax

        pending = None  # staged (device cols, chunk) for the NEXT scan
        while True:
            if pending is None:
                chunk = self._next_chunk()
                if chunk is None:
                    break
                try:
                    pending = self._stage(chunk)
                except Exception:  # noqa: BLE001 — scan thread must live
                    self._drop_failed(chunk)
                    continue
            staged, chunk = pending
            pending = None
            # prefetch: stage the following chunk BEHIND this chunk's scan
            # (the double buffer — transfer overlaps compute)
            nxt = None
            with self._cv:
                if self._chunks:
                    nxt = self._chunks.popleft()
                    self._cv.notify_all()
            if nxt is not None:
                try:
                    pending = self._stage(nxt)
                except Exception:  # noqa: BLE001
                    self._drop_failed(nxt)
            try:
                t0 = time.perf_counter()
                chunk.t_scan0 = t0
                snap = self.matrix.snapshot()
                # one dispatch per streamed chunk is the design: the scanner
                # double-buffers H2D against the scan, so the loop-carried
                # roundtrip overlaps the next chunk's transfer
                # tpusync: disable-next-line=S003
                counts, pos = self.matrix.scan_chunk(snap, *staged)
                scan_s = time.perf_counter() - t0
                wait_s = 0.0
                if pending is not None:
                    t1 = time.perf_counter()
                    jax.block_until_ready(pending[0])  # ALL columns
                    wait_s = time.perf_counter() - t1
                    # transfer wait is the PENDING chunk's staging cost:
                    # its lens h2d stage must carry it, not this chunk's
                    pending[1].wait_s += wait_s
                self._deliver(snap, counts, pos, chunk, scan_s)
            except Exception:  # noqa: BLE001 — scan thread must live
                self._drop_failed(chunk)
                continue
            with self._cv:
                self._chunks_scanned += 1
                self._rows_scanned += chunk.rows
                st = self._stats
                st["chunks"] += 1
                st["rows"] += chunk.rows
                st["transfer_wait_s"] += wait_s
                st["scan_s"] += scan_s
                lag = self._rows_in - self._rows_scanned
                self._cv.notify_all()
            telemetry.note_scan(
                self.topic, chunk.rows, wait_s,
                int(np.sum([c.nbytes for c in chunk.cols])),
            )
            telemetry.set_scan_lag(self.topic, lag)
        # drop any un-scanned work deterministically on shutdown
        with self._cv:
            self._chunks.clear()
            self._frags = []
            self._buffered = 0
            self._cv.notify_all()

    def _deliver(self, snap, counts, pos, chunk: _Chunk,
                 scan_s: float = 0.0) -> None:
        """Per-subscription hit delivery for one chunk: count delta + the
        newest-match position sample (+ row tags when kept). Wide rows
        (extended geometries, x/y = -1 device sentinel) refine host-side
        here — envelope overlap against each subscription's packed payload
        — and fold into the same delivery. Callback errors are counted,
        never propagated — one bad consumer must not stall the pipeline
        (same posture as the journal tailer).

        This is also where the stream lens feeds (docs/streaming.md
        § Stream lens): per subscription, a cost observation every chunk
        (``hits + refine_rows + 0.01 × rows`` — attribution folded out of
        outputs the fused scan already computed) and, for subscriptions
        that matched, a delivery-latency observation decomposed from the
        chunk's stage stamps, judged on-time/late against the
        subscription's event-time watermark + ``allowed_lateness_ms``,
        with the chunk's trace id as exemplar. Tenant-stamped
        subscriptions meter delivered rows into the usage meter under
        ``standing.delivery`` (shadow traffic stays unmetered)."""
        from geomesa_tpu.obs import audit as _audit
        from geomesa_tpu.obs import usage as _usage

        t_deliver0 = time.perf_counter()
        lens = _streamlens.get()
        lens.note_matrix(
            self.topic, capacity=snap.capacity, active=len(snap.subs),
            epoch=snap.epoch, slot_bytes=self.matrix.slot_bytes(),
        )
        wide: dict[int, np.ndarray] = {}  # sid → matched wide local idxs
        refine_s: dict[int, float] = {}  # sid → host refine seconds
        n_wide = 0
        if chunk.env:
            env = np.asarray(chunk.env, dtype=np.int64)
            n_wide = len(env)
            idx = env[:, 0]
            ex1, ex2, ey1, ey2 = env[:, 1], env[:, 2], env[:, 3], env[:, 4]
            wb = chunk.cols[2][idx].astype(np.int64)
            wo = chunk.cols[3][idx].astype(np.int64)
            for sid, sub in snap.subs.items():
                r0 = time.perf_counter()
                m = envelope_hits(sub.boxes, sub.times,
                                  ex1, ex2, ey1, ey2, wb, wo)
                refine_s[sid] = time.perf_counter() - r0
                if m.any():
                    wide[sid] = idx[m]
        delivered = 0
        # per-(topic, subscription) delivery watermark: the newest EVENT
        # time each active subscription has been evaluated THROUGH —
        # advanced per scanned chunk whether or not it matched (a
        # rare-match subscription's freshness must not freeze while the
        # scanner is fully current); freshness gauges derive end-to-end
        # event-time lag from it at scrape time (docs/streaming.md)
        wm_ms = None
        ev_min_ms = None
        if self._binned is not None and chunk.rows:
            wb_all = np.asarray(
                chunk.cols[2][: chunk.rows], dtype=np.int64)
            wo_all = np.asarray(
                chunk.cols[3][: chunk.rows], dtype=np.int64)
            ev = self._binned.from_bin_and_offset(wb_all, wo_all)
            wm_ms = int(ev.max())
            ev_min_ms = int(ev.min())
        # stage decomposition shared by every delivery of this chunk
        # (STAGES order: queue_wait, pad_flush, h2d, scan, refine, fanout)
        pad_ms = max(chunk.t_cut - chunk.t_first, 0.0) * 1e3
        queue_ms = (max(chunk.t_stage0 - chunk.t_cut, 0.0)
                    + max(chunk.t_scan0 - chunk.t_staged, 0.0)) * 1e3
        h2d_ms = (max(chunk.t_staged - chunk.t_stage0, 0.0)
                  + chunk.wait_s) * 1e3
        scan_ms = scan_s * 1e3
        trace_id = chunk.span.trace_id if chunk.span is not None else ""
        wall_ms = time.time() * 1000.0
        active = max(len(snap.subs), 1)
        row_cost = chunk.rows * _streamlens.SCAN_ROW_WEIGHT
        for slot, sid in enumerate(snap.sids):
            if sid is None:
                continue
            # on-time vs this subscription's own watermark: late when the
            # chunk carries rows BEHIND the event time already delivered
            # (out-of-order data) or when its oldest row's event time has
            # fallen more than allowed_lateness_ms behind the wall clock
            # (processing fell behind — the injected-stall signature)
            on_time = None
            if wm_ms is not None:
                telemetry.note_watermark(self.topic, sid, wm_ms)
                prev = self._wm.get(sid)
                on_time = (
                    (prev is None or ev_min_ms >= prev)
                    and wall_ms - ev_min_ms <= self.allowed_lateness_ms
                )
                if prev is None or wm_ms > prev:
                    self._wm[sid] = wm_ms
            c = int(counts[slot])
            ex = wide.get(sid)
            if ex is not None:
                c += len(ex)
            cost = c + n_wide + row_cost
            if c == 0:
                # cost + lateness accounting only — the delivery histogram
                # holds real deliveries
                lens.observe_delivery(self.topic, sid, cost=cost,
                                      on_time=on_time)
                continue
            sub = snap.subs[sid]
            local = merge_positions(pos[slot], self.matrix.topk)
            # int64 BEFORE adding base: global stream positions outlive
            # int32 after ~2.1B accepted rows
            local = local.astype(np.int64)
            if ex is not None:
                local = np.sort(np.concatenate(
                    [local, ex]
                ))[::-1][: self.matrix.topk]
            tags = None
            if chunk.tags is not None:
                tags = [chunk.tags[int(p)] for p in local]
            total = self._totals.get(sid, 0) + c
            self._totals[sid] = total
            batch = HitBatch(
                sid=sid, predicate=sub.predicate, count=c, total=total,
                positions=np.int64(chunk.base) + local, tags=tags,
                chunk=chunk.seq, base=chunk.base, rows=chunk.rows,
            )
            try:
                sub.callback(batch)
                delivered += 1
            except Exception:  # noqa: BLE001 — one bad consumer
                from geomesa_tpu.obs import jaxmon

                jaxmon.registry().counter("stream.callback_errors").inc()
                telemetry.note_callback_error(self.topic)
                with self._lock:
                    self._stats["callback_errors"] += 1
            t_done = time.perf_counter()
            latency_ms = max(t_done - chunk.t_first, 0.0) * 1e3
            lens.observe_delivery(
                self.topic, sid, latency_ms=latency_ms,
                stages=(queue_ms, pad_ms, h2d_ms, scan_ms,
                        refine_s.get(sid, 0.0) * 1e3,
                        max(t_done - t_deliver0, 0.0) * 1e3),
                hit_rows=c, cost=cost, on_time=on_time, trace_id=trace_id,
            )
            tenant = getattr(sub, "tenant", None)
            if tenant is not None and not _audit.in_shadow():
                # the subscription's share of the fused pass as device
                # time; slo=False — standing deliveries have their own
                # stream.delivery objective on the lens engine
                _usage.observe(
                    tenant, self._type_name, "standing.delivery",
                    rows=c, wall_ms=latency_ms,
                    device_ms=scan_ms / active, slo=False,
                )
        if delivered:
            with self._lock:
                self._stats["deliveries"] += delivered
            telemetry.note_deliveries(self.topic, delivered)
        self._attach_spans(chunk, scan_s, t_deliver0)

    def _attach_spans(self, chunk: _Chunk, scan_s: float,
                      t_deliver0: float) -> None:
        """Retroactively stitch this chunk's stage spans under the
        submitting context's span, so a traced ``submit_rows`` (the bus
        consumer's ``stream.poll`` root) reads as ONE tree: poll → cut →
        stage → scan → deliver. Spans are hand-stamped in the
        perf_counter domain (``Span.t0_ns`` is perf_counter_ns) and
        appended after the fact — late child attach is the documented
        exporter contract (obs/trace.py: snapshots via list())."""
        parent = chunk.span
        if parent is None:
            return
        t_done = time.perf_counter()
        for name, lo, hi in (
            ("stream.cut", chunk.t_first, chunk.t_cut),
            ("stream.stage", chunk.t_stage0, chunk.t_staged),
            ("stream.scan", chunk.t_scan0, chunk.t_scan0 + scan_s),
            ("stream.deliver", t_deliver0, t_done),
        ):
            sp = _trace.Span(
                name, {"topic": self.topic, "seq": chunk.seq,
                       "rows": chunk.rows}, parent)
            sp.t0_ns = int(lo * 1e9)
            sp.t1_ns = int(max(hi, lo) * 1e9)
            parent.children.append(sp)

    # -- introspection / lifecycle -------------------------------------------
    def total(self, sid: int) -> int:
        """Cumulative matches delivered to one subscription."""
        return self._totals.get(sid, 0)

    def lag(self) -> int:
        """Rows accepted but not yet scanned (the backpressure signal)."""
        with self._lock:
            return self._rows_in - self._rows_scanned

    def rows_in(self) -> int:
        with self._lock:
            return self._rows_in

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Flush the partial buffer and block until every accepted row has
        been scanned and delivered."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            if self._buffered:
                self._cut_locked(self._buffered)
                self._cv.notify_all()
            while self._rows_scanned < self._rows_in:
                if self._stop.is_set():
                    return False
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                # CV wait releases the lock (drain rendezvous)
                # tpurace: disable-next-line=R003
                self._cv.wait(min(left, 0.1))
        return True

    def close(self) -> None:
        """Deterministic idempotent shutdown: stop after the in-flight
        chunk, join the scan thread, reject further submissions. Call
        :meth:`drain` first for a graceful flush."""
        with self._cv:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
                self._stop.set()
            self._cv.notify_all()
        if not already:
            self._thread.join(timeout=10.0)


class SubscriptionHub:
    """Bus-topic → scanner bridge: decode, normalize, batch, scan.

    One hub per (topic, feature type). ``ingest`` is the bus subscriber
    callback: ``Put`` messages become int-domain scan rows ((lon, lat)
    normalized exactly like ``TpuBackend._payload``'s query side, dtg →
    (bin, offset) via the type's Z3 interval); ``Delete``/``Clear`` are
    ignored — standing queries watch the APPEND stream. Deliveries carry
    fid tags for the sampled positions."""

    def __init__(self, sft, serializer, topic: str, mesh=None,
                 chunk_rows: int = 8192, topk: int = 64,
                 box_slots: int = 2, time_slots: int = 2,
                 flush_interval_s: float = 0.05,
                 max_pending_chunks: int = 2):
        from geomesa_tpu.curve.binned_time import BinnedTime
        from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon

        self.sft = sft
        self.serializer = serializer
        self.topic = topic
        self.matrix = SubscriptionMatrix(
            sft, mesh=mesh, box_slots=box_slots, time_slots=time_slots,
            topk=topk,
        )
        self.scanner = DeviceStreamScanner(
            self.matrix, chunk_rows=chunk_rows,
            max_pending_chunks=max_pending_chunks,
            flush_interval_s=flush_interval_s, topic=topic,
        )
        self._binned = BinnedTime(sft.z3_interval)
        self._nlon = norm_lon(31)
        self._nlat = norm_lat(31)
        self._rows_ingested = 0
        # rows already ingested when each subscription registered: the
        # auditor's standing-count sweep only compares subscriptions
        # that observed the WHOLE stream (base 0 — registered before any
        # ingest, or first-with-backlog-replay); later subscribers see a
        # suffix by contract and must abstain, not alarm
        self._sub_base: dict[int, int] = {}

    def ingest(self, data: bytes) -> None:
        from geomesa_tpu.stream.messages import Put

        if self.matrix.active_count() == 0:
            # no standing queries: don't pay decode + normalize + chunk +
            # device scan per row against an all-masked matrix. Rows
            # appended in this window deliver to nobody either way — a
            # subscription added later only sees subsequent chunks (the
            # snapshot contract), so dropping here is observably identical.
            return
        msg = self.serializer.deserialize(data)
        if not isinstance(msg, Put):
            return
        geom = (
            msg.record.get(self.sft.geom_field)
            if self.sft.geom_field else None
        )
        if geom is None:
            return  # nothing to match spatially; standing queries are spatial
        x1, y1, x2, y2 = geom.bbox
        ms = msg.record.get(self.sft.dtg_field) if self.sft.dtg_field else None
        if not isinstance(ms, (int, float)):
            ms = msg.ts
        bins, offs = self._binned.to_bin_and_offset(
            np.array([int(ms)], np.int64)
        )
        ix1 = int(self._nlon.normalize(x1))
        iy1 = int(self._nlat.normalize(y1))
        if x1 == x2 and y1 == y2:
            # point: the device containment kernel is exact for it
            env = None
        else:
            # extended geometry: its envelope may straddle a query box its
            # center never enters — route through the wide-row host refine
            # (bbox overlap, matrix.envelope_hit), not center containment
            env = [(ix1, int(self._nlon.normalize(x2)),
                    iy1, int(self._nlat.normalize(y2)))]
        self.scanner.submit_rows(
            np.array([ix1], np.int32),
            np.array([iy1], np.int32),
            bins.astype(np.int32), offs.astype(np.int32),
            tags=[msg.fid],
            envelopes=env,
        )
        self._rows_ingested += 1

    # -- delegation -----------------------------------------------------------
    def subscribe(self, predicate, callback) -> int:
        from geomesa_tpu.obs import audit as _audit
        from geomesa_tpu.obs import usage as _usage

        # tenant stamped at subscribe time: deliveries meter under
        # standing.delivery for THIS tenant. Shadow-plane subscribers
        # (sweeper/audit referees) stay unstamped → unmetered.
        tenant = None if _audit.in_shadow() else _usage.current_tenant()
        sid = self.matrix.subscribe(predicate, callback, tenant=tenant)
        self._sub_base[sid] = self._rows_ingested
        return sid

    def sub_base(self, sid: int) -> int:
        """Rows already ingested when ``sid`` registered (see
        ``_sub_base``); unknown sids report as late joiners."""
        return self._sub_base.get(sid, 1 << 62)

    def unsubscribe(self, sid: int) -> bool:
        self._sub_base.pop(sid, None)
        return self.matrix.unsubscribe(sid)

    def rows_ingested(self) -> int:
        return self._rows_ingested

    def lag(self) -> int:
        return self.scanner.lag()

    def drain(self, timeout_s: float = 30.0) -> bool:
        return self.scanner.drain(timeout_s)

    def close(self) -> None:
        self.scanner.close()


class HubRegistry:
    """Key → :class:`SubscriptionHub` table shared by the standing-query
    front doors (``StreamingDataStore.subscribe_query``,
    ``JournalBus.subscribe_query``) so their lifecycle logic cannot drift.

    It owns the one ORDERING rule both callers must obey: the standing
    query registers on the hub's matrix BEFORE ``attach`` wires the hub's
    ``ingest`` onto the bus — bus registration synchronously replays the
    topic backlog, and a replay into an empty matrix would silently drop
    every historical match. The inverse ordering is enforced for every
    LATER subscriber: it waits for the first subscriber's ``attach`` to
    finish (the per-key ``armed`` event) before registering its matrix
    row, or a thread landing between the table insert and the replay
    would receive the backlog the first-subscription-only contract says
    it must not see. ``_lock`` is a LEAF guarding only the tables
    (docs/concurrency.md); hub construction spawns a scan thread,
    ``attach`` may join a draining tailer, and the armed wait blocks —
    all run strictly outside it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hubs: dict[str, SubscriptionHub] = {}
        self._cfgs: dict[str, dict] = {}
        self._armed: dict[str, threading.Event] = {}
        self._detaches: dict[str, object] = {}

    def subscribe(self, key: str, predicate, callback, make_hub,
                  attach, cfg: dict | None = None) -> int:
        """``attach(hub)`` wires ``hub.ingest`` onto the caller's bus and
        may return a detach callable — ``close_all`` invokes it so a
        shared or reused bus stops feeding the closed scanner."""
        cfg = cfg or {}
        with self._lock:
            hub = self._hubs.get(key)
            armed = self._armed.get(key)
        fresh = False
        if hub is None:
            # hub construction OUTSIDE the lock (it spawns a scan thread
            # and may initialize the device mesh); a concurrent first
            # subscriber may win the table race — the loser's hub closes
            candidate = make_hub()
            with self._lock:
                hub = self._hubs.get(key)
                if hub is None:
                    self._hubs[key] = hub = candidate
                    self._cfgs[key] = cfg
                    self._armed[key] = armed = threading.Event()
                    fresh = True
                else:
                    armed = self._armed[key]
            if not fresh:
                candidate.close()
        if not fresh:
            with self._lock:
                existing = self._cfgs.get(key, {})
            if cfg and cfg != existing:
                # the hub is built once per key; silently dropping a LATER
                # subscriber's different chunk/flush config would hand it
                # the first subscriber's delivery cadence without warning
                raise ValueError(
                    f"hub for {key!r} already configured with "
                    f"{existing!r}; differing hub_cfg {cfg!r} "
                    "applies only to the first subscription"
                )
            # wait (outside every lock) until the first subscriber's
            # attach has replayed the backlog — registering before it
            # would deliver the backlog to this subscription too
            armed.wait()
            with self._lock:
                live = self._hubs.get(key) is hub
            if not live:
                # the first subscriber failed and rolled the hub back
                # (its armed.set() released this wait) — become the
                # first subscriber of a fresh hub instead
                return self.subscribe(key, predicate, callback, make_hub,
                                      attach, cfg)
            return hub.subscribe(predicate, callback)
        try:
            sid = hub.subscribe(predicate, callback)
            detach = attach(hub)  # replays the backlog — matrix armed above
        except BaseException:
            # roll the table back so the key is retryable — and set the
            # armed event so a concurrent waiter re-checks instead of
            # blocking on a hub that will never attach
            with self._lock:
                if self._hubs.get(key) is hub:
                    del self._hubs[key]
                    self._cfgs.pop(key, None)
                    self._armed.pop(key, None)
            armed.set()
            hub.close()
            raise
        armed.set()
        if detach is not None:
            with self._lock:
                self._detaches[key] = detach
        return sid

    def unsubscribe(self, key: str, sid: int) -> bool:
        with self._lock:
            hub = self._hubs.get(key)
        return hub.unsubscribe(sid) if hub is not None else False

    def get(self, key: str):
        with self._lock:
            return self._hubs.get(key)

    def items(self) -> list:
        """``[(key, hub), ...]`` — the auditor's standing-count sweep
        iterates live hubs through this (obs/audit.py)."""
        with self._lock:
            return list(self._hubs.items())

    def close_all(self) -> None:
        with self._lock:
            hubs = list(self._hubs.values())
            detaches = list(self._detaches.values())
            self._hubs.clear()
            self._cfgs.clear()
            self._armed.clear()
            self._detaches.clear()
        for detach in detaches:
            # stop the bus feeding first: a shared/reused bus would
            # otherwise decode + normalize every record into a dead
            # scanner forever (and stack a second ingest beside it on
            # the next subscribe_query)
            detach()
        for hub in hubs:
            hub.close()
