"""Streaming feature store: message bus, live cache, streaming datastore.

Role parity: ``geomesa-kafka`` (SURVEY.md §2.10) — writes publish change
messages to a topic, readers maintain a continuously-updated in-memory feature
cache with a local spatial index and event-time expiry, queries are served
from the cache.
"""

from geomesa_tpu.stream.messages import (  # noqa: F401
    Clear,
    Delete,
    GeoMessageSerializer,
    Put,
)
from geomesa_tpu.stream.datastore import MessageBus, StreamingDataStore  # noqa: F401
from geomesa_tpu.stream.remote_journal import RemoteJournal  # noqa: F401

_LAZY = {
    # the subscription-matrix engine pulls in jax (parallel/query) — load
    # on first touch so `import geomesa_tpu.stream` stays jax-free
    "SubscriptionMatrix": ("geomesa_tpu.stream.matrix", "SubscriptionMatrix"),
    "HitBatch": ("geomesa_tpu.stream.matrix", "HitBatch"),
    "DeviceStreamScanner": (
        "geomesa_tpu.stream.pipeline", "DeviceStreamScanner"),
    "SubscriptionHub": ("geomesa_tpu.stream.pipeline", "SubscriptionHub"),
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(target[0]), target[1])
