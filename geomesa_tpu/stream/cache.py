"""Live feature cache with spatial index and event-time expiry.

Role parity: ``geomesa-kafka/.../kafka/index/KafkaFeatureCache.scala`` +
``FeatureStateFactory.scala`` (SURVEY.md §2.10): fid → latest feature state,
a local spatial index over current positions, and event-time expiry that drops
features older than a configured age.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Iterator

from geomesa_tpu.schema.sft import FeatureType
from geomesa_tpu.utils.spatial_index import SizeSeparatedBucketIndex, SpatialIndex

__all__ = ["FeatureState", "FeatureCache"]


@dataclass
class FeatureState:
    fid: str
    record: dict
    ts: int  # event time, epoch millis
    bounds: tuple[float, float, float, float] | None


class FeatureCache:
    """Thread-safe: all mutators and readers serialize on one RLock, so the
    threaded consumer group (``KafkaCacheLoader`` role) and concurrent
    queries share it without torn state."""

    def __init__(
        self,
        sft: FeatureType,
        expiry_ms: int | None = None,
        index: SpatialIndex | None = None,
    ):
        self.sft = sft
        self.expiry_ms = expiry_ms
        self.index = index if index is not None else SizeSeparatedBucketIndex()
        self._states: dict[str, FeatureState] = {}
        self._lock = threading.RLock()
        # monotonic mutation counter (the lambda-tier analog of
        # DeltaTier.version): every put/delete/clear/expire bumps it, so a
        # warm-path cache layered over the hot tier (the GeoBlocks query
        # cache validating a lambda-store aggregate) can prove its cached
        # answer predates no hot mutation — a stale stamp can only MISS
        self._version = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def put(self, fid: str, record: dict, ts: int) -> None:
        """Upsert: last write (by arrival order, like the reference) wins."""
        with self._lock:
            self._version += 1
            old = self._states.get(fid)
            if old is not None and old.bounds is not None:
                self.index.remove(old.bounds, fid)
            geom = record.get(self.sft.geom_field) if self.sft.geom_field else None
            bounds = geom.bbox if geom is not None else None
            state = FeatureState(fid, record, ts, bounds)
            self._states[fid] = state
            if bounds is not None:
                self.index.insert(bounds, fid, state)

    def delete(self, fid: str) -> None:
        with self._lock:
            self._version += 1
            old = self._states.pop(fid, None)
            if old is not None and old.bounds is not None:
                self.index.remove(old.bounds, fid)

    def remove_if_ts(self, fid: str, ts: int) -> bool:
        """Delete ``fid`` only if its event time still equals ``ts`` — the
        persister's compare-and-remove, so an update racing a persist never
        gets dropped (the newer state stays hot)."""
        with self._lock:
            s = self._states.get(fid)
            if s is None or s.ts != ts:
                return False
            self.delete(fid)
            return True

    def clear(self) -> None:
        with self._lock:
            self._version += 1
            self._states.clear()
            self.index.clear()

    def expire(self, now_ms: int) -> int:
        """Drop features whose event time is older than the expiry window."""
        if self.expiry_ms is None:
            return 0
        with self._lock:
            cutoff = now_ms - self.expiry_ms
            stale = [fid for fid, s in self._states.items() if s.ts < cutoff]
            for fid in stale:
                self.delete(fid)
            return len(stale)

    def expired_states(
        self, now_ms: int, age_ms: int | None = None
    ) -> list[FeatureState]:
        """Snapshot of states older than ``age_ms`` (default: the expiry
        window) WITHOUT removing them — the lambda persister reads these,
        lands them in the cold store, then :meth:`remove_if_ts` each
        (``DataStorePersistence.scala:161`` role)."""
        age = age_ms if age_ms is not None else self.expiry_ms
        if age is None:
            return []
        with self._lock:
            cutoff = now_ms - age
            return [s for s in self._states.values() if s.ts < cutoff]

    def get(self, fid: str) -> FeatureState | None:
        with self._lock:
            return self._states.get(fid)

    def size(self) -> int:
        with self._lock:
            return len(self._states)

    def states(self) -> Iterator[FeatureState]:
        with self._lock:
            return iter(list(self._states.values()))

    def query_bbox(self, bounds) -> Iterator[FeatureState]:
        """Candidate states whose envelope bucket overlaps ``bounds``."""
        with self._lock:
            return iter(list(self.index.query(bounds)))
