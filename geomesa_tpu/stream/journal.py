"""Durable file-journal message bus: the streaming tier's cross-process /
crash-survival transport.

Role parity: the reference's streaming datastore rides an EXTERNAL broker —
messages survive writer crashes and are consumed from other processes/hosts
(``geomesa-kafka/.../data/KafkaDataStore.scala:52``; offsets via
``ZookeeperOffsetManager.scala:160``). The in-process
:class:`~geomesa_tpu.stream.datastore.MessageBus` dies with the process;
``JournalBus`` keeps the SAME bus interface (``publish``/``poll``/
``subscribe``/``end_offset``) over an append-only length-prefixed log per
topic on a shared filesystem.

Crash safety uses a COMMIT OFFSET sidecar per topic (the Zookeeper-offset
role collapsed to a file): readers only parse bytes below the committed
size, and a writer — under the append lock — truncates any torn bytes a
killed predecessor left past the commit before appending. A reader can
therefore never misframe the stream, and a writer restart loses at most
the single record whose commit never landed:

- **Durable**: the record append and the commit-offset update happen under
  an advisory ``fcntl`` lock; ``fsync=True`` forces both to stable storage
  per publish.
- **Cross-process**: appends serialize via the lock; readers tail the
  committed prefix independently, each building its own per-partition
  index (the partition comes from the recorded key hash, so every reader
  agrees on assignment regardless of when it attached).
- **Restartable**: a writer that crashes and reopens repairs the tail and
  continues; readers see a contiguous, gap-free, duplicate-free log.

Format per record: ``<u32 payload_len><u8 barrier><i64 key_hash><payload>``.
A barrier record (Clear) belongs to EVERY partition, matching the
in-process bus's rendezvous semantics.
"""

from __future__ import annotations

import errno
import fcntl
import os
import struct
import threading
import zlib
from typing import Callable

__all__ = ["JournalBus"]

_HEADER = struct.Struct("<IBq")
_COMMIT = struct.Struct("<Q")


def _key_hash(key: str) -> int:
    """Stable across processes (``hash()`` is salted per interpreter)."""
    return zlib.crc32(key.encode("utf-8")) if key else 0


class JournalBus:
    """Append-only file journal per topic with the MessageBus interface."""

    def __init__(self, root: str, partitions: int = 4, fsync: bool = False,
                 poll_interval_s: float = 0.01, idle_max_s: float = 0.1):
        self.root = root
        self.partitions = partitions
        self.fsync = fsync
        self.poll_interval_s = poll_interval_s
        self.idle_max_s = idle_max_s  # adaptive idle-backoff cap (_tail_loop)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.RLock()
        # reader-side state per topic: committed-scan position, per-partition
        # payload index, and the total-order log feeding push subscribers —
        # all grown INCREMENTALLY (one pass per new committed byte)
        self._scan_pos: dict[str, int] = {}
        self._plogs: dict[str, list[list[bytes]]] = {}
        self._pbase: dict[str, list[int]] = {}  # trimmed-prefix offsets
        # total-order log: only the not-yet-dispatched window stays in
        # memory (_tbase + len(_tlogs) == _tcount always); poll-only
        # readers keep it empty
        self._tlogs: dict[str, list[bytes]] = {}
        self._tbase: dict[str, int] = {}
        self._tcount: dict[str, int] = {}
        self._subscribers: dict[str, list[Callable[[bytes], None]]] = {}
        self._sub_offsets: dict[str, int] = {}  # tailer dispatch cursor
        # dispatched-THROUGH cursor: advances only after every subscriber
        # callback for a batch has returned (unlike _sub_offsets, which
        # advances when the batch is claimed) — the tail_lag()/drain
        # quiescence signal
        self._dispatched: dict[str, int] = {}
        self._tailer: threading.Thread | None = None
        self._stop = threading.Event()
        self._migrated: set[tuple[str, str]] = set()
        # standing-query hubs (subscribe_query): the shared HubRegistry
        # (stream/pipeline.py, jax-free at import) owns the
        # subscribe-before-attach ordering and the leaf-lock discipline —
        # hub creation spawns a scan thread and bus registration may join
        # a draining tailer, so neither runs under the bus lock
        from geomesa_tpu.stream.pipeline import HubRegistry

        self._hubs = HubRegistry()

    # -- paths ---------------------------------------------------------------
    def _safe(self, topic: str) -> str:
        # unambiguous escaping: distinct topics can never share a log file
        # ("evt:1" vs "evt_1"). Fixed-width escapes ("_" + exactly 6 hex
        # digits, enough for any codepoint) keep the mapping injective —
        # variable-width "_%02x" would collide chr(0x1234) with
        # chr(0x12) + "34". "_" itself is escaped, so no ambiguity.
        return "".join(
            c if c.isalnum() or c in ".-" else f"_{ord(c):06x}"
            for c in topic
        )

    def _legacy_safe(self, topic: str) -> str:
        # the pre-injectivity variable-width escape ("_%02x"); kept only to
        # migrate journals written before the fixed-width scheme
        return "".join(
            c if c.isalnum() or c in ".-" else f"_{ord(c):02x}"
            for c in topic
        )

    def _migrate_legacy(self, topic: str, new: str, ext: str) -> None:
        # checked once per (topic, ext) per bus — path lookups are on every
        # publish/poll, so the steady state must not pay stat calls
        key = (topic, ext)
        if key in self._migrated:
            return
        self._migrated.add(key)
        legacy = os.path.join(
            self.root, f"{self._legacy_safe(topic)}{ext}"
        )
        if legacy != new and not os.path.exists(new) and os.path.exists(legacy):
            try:  # atomic on one filesystem; a racing process's rename wins
                os.rename(legacy, new)
            except OSError:
                pass

    def _log_path(self, topic: str) -> str:
        p = os.path.join(self.root, f"{self._safe(topic)}.log")
        self._migrate_legacy(topic, p, ".log")
        return p

    def _commit_path(self, topic: str) -> str:
        p = os.path.join(self.root, f"{self._safe(topic)}.commit")
        self._migrate_legacy(topic, p, ".commit")
        return p

    def _read_commit(self, topic: str) -> int | None:
        """Committed byte offset, or None when the sidecar is missing or
        unreadable — callers must NOT treat None as 0: truncating a
        non-empty log because its sidecar was lost would destroy committed
        history (the log, not the sidecar, is the source of truth then)."""
        try:
            with open(self._commit_path(topic), "rb") as f:
                raw = f.read(_COMMIT.size)
            if len(raw) == _COMMIT.size:
                return _COMMIT.unpack(raw)[0]
        except OSError:
            pass
        return None

    def _scan_framed_prefix(self, topic: str, size: int) -> int:
        """Longest well-framed byte prefix of the log — the commit-offset
        recovery path when the sidecar is lost."""
        try:
            with open(self._log_path(topic), "rb") as f:
                buf = f.read(size)
        except OSError:
            return 0
        off = 0
        while len(buf) - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > len(buf):
                break
            off = end
        return off

    def _write_commit(self, topic: str, value: int) -> None:
        """Atomic sidecar update (write-temp + rename): lock-free readers
        can never observe a torn 8-byte value."""
        path = self._commit_path(topic)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            os.write(fd, _COMMIT.pack(value))
            if self.fsync:
                os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)

    def create_topic(self, topic: str) -> None:
        path = self._log_path(topic)
        if not os.path.exists(path):
            open(path, "ab").close()
        with self._lock:
            self._plogs.setdefault(
                topic, [[] for _ in range(self.partitions)]
            )
            self._pbase.setdefault(topic, [0] * self.partitions)
            self._tlogs.setdefault(topic, [])
            self._tbase.setdefault(topic, 0)
            self._tcount.setdefault(topic, 0)
            self._scan_pos.setdefault(topic, 0)

    # -- write side ----------------------------------------------------------
    def publish(self, topic: str, key: str, data: bytes,
                barrier: bool = False) -> None:
        from geomesa_tpu import obs

        with obs.span("journal.publish", topic=topic, bytes=len(data)):
            self._publish(topic, key, data, barrier)

    def _publish(self, topic: str, key: str, data: bytes,
                 barrier: bool = False) -> None:
        self.create_topic(topic)
        rec = _HEADER.pack(len(data), 1 if barrier else 0, _key_hash(key)) + data
        path = self._log_path(topic)
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                    break
                except OSError as e:  # pragma: no cover — EINTR retry
                    if e.errno != errno.EINTR:
                        raise
            committed = self._read_commit(topic)
            size = os.fstat(fd).st_size
            if committed is None:
                # lost sidecar: recover from the log itself (never assume
                # 0 — that would truncate committed history away)
                committed = self._scan_framed_prefix(topic, size)
            if size > committed:
                # torn bytes from a writer killed mid-append: repair under
                # the lock so the new record starts at the commit boundary
                os.ftruncate(fd, committed)
                size = committed
            os.lseek(fd, 0, os.SEEK_END)
            os.write(fd, rec)
            if self.fsync:
                os.fsync(fd)
            # commit AFTER the record is fully (and, with fsync, durably)
            # in the log — readers never parse past this offset
            self._write_commit(topic, size + len(rec))
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- read side -----------------------------------------------------------
    def _refresh(self, topic: str) -> None:
        """Parse newly COMMITTED bytes into the per-partition and
        total-order indexes — incremental, one pass per new byte."""
        self.create_topic(topic)
        with self._lock:
            pos = self._scan_pos[topic]
            committed = self._read_commit(topic)
            if committed is None:
                # lost sidecar: fall back to the longest well-framed prefix
                try:
                    size = os.path.getsize(self._log_path(topic))
                except OSError:
                    return
                committed = self._scan_framed_prefix(topic, size)
            if committed <= pos:
                return
            try:
                # the bus lock IS this read's serialization point: scan
                # position and the indexes it feeds must advance atomically
                # with the bytes parsed, and the read is bounded by the
                # committed offset (page-cache-hot in the steady state)
                # tpurace: disable-next-line=R003
                with open(self._log_path(topic), "rb") as f:
                    f.seek(pos)
                    buf = f.read(committed - pos)
            except OSError:
                return
            plog = self._plogs[topic]
            tlog = self._tlogs[topic]
            has_subs = bool(self._subscribers.get(topic))
            off = 0
            while len(buf) - off >= _HEADER.size:
                ln, barrier, kh = _HEADER.unpack_from(buf, off)
                end = off + _HEADER.size + ln
                if end > len(buf):
                    break  # commit mid-record cannot happen; defensive
                payload = buf[off + _HEADER.size : end]
                if barrier:
                    for p in range(self.partitions):
                        plog[p].append(payload)
                else:
                    plog[kh % self.partitions].append(payload)
                # total-order window only buffers for push subscribers;
                # poll-only readers keep it empty (bounded memory)
                if has_subs:
                    tlog.append(payload)
                else:
                    self._tbase[topic] += 1
                self._tcount[topic] += 1
                off = end
            self._scan_pos[topic] = pos + off

    def poll(self, topic: str, partition: int, offset: int, max_n: int = 256):
        """Messages [offset, offset+max_n) of one partition's log. Offsets
        below a trimmed prefix (see :meth:`trim`) yield from the first
        retained message."""
        from geomesa_tpu import obs

        with obs.span("journal.poll", topic=topic, partition=partition):
            self._refresh(topic)
        with self._lock:
            base = self._pbase[topic][partition]
            log = self._plogs[topic][partition]
            lo = max(offset - base, 0)
            return log[lo : lo + max_n]

    def end_offset(self, topic: str, partition: int) -> int:
        self._refresh(topic)
        with self._lock:
            return self._pbase[topic][partition] + len(
                self._plogs[topic][partition]
            )

    def topic_size(self, topic: str) -> int:
        self._refresh(topic)
        with self._lock:
            return self._tcount.get(topic, 0)

    def tail_lag(self, topic: str) -> int:
        """Committed records the background tailer has NOT yet delivered to
        every push subscriber — the feed-side quiescence signal
        (``tail_lag() == 0`` means all published records have been handed
        to all subscriber callbacks AND those callbacks returned). Topics
        with no push subscribers report 0 (nothing to dispatch)."""
        self._refresh(topic)
        with self._lock:
            if topic not in self._sub_offsets:
                return 0
            return max(
                self._tcount.get(topic, 0) - self._dispatched.get(topic, 0), 0
            )

    def trim(self, topic: str, partition: int, upto: int) -> int:
        """Release THIS READER's memory for partition messages below
        ``upto`` (a consumed offset). The on-disk journal is untouched —
        durability and late-attaching readers are unaffected; only this
        process's replay ability for the trimmed prefix goes away. A
        long-running consumer calls this with its applied offset to bound
        resident memory. Returns the messages released."""
        self.create_topic(topic)
        with self._lock:
            base = self._pbase[topic][partition]
            drop = min(max(upto - base, 0), len(self._plogs[topic][partition]))
            if drop:
                del self._plogs[topic][partition][:drop]
                self._pbase[topic][partition] = base + drop
            return drop

    # -- push subscribers (tailer thread dispatches in total order) ----------
    def subscribe(self, topic: str, callback: Callable[[bytes], None]) -> None:
        """Register a consumer: the full backlog (offset 0) replays to the
        NEW callback first, then the background tailer pushes new records.

        Replay and registration happen under the bus lock — mirroring the
        in-process bus's no-gap no-reorder contract — so the tailer can
        neither double-deliver the backlog nor slip a record between
        replay and registration. Already-dispatched records the tailer
        trimmed from memory replay from the journal FILE.

        Stop/restart is a guarded state transition shared with
        :meth:`close`: a tailer is bound for life to the stop event
        current at its creation, the event is only ever swapped for a
        fresh one when ``self._tailer is None`` (which in turn is only
        set after the old thread is CONFIRMED dead), and a subscribe that
        lands mid-close first joins the draining tailer outside the lock.
        Without the full transition, a subscribe racing close could
        register against a dying tailer (push delivery silently never
        resumes) or leave a stale tailer running against the old event
        next to a fresh one.
        """
        self.create_topic(topic)
        while True:
            with self._lock:
                # close() in flight: _stop is set but its tailer has not
                # been confirmed dead yet — restart only after it is
                stale = self._tailer if self._stop.is_set() else None
                if stale is None or stale is threading.current_thread():
                    # the second arm: a callback ON the dying tailer
                    # re-subscribing mid-close cannot join itself —
                    # register now; the tailer restart happens on the
                    # next subscribe after close() completes (the normal
                    # bus-reuse path picks this callback up with it)
                    self._subscribe_locked(topic, callback)
                    return
            stale.join(timeout=5.0)
            with self._lock:
                if self._tailer is stale and not stale.is_alive():
                    self._tailer = None

    def _subscribe_locked(self, topic: str,
                          callback: Callable[[bytes], None]) -> None:
        """Replay + register + (re)start the tailer; caller holds the bus
        lock and has established that no stopping tailer remains."""
        self._refresh(topic)
        total = self._tcount[topic]
        first = topic not in self._sub_offsets
        # the tailer owns [cursor:] for ALL subscribers (including this
        # one); the new callback catches up on [0:cursor] here — from
        # disk for any part no longer buffered in memory. The FIRST
        # subscriber catches up on the whole history (records parsed
        # before any subscriber existed were never buffered).
        cursor = total if first else self._sub_offsets[topic]
        tbase = self._tbase[topic]
        if cursor > 0:
            if tbase > 0:
                backlog = self._disk_payloads(topic, cursor)
            else:
                backlog = self._tlogs[topic][:cursor]
            for data in backlog:
                callback(data)
        if first:
            self._sub_offsets[topic] = total
            self._dispatched[topic] = total  # replay above was synchronous
            del self._tlogs[topic][: max(total - tbase, 0)]
            self._tbase[topic] = total
        self._subscribers.setdefault(topic, []).append(callback)
        if self._tailer is None:
            if self._stop.is_set():
                self._stop = threading.Event()  # bus reused after close
            self._tailer = threading.Thread(
                target=self._tail_loop, daemon=True,
                name="geomesa-journal-tailer",
            )
            self._tailer.start()

    def unsubscribe(self, topic: str, callback: Callable[[bytes], None]) -> bool:
        """Remove a push subscriber; missing registrations are a no-op.
        The tailer keeps advancing the topic cursor for any remaining
        subscribers (and stays dispatch-idle on the topic otherwise) —
        detaching never rewinds or re-delivers."""
        with self._lock:
            subs = self._subscribers.get(topic, [])
            try:
                subs.remove(callback)
                return True
            except ValueError:
                return False

    def _disk_payloads(self, topic: str, first_n: int) -> list[bytes]:
        """First ``first_n`` payloads re-read from the committed journal
        prefix (late-subscriber replay after the in-memory log trimmed)."""
        committed = self._read_commit(topic)
        try:
            size = os.path.getsize(self._log_path(topic))
        except OSError:
            return []
        if committed is None:
            committed = self._scan_framed_prefix(topic, size)
        try:
            with open(self._log_path(topic), "rb") as f:
                buf = f.read(min(committed, size))
        except OSError:
            return []
        out: list[bytes] = []
        off = 0
        while len(out) < first_n and len(buf) - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > len(buf):
                break
            out.append(buf[off + _HEADER.size : end])
            off = end
        return out

    def total_poll(self, topic: str, offset: int, max_n: int = 256):
        """Total-order payloads ``[offset, offset+max_n)`` re-read from the
        committed journal prefix — the message-offset-addressed form
        (O(offset) per call: the log is re-framed from byte 0). Long-lived
        remote tails use :meth:`total_poll_bytes` instead, which reads
        only new bytes."""
        return self._disk_payloads(topic, offset + max_n)[offset:]

    def total_poll_bytes(self, topic: str, cursor: int,
                         max_bytes: int = 1 << 22):
        """Total-order tail by BYTE cursor: payloads framed from committed
        byte ``cursor``, plus the next cursor — each call reads only the
        new bytes, so a long-lived remote subscriber is O(new data), not
        O(journal) (the ``/api/journal/<topic>/tpoll?cursor=`` path).
        ``cursor`` is an opaque token: start at 0, always pass back the
        returned value (it only ever lands on record boundaries)."""
        committed = self._read_commit(topic)
        try:
            size = os.path.getsize(self._log_path(topic))
        except OSError:
            return [], cursor
        if committed is None:
            committed = self._scan_framed_prefix(topic, size)
        committed = min(committed, size)
        if cursor >= committed:
            return [], cursor
        try:
            with open(self._log_path(topic), "rb") as f:
                f.seek(cursor)
                buf = f.read(min(committed - cursor, max_bytes))
        except OSError:
            return [], cursor
        out: list[bytes] = []
        off = 0
        while len(buf) - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > len(buf):
                break  # record straddles the read window: next call gets it
            out.append(buf[off + _HEADER.size : end])
            off = end
        return out, cursor + off

    def _tail_loop(self) -> None:
        from geomesa_tpu.obs import jaxmon, trace as _trace
        from geomesa_tpu.resilience.policy import RetryPolicy
        from geomesa_tpu.stream import telemetry

        stop = self._stop
        errors = jaxmon.registry().counter("stream.callback_errors")
        # decorrelated-jitter idle backoff (reset on traffic): a quiet bus
        # polls ~10x/s instead of spinning at poll_interval_s
        idle = RetryPolicy(base_delay_s=self.poll_interval_s,
                           max_delay_s=self.idle_max_s)
        delay: float | None = None
        # ONE stable root span per tailer session (the local-bus analog of
        # RemoteJournal's journal.tail session): callback failures attach
        # as span EVENTS so a broken consumer shows up in flight records
        # instead of vanishing into a swallowed except. Managed manually —
        # tracing may come on mid-session.
        session = _trace.span("journal.tail", bus=self.root)
        session.__enter__()
        try:
            while not stop.is_set():
                if session is _trace.NOOP and _trace.enabled():
                    session = _trace.span("journal.tail", bus=self.root)
                    session.__enter__()
                dispatched = 0
                with self._lock:
                    topics = list(self._subscribers)
                for topic in topics:
                    self._refresh(topic)
                    with self._lock:
                        tbase = self._tbase[topic]
                        log = self._tlogs[topic]
                        start = self._sub_offsets.get(topic, 0)
                        batch = log[max(start - tbase, 0):]
                        subs = list(self._subscribers.get(topic, []))
                        end = tbase + len(log)
                        self._sub_offsets[topic] = end
                        # dispatched records leave memory (steady-state
                        # bound); late subscribers replay them from disk
                        del log[: max(start - tbase, 0) + len(batch)]
                        self._tbase[topic] = end
                    for data in batch:
                        for cb in subs:
                            try:
                                cb(data)
                            except Exception as e:  # noqa: BLE001
                                # one bad consumer must not kill delivery
                                # for every topic; the record stays
                                # consumed (at-most-once for the failing
                                # callback) — but the failure is COUNTED
                                # and lands on the session span, never
                                # silently swallowed
                                errors.inc()
                                telemetry.note_callback_error(topic)
                                if isinstance(session, _trace.Span):
                                    session.event(
                                        "callback_error", topic=topic,
                                        error=type(e).__name__,
                                    )
                        dispatched += 1
                    if batch:
                        with self._lock:
                            # dispatched-THROUGH only moves once every
                            # callback has seen the batch (tail_lag's
                            # happens-before edge)
                            self._dispatched[topic] = end
                        telemetry.note_poll(topic, len(batch), 0.0,
                                            loop="tailer")
                    if isinstance(session, _trace.Span):
                        # bound the long-lived session tree (remote-journal
                        # pattern: single-writer trim, exporters snapshot)
                        if len(session.events) > 128:
                            del session.events[:-128]
                if dispatched == 0:
                    delay = idle.next_delay(delay)
                    for topic in topics:
                        telemetry.note_poll(topic, 0, delay,
                                            loop="tailer")
                    stop.wait(delay)
                else:
                    delay = None
        finally:
            session.__exit__(None, None, None)

    # -- standing queries (fused device scan) --------------------------------
    def subscribe_query(self, topic: str, serializer, predicate,
                        callback, **hub_cfg) -> int:
        """Standing-query subscription over a journal topic: instead of a
        per-row host callback, appended records batch through the
        :class:`~geomesa_tpu.stream.pipeline.SubscriptionHub` — decoded
        with ``serializer`` (which carries the feature type), scanned as
        one fused ``(rows × queries)`` device pass per chunk, with
        per-subscription hit deliveries (docs/streaming.md). Returns the
        subscription id (``unsubscribe_query`` to remove)."""
        from geomesa_tpu.stream.pipeline import SubscriptionHub

        def attach(hub):
            self.subscribe(topic, hub.ingest)
            # detach handle: close_all stops a reused bus from feeding
            # the closed scanner after its tailer restarts
            return lambda: self.unsubscribe(topic, hub.ingest)

        return self._hubs.subscribe(
            topic, predicate, callback,
            make_hub=lambda: SubscriptionHub(
                serializer.sft, serializer, topic=topic, **hub_cfg
            ),
            attach=attach,
            cfg=hub_cfg,
        )

    def unsubscribe_query(self, topic: str, sid: int) -> bool:
        return self._hubs.unsubscribe(topic, sid)

    def query_hub(self, topic: str):
        """The topic's SubscriptionHub (None before any subscribe_query)."""
        return self._hubs.get(topic)

    def close(self) -> None:
        """Stop the tailer (idempotent; deterministic join). See
        :meth:`subscribe` for the stop/restart state transition."""
        self._hubs.close_all()
        # snapshot under the lock (subscribe swaps _stop/_tailer under it);
        # join OUTSIDE it — the tailer takes the lock per topic and joining
        # while holding it would deadlock
        with self._lock:
            self._stop.set()
            tailer = self._tailer
        if tailer is not None:
            tailer.join(timeout=5.0)
            with self._lock:
                # only a CONFIRMED-dead tailer clears the slot: a wedged
                # thread must keep blocking restarts (subscribe joins it)
                # rather than end up running beside a fresh tailer
                if self._tailer is tailer and not tailer.is_alive():
                    self._tailer = None
