"""Durable file-journal message bus: the streaming tier's cross-process /
crash-survival transport.

Role parity: the reference's streaming datastore rides an EXTERNAL broker —
messages survive writer crashes and are consumed from other processes/hosts
(``geomesa-kafka/.../data/KafkaDataStore.scala:52``; offsets via
``ZookeeperOffsetManager.scala:160``). The in-process
:class:`~geomesa_tpu.stream.datastore.MessageBus` dies with the process;
``JournalBus`` keeps the SAME bus interface (``publish``/``poll``/
``subscribe``/``end_offset``) over an append-only length-prefixed log per
topic on a shared filesystem.

Crash safety uses a COMMIT OFFSET sidecar per topic (the Zookeeper-offset
role collapsed to a file): readers only parse bytes below the committed
size, and a writer — under the append lock — truncates any torn bytes a
killed predecessor left past the commit before appending. A reader can
therefore never misframe the stream, and a writer restart loses at most
the single record whose commit never landed:

- **Durable**: the record append and the commit-offset update happen under
  an advisory ``fcntl`` lock; ``fsync=True`` forces both to stable storage
  per publish.
- **Cross-process**: appends serialize via the lock; readers tail the
  committed prefix independently, each building its own per-partition
  index (the partition comes from the recorded key hash, so every reader
  agrees on assignment regardless of when it attached).
- **Restartable**: a writer that crashes and reopens repairs the tail and
  continues; readers see a contiguous, gap-free, duplicate-free log.

Format per record: ``<u32 payload_len><u8 barrier><i64 key_hash><payload>``.
A barrier record (Clear) belongs to EVERY partition, matching the
in-process bus's rendezvous semantics.
"""

from __future__ import annotations

import errno
import fcntl
import os
import struct
import threading
import zlib
from typing import Callable

__all__ = ["JournalBus"]

_HEADER = struct.Struct("<IBq")
_COMMIT = struct.Struct("<Q")


def _key_hash(key: str) -> int:
    """Stable across processes (``hash()`` is salted per interpreter)."""
    return zlib.crc32(key.encode("utf-8")) if key else 0


class JournalBus:
    """Append-only file journal per topic with the MessageBus interface."""

    def __init__(self, root: str, partitions: int = 4, fsync: bool = False,
                 poll_interval_s: float = 0.01):
        self.root = root
        self.partitions = partitions
        self.fsync = fsync
        self.poll_interval_s = poll_interval_s
        os.makedirs(root, exist_ok=True)
        self._lock = threading.RLock()
        # reader-side state per topic: committed-scan position, per-partition
        # payload index, and the total-order log feeding push subscribers —
        # all grown INCREMENTALLY (one pass per new committed byte)
        self._scan_pos: dict[str, int] = {}
        self._plogs: dict[str, list[list[bytes]]] = {}
        self._tlogs: dict[str, list[bytes]] = {}
        self._subscribers: dict[str, list[Callable[[bytes], None]]] = {}
        self._sub_offsets: dict[str, int] = {}  # tailer dispatch cursor
        self._tailer: threading.Thread | None = None
        self._stop = threading.Event()

    # -- paths ---------------------------------------------------------------
    def _safe(self, topic: str) -> str:
        return "".join(c if c.isalnum() or c in "._-" else "_" for c in topic)

    def _log_path(self, topic: str) -> str:
        return os.path.join(self.root, f"{self._safe(topic)}.log")

    def _commit_path(self, topic: str) -> str:
        return os.path.join(self.root, f"{self._safe(topic)}.commit")

    def _read_commit(self, topic: str) -> int:
        try:
            with open(self._commit_path(topic), "rb") as f:
                raw = f.read(_COMMIT.size)
            if len(raw) == _COMMIT.size:
                return _COMMIT.unpack(raw)[0]
        except OSError:
            pass
        return 0

    def create_topic(self, topic: str) -> None:
        path = self._log_path(topic)
        if not os.path.exists(path):
            open(path, "ab").close()
        with self._lock:
            self._plogs.setdefault(
                topic, [[] for _ in range(self.partitions)]
            )
            self._tlogs.setdefault(topic, [])
            self._scan_pos.setdefault(topic, 0)

    # -- write side ----------------------------------------------------------
    def publish(self, topic: str, key: str, data: bytes,
                barrier: bool = False) -> None:
        self.create_topic(topic)
        rec = _HEADER.pack(len(data), 1 if barrier else 0, _key_hash(key)) + data
        path = self._log_path(topic)
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                    break
                except OSError as e:  # pragma: no cover — EINTR retry
                    if e.errno != errno.EINTR:
                        raise
            committed = self._read_commit(topic)
            size = os.fstat(fd).st_size
            if size > committed:
                # torn bytes from a writer killed mid-append: repair under
                # the lock so the new record starts at the commit boundary
                os.ftruncate(fd, committed)
                size = committed
            os.lseek(fd, 0, os.SEEK_END)
            os.write(fd, rec)
            if self.fsync:
                os.fsync(fd)
            # commit AFTER the record is fully (and, with fsync, durably)
            # in the log — readers never parse past this offset
            cfd = os.open(
                self._commit_path(topic), os.O_CREAT | os.O_WRONLY, 0o644
            )
            try:
                os.write(cfd, _COMMIT.pack(size + len(rec)))
                if self.fsync:
                    os.fsync(cfd)
            finally:
                os.close(cfd)
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- read side -----------------------------------------------------------
    def _refresh(self, topic: str) -> None:
        """Parse newly COMMITTED bytes into the per-partition and
        total-order indexes — incremental, one pass per new byte."""
        self.create_topic(topic)
        with self._lock:
            pos = self._scan_pos[topic]
            committed = self._read_commit(topic)
            if committed <= pos:
                return
            try:
                with open(self._log_path(topic), "rb") as f:
                    f.seek(pos)
                    buf = f.read(committed - pos)
            except OSError:
                return
            plog = self._plogs[topic]
            tlog = self._tlogs[topic]
            off = 0
            while len(buf) - off >= _HEADER.size:
                ln, barrier, kh = _HEADER.unpack_from(buf, off)
                end = off + _HEADER.size + ln
                if end > len(buf):
                    break  # commit mid-record cannot happen; defensive
                payload = buf[off + _HEADER.size : end]
                if barrier:
                    for p in range(self.partitions):
                        plog[p].append(payload)
                else:
                    plog[kh % self.partitions].append(payload)
                tlog.append(payload)
                off = end
            self._scan_pos[topic] = pos + off

    def poll(self, topic: str, partition: int, offset: int, max_n: int = 256):
        """Messages [offset, offset+max_n) of one partition's log."""
        self._refresh(topic)
        with self._lock:
            log = self._plogs[topic][partition]
            return log[offset : offset + max_n]

    def end_offset(self, topic: str, partition: int) -> int:
        self._refresh(topic)
        with self._lock:
            return len(self._plogs[topic][partition])

    def topic_size(self, topic: str) -> int:
        self._refresh(topic)
        with self._lock:
            return len(self._tlogs.get(topic, []))

    # -- push subscribers (tailer thread dispatches in total order) ----------
    def subscribe(self, topic: str, callback: Callable[[bytes], None]) -> None:
        """Register a consumer: the full backlog (offset 0) replays to the
        NEW callback first, then the background tailer pushes new records.

        Replay and registration happen under the bus lock — mirroring the
        in-process bus's no-gap no-reorder contract — so the tailer can
        neither double-deliver the backlog nor slip a record between
        replay and registration.
        """
        self.create_topic(topic)
        with self._lock:
            self._refresh(topic)
            backlog = list(self._tlogs[topic])
            cursor = self._sub_offsets.setdefault(topic, 0)
            # the tailer owns [cursor:] for ALL subscribers (including this
            # one); the new callback catches up on [0:cursor] here
            for data in backlog[:cursor]:
                callback(data)
            self._subscribers.setdefault(topic, []).append(callback)
            if self._tailer is None:
                self._tailer = threading.Thread(
                    target=self._tail_loop, daemon=True,
                    name="geomesa-journal-tailer",
                )
                self._tailer.start()

    def _tail_loop(self) -> None:
        while not self._stop.is_set():
            dispatched = 0
            with self._lock:
                topics = list(self._subscribers)
            for topic in topics:
                self._refresh(topic)
                with self._lock:
                    log = self._tlogs[topic]
                    start = self._sub_offsets.get(topic, 0)
                    batch = log[start:]
                    subs = list(self._subscribers.get(topic, []))
                    self._sub_offsets[topic] = len(log)
                for data in batch:
                    for cb in subs:
                        try:
                            cb(data)
                        except Exception:  # noqa: BLE001 — one bad consumer
                            # must not kill delivery for every topic; the
                            # record is consumed (at-most-once for the
                            # failing callback, like the in-process bus's
                            # synchronous dispatch raising to the publisher)
                            pass
                    dispatched += 1
            if dispatched == 0:
                self._stop.wait(self.poll_interval_s)

    def close(self) -> None:
        self._stop.set()
        if self._tailer is not None:
            self._tailer.join(timeout=5.0)
            self._tailer = None
