"""Durable file-journal message bus: the streaming tier's cross-process /
crash-survival transport.

Role parity: the reference's streaming datastore rides an EXTERNAL broker —
messages survive writer crashes and are consumed from other processes/hosts
(``geomesa-kafka/.../data/KafkaDataStore.scala:52``; offsets via
``ZookeeperOffsetManager.scala:160``). The in-process
:class:`~geomesa_tpu.stream.datastore.MessageBus` dies with the process;
``JournalBus`` keeps the SAME bus interface (``publish``/``poll``/
``subscribe``/``end_offset``) over an append-only length-prefixed log per
topic on a shared filesystem.

Crash safety uses a COMMIT OFFSET sidecar per topic (the Zookeeper-offset
role collapsed to a file): readers only parse bytes below the committed
size, and a writer — under the append lock — truncates any torn bytes a
killed predecessor left past the commit before appending. A reader can
therefore never misframe the stream, and a writer restart loses at most
the single record whose commit never landed:

- **Durable**: the record append and the commit-offset update happen under
  an advisory ``fcntl`` lock; ``fsync=True`` forces both to stable storage
  per publish.
- **Cross-process**: appends serialize via the lock; readers tail the
  committed prefix independently, each building its own per-partition
  index (the partition comes from the recorded key hash, so every reader
  agrees on assignment regardless of when it attached).
- **Restartable**: a writer that crashes and reopens repairs the tail and
  continues; readers see a contiguous, gap-free, duplicate-free log.

Format per record: ``<u32 payload_len><u8 barrier><i64 key_hash><payload>``.
A barrier record (Clear) belongs to EVERY partition, matching the
in-process bus's rendezvous semantics.

Head truncation (:meth:`JournalBus.trim` with two arguments): the log head
can be durably dropped below a LOGICAL byte offset once a checkpoint (the
WAL manifest stamp, a consumer's applied offset) covers it, so neither the
durability WAL nor long-lived stream topics grow without bound. Trimmed
files carry a fixed header (``GMJL`` magic + base byte/record offsets);
logical offsets — commit sidecar values, ``total_poll_bytes`` cursors —
NEVER shift, and a reader whose cursor falls below the retained head gets
a typed :class:`TrimmedError`, never misframed bytes. Legacy headerless
logs read as base 0 and gain the header on their first trim.
"""

from __future__ import annotations

import errno
import fcntl
import os
import struct
import threading
import zlib
from typing import Callable

__all__ = ["JournalBus", "TrimmedError"]

_HEADER = struct.Struct("<IBq")
_COMMIT = struct.Struct("<Q")
# optional log-file header, present once a log has been head-trimmed:
# magic, format version, pad, base LOGICAL byte offset of the first
# retained byte, count of records wholly below it
_MAGIC = b"GMJL"
_FILEHDR = struct.Struct("<4sBxxxQQ")


class TrimmedError(RuntimeError):
    """A reader asked for journal bytes below the durably trimmed head.

    The retained log is intact — only history below the checkpointed trim
    point is gone. Callers restart from the current head (``cursor=0`` on
    :meth:`JournalBus.total_poll_bytes` resumes at the first retained
    record) or from their own checkpoint above it."""


def _parse_filehdr(buf: bytes) -> tuple[int, int, int]:
    """``(base_bytes, base_records, header_len)`` from a log file's first
    bytes; legacy headerless logs → ``(0, 0, 0)``."""
    if len(buf) >= _FILEHDR.size and buf[: len(_MAGIC)] == _MAGIC:
        _m, _v, base, brecs = _FILEHDR.unpack(buf[: _FILEHDR.size])
        return int(base), int(brecs), _FILEHDR.size
    return 0, 0, 0


def _key_hash(key: str) -> int:
    """Stable across processes (``hash()`` is salted per interpreter)."""
    return zlib.crc32(key.encode("utf-8")) if key else 0


def _unsafe_name(safe: str) -> str:
    """Inverse of :meth:`JournalBus._safe` (fixed-width ``_xxxxxx`` hex
    escapes) — topic discovery from on-disk file names."""
    out, i = [], 0
    while i < len(safe):
        c = safe[i]
        if c == "_" and i + 6 < len(safe):
            try:
                out.append(chr(int(safe[i + 1 : i + 7], 16)))
                i += 7
                continue
            except ValueError:
                pass
        out.append(c)
        i += 1
    return "".join(out)


class JournalBus:
    """Append-only file journal per topic with the MessageBus interface."""

    def __init__(self, root: str, partitions: int = 4, fsync: bool = False,
                 poll_interval_s: float = 0.01, idle_max_s: float = 0.1):
        self.root = root
        self.partitions = partitions
        self.fsync = fsync
        self.poll_interval_s = poll_interval_s
        self.idle_max_s = idle_max_s  # adaptive idle-backoff cap (_tail_loop)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.RLock()
        # reader-side state per topic: committed-scan position, per-partition
        # payload index, and the total-order log feeding push subscribers —
        # all grown INCREMENTALLY (one pass per new committed byte)
        self._scan_pos: dict[str, int] = {}
        self._plogs: dict[str, list[list[bytes]]] = {}
        self._pbase: dict[str, list[int]] = {}  # trimmed-prefix offsets
        # absolute record index where THIS process's scan began (the log's
        # base_records at first refresh): disk replay below it means some
        # other process head-trimmed under us → TrimmedError, never a
        # silently shortened backlog
        self._rec_base: dict[str, int] = {}
        # durable-trim tracking (enable_trim_tracking): per-record
        # (partition, logical_end_byte) metadata in total order, so a
        # checkpointed consumer's per-partition applied offsets map back
        # to a safe head-trim byte boundary (trim_applied)
        self._trim_track: set[str] = set()
        self._rec_meta: dict[str, list[tuple[int, int]]] = {}
        self._rec_meta_pcounts: dict[str, list[int]] = {}
        # pinned writers (pin_writer): an EXCLUSIVE long-lived appender —
        # the WAL, which owns its whole directory via the catalog lock —
        # keeps the log fd open and flocked across appends, with header
        # and commit offset cached, so the group-commit hot path is
        # write + sidecar flip instead of open/lock/read/close per flush.
        # _pin_mu serializes pinned appends with head-trims (a trim
        # replaces the inode and must re-pin).
        self._pin_mu = threading.Lock()
        self._pinned: dict[str, list] = {}  # topic -> [fd, base, hdr, committed]
        # total-order log: only the not-yet-dispatched window stays in
        # memory (_tbase + len(_tlogs) == _tcount always); poll-only
        # readers keep it empty
        self._tlogs: dict[str, list[bytes]] = {}
        self._tbase: dict[str, int] = {}
        self._tcount: dict[str, int] = {}
        self._subscribers: dict[str, list[Callable[[bytes], None]]] = {}
        self._sub_offsets: dict[str, int] = {}  # tailer dispatch cursor
        # dispatched-THROUGH cursor: advances only after every subscriber
        # callback for a batch has returned (unlike _sub_offsets, which
        # advances when the batch is claimed) — the tail_lag()/drain
        # quiescence signal
        self._dispatched: dict[str, int] = {}
        self._tailer: threading.Thread | None = None
        self._stop = threading.Event()
        self._migrated: set[tuple[str, str]] = set()
        # standing-query hubs (subscribe_query): the shared HubRegistry
        # (stream/pipeline.py, jax-free at import) owns the
        # subscribe-before-attach ordering and the leaf-lock discipline —
        # hub creation spawns a scan thread and bus registration may join
        # a draining tailer, so neither runs under the bus lock
        from geomesa_tpu.stream.pipeline import HubRegistry

        self._hubs = HubRegistry()

    # -- paths ---------------------------------------------------------------
    def _safe(self, topic: str) -> str:
        # unambiguous escaping: distinct topics can never share a log file
        # ("evt:1" vs "evt_1"). Fixed-width escapes ("_" + exactly 6 hex
        # digits, enough for any codepoint) keep the mapping injective —
        # variable-width "_%02x" would collide chr(0x1234) with
        # chr(0x12) + "34". "_" itself is escaped, so no ambiguity.
        return "".join(
            c if c.isalnum() or c in ".-" else f"_{ord(c):06x}"
            for c in topic
        )

    def _legacy_safe(self, topic: str) -> str:
        # the pre-injectivity variable-width escape ("_%02x"); kept only to
        # migrate journals written before the fixed-width scheme
        return "".join(
            c if c.isalnum() or c in ".-" else f"_{ord(c):02x}"
            for c in topic
        )

    def _migrate_legacy(self, topic: str, new: str, ext: str) -> None:
        # checked once per (topic, ext) per bus — path lookups are on every
        # publish/poll, so the steady state must not pay stat calls
        key = (topic, ext)
        if key in self._migrated:
            return
        self._migrated.add(key)
        legacy = os.path.join(
            self.root, f"{self._legacy_safe(topic)}{ext}"
        )
        if legacy != new and not os.path.exists(new) and os.path.exists(legacy):
            try:  # atomic on one filesystem; a racing process's rename wins
                os.rename(legacy, new)
            except OSError:
                pass

    def _log_path(self, topic: str) -> str:
        p = os.path.join(self.root, f"{self._safe(topic)}.log")
        self._migrate_legacy(topic, p, ".log")
        return p

    def _commit_path(self, topic: str) -> str:
        p = os.path.join(self.root, f"{self._safe(topic)}.commit")
        self._migrate_legacy(topic, p, ".commit")
        return p

    def _read_commit(self, topic: str) -> int | None:
        """Committed byte offset, or None when the sidecar is missing or
        unreadable — callers must NOT treat None as 0: truncating a
        non-empty log because its sidecar was lost would destroy committed
        history (the log, not the sidecar, is the source of truth then)."""
        try:
            with open(self._commit_path(topic), "rb") as f:
                raw = f.read(_COMMIT.size)
            if len(raw) == _COMMIT.size:
                return _COMMIT.unpack(raw)[0]
        except OSError:
            pass
        return None

    def _log_head(self, topic: str) -> tuple[int, int, int]:
        """``(base_bytes, base_records, header_len)`` of the topic's log
        file (all zero for legacy/missing logs)."""
        try:
            with open(self._log_path(topic), "rb") as f:
                return _parse_filehdr(f.read(_FILEHDR.size))
        except OSError:
            return 0, 0, 0

    def _scan_framed_prefix(self, topic: str, size: int | None = None) -> int:
        """Longest well-framed LOGICAL byte prefix of the log — the
        commit-offset recovery path when the sidecar is lost. ``size``
        optionally bounds the PHYSICAL bytes considered (a writer's
        fstat under the append lock)."""
        try:
            with open(self._log_path(topic), "rb") as f:
                buf = f.read(size) if size is not None else f.read()
        except OSError:
            return 0
        base, _brecs, hdrlen = _parse_filehdr(buf)
        off = hdrlen
        while len(buf) - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > len(buf):
                break
            off = end
        return base + (off - hdrlen)

    def _write_commit(self, topic: str, value: int,
                      fsync: bool | None = None) -> None:
        """Atomic sidecar update (write-temp + rename): lock-free readers
        can never observe a torn 8-byte value."""
        path = self._commit_path(topic)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            os.write(fd, _COMMIT.pack(value))
            if self.fsync if fsync is None else fsync:
                os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)

    def create_topic(self, topic: str) -> None:
        path = self._log_path(topic)
        if not os.path.exists(path):
            open(path, "ab").close()
        with self._lock:
            self._plogs.setdefault(
                topic, [[] for _ in range(self.partitions)]
            )
            self._pbase.setdefault(topic, [0] * self.partitions)
            self._tlogs.setdefault(topic, [])
            self._tbase.setdefault(topic, 0)
            self._tcount.setdefault(topic, 0)
            self._scan_pos.setdefault(topic, 0)

    # -- write side ----------------------------------------------------------
    def publish(self, topic: str, key: str, data: bytes,
                barrier: bool = False) -> None:
        from geomesa_tpu import obs

        with obs.span("journal.publish", topic=topic, bytes=len(data)):
            self._publish(topic, key, data, barrier)

    def _publish(self, topic: str, key: str, data: bytes,
                 barrier: bool = False) -> None:
        self._append_records(topic, [(key, data, barrier)], fsync=self.fsync)

    def publish_many(self, topic: str, records, fsync=None,
                     crash_points: bool = False) -> tuple[int, int]:
        """Group-commit append: all of ``records`` (``(key, data)`` or
        ``(key, data, barrier)`` tuples) land under ONE append lock with
        ONE commit-offset update — the WAL's batched flush (one fsync per
        batch instead of per record; docs/operations.md § Durability &
        recovery). ``fsync``: ``False`` never syncs; ``True`` syncs the
        log AND the commit sidecar once after the batch; ``"group"``
        syncs the log once but lets the sidecar ride the page cache (a
        machine crash truncates back to the last synced commit — RPO one
        batch, the group mode's documented contract — while SIGKILL
        loses nothing); ``"each"`` syncs after every record plus the
        sidecar (the strictest RPO); ``None`` inherits the bus default.
        Returns the batch's ``(start, end)`` logical byte offsets.
        ``crash_points``: consult the fault injector's named kill points
        between records and before the commit flip (the crash harness's
        torn-batch / unacked-tail windows)."""
        recs = [r if len(r) == 3 else (r[0], r[1], False) for r in records]
        return self._append_records(
            topic, recs, fsync=self.fsync if fsync is None else fsync,
            crash_points=crash_points)

    def _locked_log_fd(self, topic: str) -> int:
        """Open + exclusively flock the topic's log, re-opening if a
        concurrent head-trim replaced the inode between open and lock
        (appending to the unlinked old inode would silently lose the
        record)."""
        path = self._log_path(topic)
        while True:
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
            try:
                while True:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX)
                        break
                    except OSError as e:  # pragma: no cover — EINTR retry
                        if e.errno != errno.EINTR:
                            raise
                try:
                    if os.fstat(fd).st_ino == os.stat(path).st_ino:
                        return fd
                except OSError:
                    pass  # replaced mid-lock: retry
            except BaseException:
                os.close(fd)
                raise
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def pin_writer(self, topic: str) -> None:
        """Pin an exclusive long-lived writer for a topic: the log fd
        stays open and flocked, the tail is repaired ONCE, and the commit
        offset is cached — later appends skip the per-publish open/lock/
        read cycle. ONLY for single-writer topics (the durability WAL,
        whose catalog lock already guarantees exclusivity): a second
        process's publish would block on the held flock forever."""
        with self._pin_mu:
            self._pin_locked(topic)

    def _pin_locked(self, topic: str) -> None:
        if topic in self._pinned:
            return
        self.create_topic(topic)
        fd = self._locked_log_fd(topic)
        base, _brecs, hdrlen = _parse_filehdr(os.pread(fd, _FILEHDR.size, 0))
        committed = self._read_commit(topic)
        size = os.fstat(fd).st_size
        if committed is None:
            committed = self._scan_framed_prefix(topic, size)
        committed = max(committed, base)
        if base + (size - hdrlen) > committed:
            os.ftruncate(fd, hdrlen + (committed - base))
        os.lseek(fd, 0, os.SEEK_END)
        # the commit sidecar rides a pinned fd too: an exclusive writer
        # updates it with one 8-byte pwrite instead of tmp+rename per
        # flush (readers of a torn value fall back to the framed-prefix
        # scan — the sidecar is a hint, the log is the truth)
        cfd = os.open(self._commit_path(topic),
                      os.O_CREAT | os.O_RDWR, 0o644)
        os.pwrite(cfd, _COMMIT.pack(committed), 0)
        self._pinned[topic] = [fd, base, hdrlen, committed, cfd]

    def _unpin_locked(self, topic: str) -> None:
        pin = self._pinned.pop(topic, None)
        if pin is not None:
            try:
                fcntl.flock(pin[0], fcntl.LOCK_UN)
            finally:
                os.close(pin[0])
                os.close(pin[4])

    def unpin_all(self) -> None:
        with self._pin_mu:
            for topic in list(self._pinned):
                self._unpin_locked(topic)

    @staticmethod
    def _write_all(fd: int, buf: bytes) -> None:
        """os.write until everything landed: a short write (ENOSPC
        edge, >RW_MAX buffers) must never let the commit offset advance
        past bytes that were not written."""
        view = memoryview(buf)
        while view:
            n = os.write(fd, view)
            view = view[n:]

    def _write_records(self, fd: int, recs, fsync, crash_points: bool,
                       committed: int) -> int:
        """The ONE record-append loop shared by the pinned and unpinned
        paths (frame pack, per-record/batch fsync, named crash points);
        returns the new committed offset. The caller flips the commit."""
        from geomesa_tpu.resilience import faults as _faults

        for i, (key, data, barrier) in enumerate(recs):
            if crash_points and i:
                _faults.crash_point("wal.mid_group_commit")
            self._write_all(fd, _HEADER.pack(
                len(data), 1 if barrier else 0, _key_hash(key)) + data)
            committed += _HEADER.size + len(data)
            if fsync == "each":
                os.fsync(fd)
        if fsync and fsync != "each":
            os.fsync(fd)
        if crash_points:
            # the widest unacked window: bytes are in the log but the
            # commit offset still points below them — recovery MUST
            # truncate them as torn, never misframe
            _faults.crash_point("wal.post_append_pre_commit")
        return committed

    def _append_records(self, topic: str, recs, fsync,
                        crash_points: bool = False) -> tuple[int, int]:
        self.create_topic(topic)
        with self._pin_mu:
            pin = self._pinned.get(topic)
            if pin is not None:
                fd, _base, _hdrlen, committed, cfd = pin
                start = committed
                try:
                    committed = self._write_records(
                        fd, recs, fsync, crash_points, committed)
                    os.pwrite(cfd, _COMMIT.pack(committed), 0)
                    if fsync and fsync not in ("group",):
                        # tpurace: disable-next-line=R003
                        os.fsync(cfd)
                except BaseException:
                    # a failed flush leaves the fd positioned past
                    # un-committed bytes while the cached offset is stale:
                    # drop the pin — the next append's slow path (or
                    # re-pin) repairs via ftruncate-to-commit, so a retry
                    # can never misframe or duplicate
                    self._unpin_locked(topic)
                    raise
                pin[3] = committed
                return start, committed
        fd = self._locked_log_fd(topic)
        try:
            base, _brecs, hdrlen = _parse_filehdr(os.pread(fd, _FILEHDR.size, 0))
            committed = self._read_commit(topic)
            size = os.fstat(fd).st_size
            if committed is None:
                # lost sidecar: recover from the log itself (never assume
                # 0 — that would truncate committed history away)
                committed = self._scan_framed_prefix(topic, size)
            committed = max(committed, base)
            if base + (size - hdrlen) > committed:
                # torn bytes from a writer killed mid-append: repair under
                # the lock so the new record starts at the commit boundary
                os.ftruncate(fd, hdrlen + (committed - base))
            os.lseek(fd, 0, os.SEEK_END)
            start = committed
            committed = self._write_records(
                fd, recs, fsync, crash_points, committed)
            # commit AFTER the records are fully (and, with fsync, durably)
            # in the log — readers never parse past this offset. "group"
            # skips the sidecar sync: its loss truncates back to the last
            # synced commit, which IS the mode's one-batch RPO
            self._write_commit(topic, committed,
                               fsync=bool(fsync) and fsync != "group")
            return start, committed
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- read side -----------------------------------------------------------
    def _refresh(self, topic: str) -> None:
        """Parse newly COMMITTED bytes into the per-partition and
        total-order indexes — incremental, one pass per new byte."""
        self.create_topic(topic)
        with self._lock:
            pos = self._scan_pos[topic]
            committed = self._read_commit(topic)
            if committed is None:
                # lost sidecar: fall back to the longest well-framed prefix
                committed = self._scan_framed_prefix(topic)
            if committed <= pos:
                # base <= committed always (trim clamps at the commit), so
                # nothing-new also means pos is at/above any trimmed head
                return
            try:
                # the bus lock IS this read's serialization point: scan
                # position and the indexes it feeds must advance atomically
                # with the bytes parsed, and the read is bounded by the
                # committed offset (page-cache-hot in the steady state)
                # tpurace: disable-next-line=R003
                with open(self._log_path(topic), "rb") as f:
                    base, brecs, hdrlen = _parse_filehdr(f.read(_FILEHDR.size))
                    if pos < base:
                        if pos == 0 and self._tcount[topic] == 0:
                            # fresh attach to a head-trimmed log: the scan
                            # begins at the first retained record — nothing
                            # below was ever promised to this process
                            pos = base
                            self._rec_base[topic] = brecs
                        else:
                            raise TrimmedError(
                                f"journal {topic!r}: scan position {pos} is "
                                f"below the trimmed head {base}")
                    elif pos == 0:
                        self._rec_base.setdefault(topic, 0)
                    f.seek(hdrlen + (pos - base))
                    buf = f.read(committed - pos)
            except OSError:
                return
            plog = self._plogs[topic]
            tlog = self._tlogs[topic]
            has_subs = bool(self._subscribers.get(topic))
            track = topic in self._trim_track
            off = 0
            while len(buf) - off >= _HEADER.size:
                ln, barrier, kh = _HEADER.unpack_from(buf, off)
                end = off + _HEADER.size + ln
                if end > len(buf):
                    break  # commit mid-record cannot happen; defensive
                payload = buf[off + _HEADER.size : end]
                if barrier:
                    for p in range(self.partitions):
                        plog[p].append(payload)
                else:
                    plog[kh % self.partitions].append(payload)
                if track:
                    # (-1 = barrier: belongs to every partition)
                    self._rec_meta[topic].append(
                        (-1 if barrier else kh % self.partitions, pos + end))
                # total-order window only buffers for push subscribers;
                # poll-only readers keep it empty (bounded memory)
                if has_subs:
                    tlog.append(payload)
                else:
                    self._tbase[topic] += 1
                self._tcount[topic] += 1
                off = end
            self._scan_pos[topic] = pos + off

    def poll(self, topic: str, partition: int, offset: int, max_n: int = 256):
        """Messages [offset, offset+max_n) of one partition's log. Offsets
        below a trimmed prefix (see :meth:`trim`) yield from the first
        retained message."""
        from geomesa_tpu import obs

        with obs.span("journal.poll", topic=topic, partition=partition):
            self._refresh(topic)
        with self._lock:
            base = self._pbase[topic][partition]
            log = self._plogs[topic][partition]
            lo = max(offset - base, 0)
            return log[lo : lo + max_n]

    def end_offset(self, topic: str, partition: int) -> int:
        self._refresh(topic)
        with self._lock:
            return self._pbase[topic][partition] + len(
                self._plogs[topic][partition]
            )

    def topic_size(self, topic: str) -> int:
        self._refresh(topic)
        with self._lock:
            return self._tcount.get(topic, 0)

    def tail_lag(self, topic: str) -> int:
        """Committed records the background tailer has NOT yet delivered to
        every push subscriber — the feed-side quiescence signal
        (``tail_lag() == 0`` means all published records have been handed
        to all subscriber callbacks AND those callbacks returned). Topics
        with no push subscribers report 0 (nothing to dispatch)."""
        self._refresh(topic)
        with self._lock:
            if topic not in self._sub_offsets:
                return 0
            return max(
                self._tcount.get(topic, 0) - self._dispatched.get(topic, 0), 0
            )

    def trim(self, topic: str, partition: int, upto: int | None = None) -> int:
        """Two forms. ``trim(topic, partition, upto)`` releases THIS
        READER's memory for partition messages below ``upto`` (a consumed
        offset); the on-disk journal is untouched — durability and
        late-attaching readers are unaffected. ``trim(topic,
        below_offset)`` (two arguments) durably truncates the LOG HEAD
        below a logical byte offset — see :meth:`trim_log`. Both return
        what they released (messages / bytes)."""
        if upto is None:
            return self.trim_log(topic, partition)
        self.create_topic(topic)
        with self._lock:
            base = self._pbase[topic][partition]
            drop = min(max(upto - base, 0), len(self._plogs[topic][partition]))
            if drop:
                del self._plogs[topic][partition][:drop]
                self._pbase[topic][partition] = base + drop
            return drop

    def trim_log(self, topic: str, below_offset: int) -> int:
        """Durable log-HEAD truncation: committed records wholly below
        logical byte ``below_offset`` leave the disk (clamped to the
        commit offset and snapped DOWN to a record boundary — a record is
        never split). Logical offsets never shift: the retained tail is
        rewritten under the append lock behind a header stamping the new
        base, the commit sidecar is untouched, and the replacement is
        atomic (tmp + fsync + rename) so a crash leaves either the old or
        the new file intact. Readers whose cursor falls below the new
        head raise :class:`TrimmedError`. Returns the bytes trimmed."""
        self.create_topic(topic)
        with self._pin_mu:
            # a pinned writer holds the flock and its inode dies with the
            # rewrite: release, trim, re-pin on the new inode
            repin = topic in self._pinned
            if repin:
                self._unpin_locked(topic)
            try:
                return self._trim_log_locked(topic, below_offset)
            finally:
                if repin:
                    self._pin_locked(topic)

    def _trim_log_locked(self, topic: str, below_offset: int) -> int:
        path = self._log_path(topic)
        fd = self._locked_log_fd(topic)
        try:
            base, brecs, hdrlen = _parse_filehdr(os.pread(fd, _FILEHDR.size, 0))
            size = os.fstat(fd).st_size
            committed = self._read_commit(topic)
            if committed is None:
                committed = self._scan_framed_prefix(topic, size)
            committed = max(committed, base)
            below = min(below_offset, committed)
            if below <= base:
                return 0
            buf = os.pread(fd, max(committed - base, 0), hdrlen)
            off, dropped = 0, 0
            while len(buf) - off >= _HEADER.size:
                ln, _b, _k = _HEADER.unpack_from(buf, off)
                end = off + _HEADER.size + ln
                if end > len(buf) or base + end > below:
                    break
                off = end
                dropped += 1
            if off == 0:
                return 0
            boundary = base + off
            tmp = f"{path}.trim.{os.getpid()}"
            tfd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
            try:
                os.write(tfd, _FILEHDR.pack(_MAGIC, 1, boundary,
                                            brecs + dropped))
                os.write(tfd, buf[off:])  # committed suffix; torn tail drops
                # always durable: a machine crash after the rename must not
                # surface an empty retained tail under the committed name
                os.fsync(tfd)
            finally:
                os.close(tfd)
            os.replace(tmp, path)
            try:
                dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:  # pragma: no cover — platform-dependent
                pass
            with self._lock:
                if topic in self._trim_track and self._rec_meta.get(topic):
                    meta = self._rec_meta[topic]
                    keep = 0
                    counts = self._rec_meta_pcounts[topic]
                    while keep < len(meta) and meta[keep][1] <= boundary:
                        p = meta[keep][0]
                        for q in (range(self.partitions) if p < 0 else (p,)):
                            counts[q] += 1
                        keep += 1
                    del meta[:keep]
            return off
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- checkpointed-consumer durable trim -----------------------------------
    def enable_trim_tracking(self, topic: str) -> None:
        """Start recording per-record (partition, end-byte) metadata for a
        topic so :meth:`trim_applied` can map a consumer's per-partition
        applied offsets back to a safe head-trim boundary. Memory is
        bounded by the trim cadence (metadata drops with each trim)."""
        self.create_topic(topic)
        with self._lock:
            if topic in self._trim_track:
                return
            # only records parsed AFTER enabling are trackable: snapshot
            # the per-partition counts consumed so far as the floor
            self._trim_track.add(topic)
            self._rec_meta[topic] = []
            self._rec_meta_pcounts[topic] = [
                self._pbase[topic][p] + len(self._plogs[topic][p])
                for p in range(self.partitions)
            ]

    def trim_applied(self, topic: str, applied: list[int]) -> int:
        """Durably trim the log head below every record all of whose
        partitions' consumers have applied it: ``applied[p]`` is partition
        ``p``'s applied message offset (this process's view — the same
        offsets :class:`~geomesa_tpu.stream.consumer.ThreadedConsumer`
        keeps). Walks tracked records in total order, stops at the first
        unapplied one, and hands the boundary to :meth:`trim_log`.
        Returns the bytes trimmed (0 when tracking is off or nothing new
        is coverable)."""
        with self._lock:
            meta = self._rec_meta.get(topic)
            if not meta:
                return 0
            counts = list(self._rec_meta_pcounts[topic])
            boundary = None
            for part, end in meta:
                parts = range(self.partitions) if part < 0 else (part,)
                if any(applied[p] <= counts[p] for p in parts):
                    break
                for p in parts:
                    counts[p] += 1
                boundary = end
        if boundary is None:
            return 0
        return self.trim_log(topic, boundary)

    def iter_records(self, topic: str):
        """Yield ``(start_logical, end_logical, payload)`` for every
        committed, retained record — the WAL's replay/trim framing surface
        and the ``geomesa-tpu wal`` inspection path. Reads one committed
        snapshot; records appended after the call starts are not seen."""
        committed = self._read_commit(topic)
        try:
            with open(self._log_path(topic), "rb") as f:
                buf = f.read()
        except OSError:
            return
        base, _brecs, hdrlen = _parse_filehdr(buf)
        if committed is None:
            committed = self._scan_framed_prefix(topic)
        limit = hdrlen + max(min(committed, base + len(buf) - hdrlen) - base, 0)
        off = hdrlen
        while limit - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > limit:
                break
            yield (base + off - hdrlen, base + end - hdrlen,
                   buf[off + _HEADER.size : end])
            off = end

    def topics(self) -> list[str]:
        """Topics present ON DISK under this bus root (unescaped names) —
        the recovery path's topic discovery; in-memory-only topics that
        never published are not listed."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for fn in sorted(names):
            if fn.endswith(".log"):
                out.append(_unsafe_name(fn[: -len(".log")]))
        return out

    def head_offset(self, topic: str) -> int:
        """Logical byte offset of the first retained record (the durably
        trimmed head; 0 for never-trimmed logs)."""
        return self._log_head(topic)[0]

    def committed_offset(self, topic: str) -> int:
        """The committed logical byte offset (sidecar value, or the
        framed-prefix recovery value when the sidecar is lost)."""
        committed = self._read_commit(topic)
        if committed is None:
            committed = self._scan_framed_prefix(topic)
        return max(committed, self._log_head(topic)[0])

    # -- push subscribers (tailer thread dispatches in total order) ----------
    def subscribe(self, topic: str, callback: Callable[[bytes], None]) -> None:
        """Register a consumer: the full backlog (offset 0) replays to the
        NEW callback first, then the background tailer pushes new records.

        Replay and registration happen under the bus lock — mirroring the
        in-process bus's no-gap no-reorder contract — so the tailer can
        neither double-deliver the backlog nor slip a record between
        replay and registration. Already-dispatched records the tailer
        trimmed from memory replay from the journal FILE.

        Stop/restart is a guarded state transition shared with
        :meth:`close`: a tailer is bound for life to the stop event
        current at its creation, the event is only ever swapped for a
        fresh one when ``self._tailer is None`` (which in turn is only
        set after the old thread is CONFIRMED dead), and a subscribe that
        lands mid-close first joins the draining tailer outside the lock.
        Without the full transition, a subscribe racing close could
        register against a dying tailer (push delivery silently never
        resumes) or leave a stale tailer running against the old event
        next to a fresh one.
        """
        self.create_topic(topic)
        while True:
            with self._lock:
                # close() in flight: _stop is set but its tailer has not
                # been confirmed dead yet — restart only after it is
                stale = self._tailer if self._stop.is_set() else None
                if stale is None or stale is threading.current_thread():
                    # the second arm: a callback ON the dying tailer
                    # re-subscribing mid-close cannot join itself —
                    # register now; the tailer restart happens on the
                    # next subscribe after close() completes (the normal
                    # bus-reuse path picks this callback up with it)
                    self._subscribe_locked(topic, callback)
                    return
            stale.join(timeout=5.0)
            with self._lock:
                if self._tailer is stale and not stale.is_alive():
                    self._tailer = None

    def _subscribe_locked(self, topic: str,
                          callback: Callable[[bytes], None]) -> None:
        """Replay + register + (re)start the tailer; caller holds the bus
        lock and has established that no stopping tailer remains."""
        self._refresh(topic)
        total = self._tcount[topic]
        first = topic not in self._sub_offsets
        # the tailer owns [cursor:] for ALL subscribers (including this
        # one); the new callback catches up on [0:cursor] here — from
        # disk for any part no longer buffered in memory. The FIRST
        # subscriber catches up on the whole history (records parsed
        # before any subscriber existed were never buffered).
        cursor = total if first else self._sub_offsets[topic]
        tbase = self._tbase[topic]
        if cursor > 0:
            if tbase > 0:
                backlog = self._disk_payloads(topic, cursor)
            else:
                backlog = self._tlogs[topic][:cursor]
            for data in backlog:
                callback(data)
        if first:
            self._sub_offsets[topic] = total
            self._dispatched[topic] = total  # replay above was synchronous
            del self._tlogs[topic][: max(total - tbase, 0)]
            self._tbase[topic] = total
        self._subscribers.setdefault(topic, []).append(callback)
        if self._tailer is None:
            if self._stop.is_set():
                self._stop = threading.Event()  # bus reused after close
            self._tailer = threading.Thread(
                target=self._tail_loop, daemon=True,
                name="geomesa-journal-tailer",
            )
            self._tailer.start()

    def unsubscribe(self, topic: str, callback: Callable[[bytes], None]) -> bool:
        """Remove a push subscriber; missing registrations are a no-op.
        The tailer keeps advancing the topic cursor for any remaining
        subscribers (and stays dispatch-idle on the topic otherwise) —
        detaching never rewinds or re-delivers."""
        with self._lock:
            subs = self._subscribers.get(topic, [])
            try:
                subs.remove(callback)
                return True
            except ValueError:
                return False

    def _disk_payloads(self, topic: str, first_n: int) -> list[bytes]:
        """First ``first_n`` payloads OF THIS PROCESS'S VIEW re-read from
        the committed journal prefix (late-subscriber replay after the
        in-memory log trimmed). Raises :class:`TrimmedError` if a durable
        head-trim since this process attached removed records the view
        still addresses."""
        committed = self._read_commit(topic)
        try:
            with open(self._log_path(topic), "rb") as f:
                buf = f.read()
        except OSError:
            return []
        base, brecs, hdrlen = _parse_filehdr(buf)
        with self._lock:
            rec_base = self._rec_base.get(topic, 0)
        if brecs > rec_base:
            raise TrimmedError(
                f"journal {topic!r}: records below index {brecs} were "
                f"durably trimmed; replay from index {rec_base} is gone")
        if committed is None:
            committed = self._scan_framed_prefix(topic)
        limit = hdrlen + max(min(committed, base + len(buf) - hdrlen) - base, 0)
        out: list[bytes] = []
        skip = rec_base - brecs
        off = hdrlen
        while len(out) < first_n and limit - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > limit:
                break
            if skip > 0:
                skip -= 1
            else:
                out.append(buf[off + _HEADER.size : end])
            off = end
        return out

    def total_poll(self, topic: str, offset: int, max_n: int = 256):
        """Total-order payloads ``[offset, offset+max_n)`` re-read from the
        committed journal prefix — the message-offset-addressed form
        (O(offset) per call: the log is re-framed from byte 0). Long-lived
        remote tails use :meth:`total_poll_bytes` instead, which reads
        only new bytes."""
        return self._disk_payloads(topic, offset + max_n)[offset:]

    def total_poll_bytes(self, topic: str, cursor: int,
                         max_bytes: int = 1 << 22):
        """Total-order tail by BYTE cursor: payloads framed from committed
        byte ``cursor``, plus the next cursor — each call reads only the
        new bytes, so a long-lived remote subscriber is O(new data), not
        O(journal) (the ``/api/journal/<topic>/tpoll?cursor=`` path).
        ``cursor`` is an opaque token: start at 0, always pass back the
        returned value (it only ever lands on record boundaries)."""
        committed = self._read_commit(topic)
        try:
            size = os.path.getsize(self._log_path(topic))
        except OSError:
            return [], cursor
        base, _brecs, hdrlen = self._log_head(topic)
        if committed is None:
            committed = self._scan_framed_prefix(topic, size)
        committed = max(min(committed, base + size - hdrlen), base)
        if cursor == 0:
            # 0 = "from the start of RETAINED history": a fresh tail of a
            # head-trimmed topic begins at the first surviving record
            cursor = base
        elif cursor < base:
            raise TrimmedError(
                f"journal {topic!r}: cursor {cursor} is below the durably "
                f"trimmed head {base}")
        if cursor >= committed:
            return [], cursor
        try:
            with open(self._log_path(topic), "rb") as f:
                f.seek(hdrlen + (cursor - base))
                buf = f.read(min(committed - cursor, max_bytes))
        except OSError:
            return [], cursor
        out: list[bytes] = []
        off = 0
        while len(buf) - off >= _HEADER.size:
            ln, _b, _k = _HEADER.unpack_from(buf, off)
            end = off + _HEADER.size + ln
            if end > len(buf):
                break  # record straddles the read window: next call gets it
            out.append(buf[off + _HEADER.size : end])
            off = end
        return out, cursor + off

    def _tail_loop(self) -> None:
        from geomesa_tpu.obs import jaxmon, trace as _trace
        from geomesa_tpu.resilience.policy import RetryPolicy
        from geomesa_tpu.stream import telemetry

        stop = self._stop
        errors = jaxmon.registry().counter("stream.callback_errors")
        # decorrelated-jitter idle backoff (reset on traffic): a quiet bus
        # polls ~10x/s instead of spinning at poll_interval_s
        idle = RetryPolicy(base_delay_s=self.poll_interval_s,
                           max_delay_s=self.idle_max_s)
        delay: float | None = None
        # ONE stable root span per tailer session (the local-bus analog of
        # RemoteJournal's journal.tail session): callback failures attach
        # as span EVENTS so a broken consumer shows up in flight records
        # instead of vanishing into a swallowed except. Managed manually —
        # tracing may come on mid-session.
        session = _trace.span("journal.tail", bus=self.root)
        session.__enter__()
        try:
            while not stop.is_set():
                if session is _trace.NOOP and _trace.enabled():
                    session = _trace.span("journal.tail", bus=self.root)
                    session.__enter__()
                dispatched = 0
                with self._lock:
                    topics = list(self._subscribers)
                for topic in topics:
                    try:
                        self._refresh(topic)
                    except TrimmedError:
                        # another process durably trimmed above this
                        # tailer's cursor: fast-forward to the retained
                        # head — COUNTED, the gap is never silent
                        errors.inc()
                        telemetry.note_callback_error(topic)
                        base = self._log_head(topic)[0]
                        with self._lock:
                            self._scan_pos[topic] = max(
                                self._scan_pos[topic], base)
                        if isinstance(session, _trace.Span):
                            session.event("trimmed_gap", topic=topic)
                    with self._lock:
                        tbase = self._tbase[topic]
                        log = self._tlogs[topic]
                        start = self._sub_offsets.get(topic, 0)
                        batch = log[max(start - tbase, 0):]
                        subs = list(self._subscribers.get(topic, []))
                        end = tbase + len(log)
                        self._sub_offsets[topic] = end
                        # dispatched records leave memory (steady-state
                        # bound); late subscribers replay them from disk
                        del log[: max(start - tbase, 0) + len(batch)]
                        self._tbase[topic] = end
                    for data in batch:
                        for cb in subs:
                            try:
                                cb(data)
                            except Exception as e:  # noqa: BLE001
                                # one bad consumer must not kill delivery
                                # for every topic; the record stays
                                # consumed (at-most-once for the failing
                                # callback) — but the failure is COUNTED
                                # and lands on the session span, never
                                # silently swallowed
                                errors.inc()
                                telemetry.note_callback_error(topic)
                                if isinstance(session, _trace.Span):
                                    session.event(
                                        "callback_error", topic=topic,
                                        error=type(e).__name__,
                                    )
                        dispatched += 1
                    if batch:
                        with self._lock:
                            # dispatched-THROUGH only moves once every
                            # callback has seen the batch (tail_lag's
                            # happens-before edge)
                            self._dispatched[topic] = end
                        telemetry.note_poll(topic, len(batch), 0.0,
                                            loop="tailer")
                    if isinstance(session, _trace.Span):
                        # bound the long-lived session tree (remote-journal
                        # pattern: single-writer trim, exporters snapshot)
                        if len(session.events) > 128:
                            del session.events[:-128]
                if dispatched == 0:
                    delay = idle.next_delay(delay)
                    for topic in topics:
                        telemetry.note_poll(topic, 0, delay,
                                            loop="tailer")
                    stop.wait(delay)
                else:
                    delay = None
        finally:
            session.__exit__(None, None, None)

    # -- standing queries (fused device scan) --------------------------------
    def subscribe_query(self, topic: str, serializer, predicate,
                        callback, **hub_cfg) -> int:
        """Standing-query subscription over a journal topic: instead of a
        per-row host callback, appended records batch through the
        :class:`~geomesa_tpu.stream.pipeline.SubscriptionHub` — decoded
        with ``serializer`` (which carries the feature type), scanned as
        one fused ``(rows × queries)`` device pass per chunk, with
        per-subscription hit deliveries (docs/streaming.md). Returns the
        subscription id (``unsubscribe_query`` to remove)."""
        from geomesa_tpu.stream.pipeline import SubscriptionHub

        def attach(hub):
            self.subscribe(topic, hub.ingest)
            # detach handle: close_all stops a reused bus from feeding
            # the closed scanner after its tailer restarts
            return lambda: self.unsubscribe(topic, hub.ingest)

        return self._hubs.subscribe(
            topic, predicate, callback,
            make_hub=lambda: SubscriptionHub(
                serializer.sft, serializer, topic=topic, **hub_cfg
            ),
            attach=attach,
            cfg=hub_cfg,
        )

    def unsubscribe_query(self, topic: str, sid: int) -> bool:
        return self._hubs.unsubscribe(topic, sid)

    def query_hub(self, topic: str):
        """The topic's SubscriptionHub (None before any subscribe_query)."""
        return self._hubs.get(topic)

    def close(self) -> None:
        """Stop the tailer (idempotent; deterministic join). See
        :meth:`subscribe` for the stop/restart state transition."""
        self.unpin_all()
        self._hubs.close_all()
        # snapshot under the lock (subscribe swaps _stop/_tailer under it);
        # join OUTSIDE it — the tailer takes the lock per topic and joining
        # while holding it would deadlock
        with self._lock:
            self._stop.set()
            tailer = self._tailer
        if tailer is not None:
            tailer.join(timeout=5.0)
            with self._lock:
                # only a CONFIRMED-dead tailer clears the slot: a wedged
                # thread must keep blocking restarts (subscribe joins it)
                # rather than end up running beside a fresh tailer
                if self._tailer is tailer and not tailer.is_alive():
                    self._tailer = None
