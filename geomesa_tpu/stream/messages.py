"""Change messages + binary serialization for the streaming store.

Role parity: ``geomesa-kafka/.../utils/GeoMessageSerializer.scala`` (SURVEY.md
§2.10): three message kinds — put (upsert a feature), delete (by fid), clear
(drop everything) — with a compact binary wire format so the bus carries bytes,
not Python objects. Geometry attributes ride as WKB; dates as int64 epoch
millis; a null bitmap covers missing attributes.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any

from geomesa_tpu.geometry.types import Geometry
from geomesa_tpu.geometry.wkb import from_wkb, to_wkb
from geomesa_tpu.schema.sft import AttributeType, FeatureType

__all__ = ["Put", "Delete", "Clear", "GeoMessageSerializer"]

_K_PUT, _K_DELETE, _K_CLEAR = 0, 1, 2


@dataclass(frozen=True)
class Put:
    fid: str
    record: dict
    ts: int  # event-time epoch millis


@dataclass(frozen=True)
class Delete:
    fid: str
    ts: int


@dataclass(frozen=True)
class Clear:
    ts: int


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<I", len(b)) + b


class _Cursor:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def unpack(self, fmt: str):
        vals = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += struct.calcsize(fmt)
        return vals

    def take(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def unpack_str(self) -> str:
        (n,) = self.unpack("<I")
        return self.take(n).decode("utf-8")


class GeoMessageSerializer:
    """Schema-bound message codec (one per feature type, like the reference)."""

    def __init__(self, sft: FeatureType):
        if len(sft.attributes) > 64:
            raise ValueError(
                f"GeoMessage null bitmap supports at most 64 attributes; "
                f"schema {sft.name!r} has {len(sft.attributes)}"
            )
        self.sft = sft

    def serialize(self, msg: Put | Delete | Clear) -> bytes:
        if isinstance(msg, Clear):
            return struct.pack("<Bq", _K_CLEAR, msg.ts)
        if isinstance(msg, Delete):
            return struct.pack("<Bq", _K_DELETE, msg.ts) + _pack_str(msg.fid)
        out = [struct.pack("<Bq", _K_PUT, msg.ts), _pack_str(msg.fid)]
        attrs = self.sft.attributes
        null_bits = 0
        for i, a in enumerate(attrs):
            if msg.record.get(a.name) is None:
                null_bits |= 1 << i
        out.append(struct.pack("<Q", null_bits))
        for a in attrs:
            v = msg.record.get(a.name)
            if v is None:
                continue
            out.append(self._encode_value(a.type, v))
        return b"".join(out)

    def deserialize(self, data: bytes) -> Put | Delete | Clear:
        c = _Cursor(data)
        kind, ts = c.unpack("<Bq")
        if kind == _K_CLEAR:
            return Clear(ts)
        if kind == _K_DELETE:
            return Delete(c.unpack_str(), ts)
        fid = c.unpack_str()
        (null_bits,) = c.unpack("<Q")
        record: dict[str, Any] = {}
        for i, a in enumerate(self.sft.attributes):
            if null_bits & (1 << i):
                record[a.name] = None
            else:
                record[a.name] = self._decode_value(a.type, c)
        return Put(fid, record, ts)

    @staticmethod
    def _encode_value(typ: AttributeType, v) -> bytes:
        if typ.is_geometry:
            assert isinstance(v, Geometry)
            b = to_wkb(v)
            return struct.pack("<I", len(b)) + b
        if typ == AttributeType.DATE:
            return struct.pack("<q", int(v))
        if typ == AttributeType.INT:
            return struct.pack("<i", int(v))
        if typ == AttributeType.LONG:
            return struct.pack("<q", int(v))
        if typ == AttributeType.FLOAT:
            return struct.pack("<f", float(v))
        if typ == AttributeType.DOUBLE:
            return struct.pack("<d", float(v))
        if typ == AttributeType.BOOLEAN:
            return struct.pack("<B", 1 if v else 0)
        if typ == AttributeType.BYTES:
            return struct.pack("<I", len(v)) + bytes(v)
        return _pack_str(str(v))  # STRING/UUID + anything stringly

    @staticmethod
    def _decode_value(typ: AttributeType, c: _Cursor):
        if typ.is_geometry:
            (n,) = c.unpack("<I")
            return from_wkb(c.take(n))
        if typ == AttributeType.DATE:
            return c.unpack("<q")[0]
        if typ == AttributeType.INT:
            return c.unpack("<i")[0]
        if typ == AttributeType.LONG:
            return c.unpack("<q")[0]
        if typ == AttributeType.FLOAT:
            return c.unpack("<f")[0]
        if typ == AttributeType.DOUBLE:
            return c.unpack("<d")[0]
        if typ == AttributeType.BOOLEAN:
            return bool(c.unpack("<B")[0])
        if typ == AttributeType.BYTES:
            (n,) = c.unpack("<I")
            return c.take(n)
        return c.unpack_str()
