"""Lambda-architecture store: streaming hot tier + persistent cold tier with
a BACKGROUND persister thread.

Role parity: ``geomesa-lambda`` (SURVEY.md §2.11) — ``LambdaDataStore.scala``
(tier composition), ``DataStorePersistence.scala:161`` (the background
process moving aged-out features from the Kafka tier into the persistent
store), ``LambdaQueryRunner.scala`` (queries merge both tiers, hot winning on
fid collisions). Unlike round 1's threshold-triggered compaction inside
``write()``, persistence here runs on its own thread on a wall-clock cadence,
and the move is write-cold-first + compare-and-remove so a feature is never
lost or duplicated even under concurrent updates.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec
from geomesa_tpu.store.datastore import DataStore, QueryResult
from geomesa_tpu.stream.datastore import MessageBus, StreamingDataStore

__all__ = ["LambdaDataStore"]


class LambdaDataStore:
    """Hot (live cache) + cold (sorted columnar store) with background
    persistence.

    ``persist_age_ms``: features older than this move to the cold tier on
    the persister's next pass. ``persist_interval_s``: persister cadence;
    pass ``None`` to disable the thread (drive :meth:`persist_once`
    manually, e.g. in tests).
    """

    def __init__(
        self,
        cold: DataStore | None = None,
        bus: MessageBus | None = None,
        persist_age_ms: int = 60_000,
        persist_interval_s: float | None = 1.0,
        consumers: int = 2,
    ):
        self.cold = cold if cold is not None else DataStore(backend="tpu")
        self.stream = StreamingDataStore(bus=bus, async_consumers=consumers)
        self.persist_age_ms = persist_age_ms
        self._stop = threading.Event()
        self._persist_lock = threading.Lock()
        # fids known to live in cold (avoids an O(rows) cold scan per tick)
        self._persisted: dict[str, set] = {}
        # deletes not yet drained by the consumers: excluded from queries and
        # from persistence so an in-flight persist can't resurrect them
        self._tombstones: dict[str, set] = {}
        self._closed = False
        self._thread = None
        if persist_interval_s is not None:
            self._thread = threading.Thread(
                target=self._persist_loop, args=(persist_interval_s,),
                daemon=True, name="geomesa-lambda-persister",
            )
            self._thread.start()

    # -- schema / writes ------------------------------------------------------
    def create_schema(self, sft: FeatureType | str, spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec)
        self.stream.create_schema(sft)
        self.cold.create_schema(sft)
        return sft

    def _ensure_hot(self, type_name: str) -> None:
        """Lazily register a wrapped cold store's schema with the hot tier
        on first touch — eager registration at wrap time would spawn
        consumer threads (and persister work) for every cold type, streamed
        or not."""
        if type_name not in self.stream.list_schemas():
            self.stream.create_schema(self.cold.get_schema(type_name))

    def list_schemas(self) -> list[str]:
        return self.cold.list_schemas()

    def data_epoch(self, type_name: str) -> tuple:
        """The lambda-tier data epoch: the cold store's (rebuild epoch,
        delta version) pair plus the hot cache's mutation version
        (``FeatureCache.version``). Monotone per component, so any cache
        layered over the merged view (the GeoBlocks warm path) can stamp
        entries with it — a hot put/delete/expiry or a cold mutation each
        advance it, and a stale stamp can only MISS."""
        st = self.cold._state(type_name)
        hot = 0
        if type_name in self.stream.list_schemas():
            hot = self.stream.cache(type_name).version
        return (*st.data_epoch(), hot)

    def write(self, type_name: str, fid: str, record: dict, ts: int | None = None):
        self._ensure_hot(type_name)
        with self._persist_lock:
            self._tombstones.get(type_name, set()).discard(fid)  # re-put revives
        self.stream.put(type_name, fid, record, ts=ts)

    def subscribe_query(self, type_name: str, predicate, callback,
                        **hub_cfg) -> int:
        """Standing query over the lambda tier's LIVE stream: every write
        flows through the hot tier's bus, so subscriptions see each
        appended feature exactly once regardless of when the persister
        later moves it cold (see
        :meth:`~geomesa_tpu.stream.datastore.StreamingDataStore.subscribe_query`)."""
        self._ensure_hot(type_name)
        return self.stream.subscribe_query(
            type_name, predicate, callback, **hub_cfg
        )

    def unsubscribe_query(self, type_name: str, sid: int) -> bool:
        return self.stream.unsubscribe_query(type_name, sid)

    def delete(self, type_name: str, fid: str) -> None:
        """Delete from BOTH tiers: tombstone first (so a racing persist pass
        can't resurrect the feature into cold), then the hot-tier message and
        the synchronous cold delete."""
        self._ensure_hot(type_name)
        with self._persist_lock:
            self._tombstones.setdefault(type_name, set()).add(fid)
            self.stream.delete(type_name, fid)
            self.cold.delete_features(type_name, [fid])
            self._persisted.get(type_name, set()).discard(fid)

    # -- background persistence (DataStorePersistence role) -------------------
    def _persist_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                for name in self.stream.list_schemas():
                    self.persist_once(name)
            except Exception:  # noqa: BLE001 — the loop must survive
                pass

    def persist_once(self, type_name: str, now_ms: int | None = None) -> int:
        """One persister pass: cold-write aged-out hot features, then
        compare-and-remove them from the hot cache. Returns rows moved."""
        now = int(time.time() * 1000) if now_ms is None else now_ms
        cache = self.stream.cache(type_name)
        consumer = self.stream.consumer(type_name)
        with self._persist_lock:
            tombs = self._tombstones.get(type_name, set())
            # a tombstone is spent once the consumers drained past the Delete
            # and the hot cache no longer holds the fid
            if tombs and (consumer is None or consumer.lag() == 0):
                tombs -= {f for f in tombs if cache.get(f) is None}
            aged = [
                s
                for s in cache.expired_states(now, age_ms=self.persist_age_ms)
                if s.fid not in tombs
            ]
            if not aged:
                return 0
            sft = self.stream.get_schema(type_name)
            recs = [s.record for s in aged]
            fids = [s.fid for s in aged]
            # land in cold FIRST (queries merge tiers and dedupe, so the
            # transient overlap is invisible); remove hot only when the state
            # is unchanged — an update during the write stays hot
            existing = self._persisted_fids(type_name)
            fresh = [i for i, f in enumerate(fids) if f not in existing]
            stale = [i for i in range(len(fids)) if fids[i] in existing]
            if fresh:
                self.cold.write(
                    type_name,
                    FeatureTable.from_records(
                        sft, [recs[i] for i in fresh], [fids[i] for i in fresh]
                    ),
                )
            if stale:
                # an older generation of this fid was persisted before: the
                # hot state supersedes it — overwrite via delete+write
                self.cold.delete_features(type_name, [fids[i] for i in stale])
                self.cold.write(
                    type_name,
                    FeatureTable.from_records(
                        sft, [recs[i] for i in stale], [fids[i] for i in stale]
                    ),
                )
            existing.update(fids)
            moved = 0
            for s in aged:
                if cache.remove_if_ts(s.fid, s.ts):
                    moved += 1
            return moved

    def _persisted_fids(self, type_name: str) -> set:
        """Cold-tier fid set, scanned once per type then maintained
        incrementally (avoids an O(rows) cold query per persister tick)."""
        s = self._persisted.get(type_name)
        if s is None:
            s = set(self.cold.query(type_name, "INCLUDE").table.fids.tolist())
            self._persisted[type_name] = s
        return s

    # -- queries (LambdaQueryRunner role) -------------------------------------
    def query(self, type_name: str, q: Query | str | None = None, **kwargs):
        if isinstance(q, str) or q is None:
            q = Query(filter=q, **kwargs)
        # tier sub-queries must not page or aggregate: sort/limit/start_index
        # and the reduce-stage hints (density/stats/bin/sample/crs) apply to
        # the MERGED stream, or each tier independently skips/truncates/
        # aggregates and the merged answer is wrong (MergedDataStoreView
        # pattern); scan-stage hints (index/loose_bbox/now_ms/timeout...)
        # stay on the tier queries
        from dataclasses import replace

        from geomesa_tpu.store.reduce import reduce_result

        _REDUCE_HINTS = ("density", "stats", "bin", "sample", "sample_by", "crs")
        sub_hints = {k: v for k, v in q.hints.items() if k not in _REDUCE_HINTS}
        sub = replace(q, sort_by=None, limit=None, start_index=None,
                      hints=sub_hints, properties=None)
        self._ensure_hot(type_name)
        hot = self.stream.query(type_name, sub)
        cold = self.cold.query(type_name, sub)
        with self._persist_lock:
            tombs = set(self._tombstones.get(type_name, ()))
        hot_table = hot.table
        if tombs:
            keep_h = np.array(
                [f not in tombs for f in hot_table.fids], dtype=bool
            )
            hot_table = hot_table.take(np.nonzero(keep_h)[0])
        hot_fids = set(hot_table.fids.tolist())
        drop = hot_fids | tombs
        if not drop:
            merged = cold.table
        else:
            # merge tiers: hot wins on fid collisions (it is strictly newer);
            # tombstoned fids are invisible even before the consumers drain
            keep = np.array(
                [f not in drop for f in cold.table.fids], dtype=bool
            )
            cold_kept = cold.table.take(np.nonzero(keep)[0])
            merged = (
                hot_table
                if len(cold_kept) == 0
                else FeatureTable.concat([hot_table, cold_kept])
            )
        # one reduce pass over the merged stream: aggregation hints, sort,
        # paging, projection — visibility was already applied per tier (the
        # second application is idempotent)
        sft = self.cold.get_schema(type_name)
        out = reduce_result(sft, merged, np.arange(len(merged)), q)
        table, rows, density, stats, bin_data = out
        return QueryResult(
            table, rows, density=density, stats=stats, bin_data=bin_data
        )

    def hot_count(self, type_name: str) -> int:
        if type_name not in self.stream.list_schemas():
            return 0  # cold-only type: never streamed
        return self.stream.cache(type_name).size()

    def close(self) -> None:
        """Deterministic shutdown: the persister thread is JOINED (not
        abandoned to daemon teardown), then the streaming tier's
        consumers and bus stop the same way. Idempotent — double-close
        is a no-op (tests/test_race_stress.py pins both properties)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.stream.close()
