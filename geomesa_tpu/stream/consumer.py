"""Threaded consumer group: parallel partition draining into the cache.

Role parity: ``geomesa-kafka/.../data/KafkaCacheLoader.scala:247`` +
``geomesa-kafka-utils/.../consumer/ThreadedConsumer.scala`` (SURVEY.md
§2.10): N consumer threads split a topic's partitions, poll batches, and
apply them to the shared live cache; per-key ordering is preserved because a
feature id always hashes to one partition. ``Clear`` is a cross-partition
barrier (the bus publishes it to every partition): consumers rendezvous on
it, one performs the clear, and only then does any partition move past it —
so a Put published after a Clear can never be wiped by it.
"""

from __future__ import annotations

import threading
from typing import Callable

__all__ = ["ThreadedConsumer"]


class ThreadedConsumer:
    """Drains a topic's partitions into ``apply`` on worker threads.

    ``apply(data: bytes, partition: int) -> bool | None`` must be
    thread-safe (the feature cache locks internally). Returning ``False``
    stalls that partition WITHOUT advancing its offset — the message is
    re-delivered on the next poll (used by cross-partition barriers; a
    stalled partition never blocks the thread, so one thread owning several
    partitions cannot deadlock a rendezvous). ``threads`` ≤ partitions; each
    thread owns a static partition subset (consumer-group assignment).
    """

    def __init__(
        self,
        bus,
        topic: str,
        apply: Callable[[bytes, int], None],
        threads: int = 2,
        poll_interval_s: float = 0.002,
    ):
        self.bus = bus
        self.topic = topic
        self.apply = apply
        self.poll_interval_s = poll_interval_s
        n_parts = bus.partitions
        threads = max(1, min(threads, n_parts))
        self._assignments = [
            [p for p in range(n_parts) if p % threads == t] for t in range(threads)
        ]
        self._offsets = [0] * n_parts
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._run, args=(parts,), daemon=True,
                name=f"geomesa-consumer-{topic}-{t}",
            )
            for t, parts in enumerate(self._assignments)
        ]
        for t in self._threads:
            t.start()

    def _run(self, partitions: list[int]) -> None:
        trim = getattr(self.bus, "trim", None)  # durable buses free applied
        while not self._stop.is_set():
            drained = 0
            for p in partitions:
                batch = self.bus.poll(self.topic, p, self._offsets[p], max_n=256)
                applied = 0
                for data in batch:
                    if self.apply(data, p) is False:
                        break  # stalled at a barrier; redeliver next poll
                    self._offsets[p] += 1
                    applied += 1
                drained += applied
                if applied and trim is not None:
                    # bound the bus's in-memory window to unapplied messages
                    trim(self.topic, p, self._offsets[p])
            if drained == 0:
                self._stop.wait(self.poll_interval_s)

    def lag(self) -> int:
        """Unconsumed messages across partitions (backpressure signal)."""
        return sum(
            self.bus.end_offset(self.topic, p) - self._offsets[p]
            for p in range(self.bus.partitions)
        )

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until fully caught up (tests / graceful handoff)."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.lag() == 0:
                return True
            time.sleep(0.002)
        return self.lag() == 0

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
