"""Threaded consumer group: parallel partition draining into the cache.

Role parity: ``geomesa-kafka/.../data/KafkaCacheLoader.scala:247`` +
``geomesa-kafka-utils/.../consumer/ThreadedConsumer.scala`` (SURVEY.md
§2.10): N consumer threads split a topic's partitions, poll batches, and
apply them to the shared live cache; per-key ordering is preserved because a
feature id always hashes to one partition. ``Clear`` is a cross-partition
barrier (the bus publishes it to every partition): consumers rendezvous on
it, one performs the clear, and only then does any partition move past it —
so a Put published after a Clear can never be wiped by it.
"""

from __future__ import annotations

import threading
from typing import Callable

__all__ = ["ThreadedConsumer"]


class ThreadedConsumer:
    """Drains a topic's partitions into ``apply`` on worker threads.

    ``apply(data: bytes, partition: int) -> bool | None`` must be
    thread-safe (the feature cache locks internally). Returning ``False``
    stalls that partition WITHOUT advancing its offset — the message is
    re-delivered on the next poll (used by cross-partition barriers; a
    stalled partition never blocks the thread, so one thread owning several
    partitions cannot deadlock a rendezvous). ``threads`` ≤ partitions; each
    thread owns a static partition subset (consumer-group assignment).

    Idle polling backs off ADAPTIVELY instead of spinning at
    ``poll_interval_s``: each empty round grows the sleep by the
    decorrelated-jitter schedule (``resilience.policy.RetryPolicy`` — the
    same jitter that spreads federated retry storms), capped at
    ``idle_max_s``, and any traffic resets it to the base — so a quiet
    topic costs ~10 polls/s instead of 500, while a busy one still drains
    at full rate. Per-topic lag and poll-rate gauges land in
    :mod:`geomesa_tpu.stream.telemetry`
    (``geomesa_stream_lag{topic}`` on ``/api/metrics?format=prometheus``).
    """

    def __init__(
        self,
        bus,
        topic: str,
        apply: Callable[[bytes, int], None],
        threads: int = 2,
        poll_interval_s: float = 0.002,
        idle_max_s: float = 0.1,
        durable_trim: bool = False,
    ):
        from geomesa_tpu.resilience.policy import RetryPolicy

        self.bus = bus
        self.topic = topic
        self.apply = apply
        self.poll_interval_s = poll_interval_s
        self.idle_max_s = idle_max_s
        # durable_trim: a CHECKPOINTED consumer (its applied offsets are
        # its checkpoint) also truncates the journal's disk HEAD below the
        # fully-applied prefix (JournalBus.trim_applied) so a long-lived
        # topic stops growing without bound — docs/streaming.md. Off by
        # default: head-trimming is destructive for other readers of the
        # same journal directory.
        self.durable_trim = bool(
            durable_trim and hasattr(bus, "enable_trim_tracking"))
        if self.durable_trim:
            bus.enable_trim_tracking(topic)
        self._trim_lock = None
        if self.durable_trim:
            import threading as _threading

            self._trim_lock = _threading.Lock()
        # jitter source only (next_delay); the retry machinery is unused
        self._idle = RetryPolicy(
            base_delay_s=poll_interval_s, max_delay_s=idle_max_s
        )
        n_parts = bus.partitions
        threads = max(1, min(threads, n_parts))
        self._assignments = [
            [p for p in range(n_parts) if p % threads == t] for t in range(threads)
        ]
        self._offsets = [0] * n_parts
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._run, args=(parts,), daemon=True,
                name=f"geomesa-consumer-{topic}-{t}",
            )
            for t, parts in enumerate(self._assignments)
        ]
        for t in self._threads:
            t.start()

    def _run(self, partitions: list[int]) -> None:
        import time as _time

        from geomesa_tpu.obs import trace as _trace
        from geomesa_tpu.stream import telemetry

        trim = getattr(self.bus, "trim", None)  # durable buses free applied
        delay: float | None = None
        next_lag_t = 0.0
        next_disk_trim_t = 0.0
        while not self._stop.is_set():
            drained = 0
            for p in partitions:
                batch = self.bus.poll(self.topic, p, self._offsets[p], max_n=256)
                applied = 0
                # one stream.poll span per non-empty batch: the ROOT the
                # device scanner's retroactive cut/stage/scan/deliver
                # spans stitch under — a traced ingest reads as ONE tree
                # (docs/streaming.md § Stream lens). NOOP when untraced:
                # the idle loop never pays a span allocation.
                sp = (_trace.span("stream.poll", topic=self.topic,
                                  partition=p, n=len(batch))
                      if batch else _trace.NOOP)
                with sp:
                    for data in batch:
                        if self.apply(data, p) is False:
                            break  # stalled at a barrier; redeliver next poll
                        self._offsets[p] += 1
                        applied += 1
                drained += applied
                if applied and trim is not None:
                    # bound the bus's in-memory window to unapplied messages
                    trim(self.topic, p, self._offsets[p])
            if drained and self.durable_trim:
                # throttled disk head-trim below the fully-applied prefix
                # (one rewrite per window, not per record); offsets read
                # outside locks are safe — trim_applied only advances over
                # records EVERY partition has applied, so a stale read can
                # only under-trim
                now = _time.monotonic()
                if now >= next_disk_trim_t and self._trim_lock.acquire(
                        blocking=False):
                    try:
                        next_disk_trim_t = now + 0.25
                        self.bus.trim_applied(self.topic, list(self._offsets))
                    finally:
                        self._trim_lock.release()
            if drained == 0:
                # decorrelated exponential backoff while idle; reset on
                # traffic (fixed 2 ms spins burned a core per quiet topic)
                delay = self._idle.next_delay(delay)
                telemetry.note_poll(self.topic, 0, delay)
                # lag is NOT necessarily 0 here: a partition stalled at a
                # barrier drains nothing while messages keep queueing —
                # but throttle like the busy branch: the first idle rounds
                # after traffic spin at the 2 ms base delay
                now = _time.monotonic()
                if now >= next_lag_t:
                    next_lag_t = now + 0.25
                    telemetry.set_lag(self.topic, self.lag())
                self._stop.wait(delay)
            else:
                delay = None
                telemetry.note_poll(self.topic, drained, 0.0)
                # lag() pays bus.end_offset per partition (a commit-sidecar
                # read on JournalBus) — a gauge doesn't need that on EVERY
                # busy round, so throttle it on the hot consume path
                now = _time.monotonic()
                if now >= next_lag_t:
                    next_lag_t = now + 0.25
                    telemetry.set_lag(self.topic, self.lag())

    def lag(self) -> int:
        """Unconsumed messages across partitions (backpressure signal)."""
        return sum(
            self.bus.end_offset(self.topic, p) - self._offsets[p]
            for p in range(self.bus.partitions)
        )

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until fully caught up (tests / graceful handoff)."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.lag() == 0:
                return True
            time.sleep(0.002)
        return self.lag() == 0

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
