"""In-process message bus + streaming datastore over live feature caches.

Role parity: ``geomesa-kafka/.../data/KafkaDataStore.scala:52,232,355`` and
``KafkaCacheLoader.scala`` (SURVEY.md §2.10): one topic per feature type;
writers publish serialized change messages; each consumer group materializes
the topic into a :class:`~geomesa_tpu.stream.cache.FeatureCache`; queries run
against the cache through the same vectorized filter machinery as the batch
store (the ``KafkaQueryRunner``-over-``LocalQueryRunner`` pattern). The bus is
in-process (partitions + offsets, synchronous dispatch) — the Kafka broker
role without a broker; swapping in a real bus only needs `publish`/`poll`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np

from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec
from geomesa_tpu.stream.cache import FeatureCache
from geomesa_tpu.stream.messages import Clear, Delete, GeoMessageSerializer, Put
from geomesa_tpu.store.datastore import QueryResult

__all__ = ["MessageBus", "StreamingDataStore"]


class MessageBus:
    """Minimal in-process topic bus: ordered log per topic + subscribers,
    plus per-partition logs for threaded consumer groups.

    Messages carry a partition tag (key-hash) for parity with the Kafka
    model. The synchronous ``subscribe`` path sees the totally-ordered log;
    the ``poll`` path (used by :class:`~geomesa_tpu.stream.consumer.
    ThreadedConsumer`) reads per-partition logs, where per-feature ordering
    holds because a fid always hashes to the same partition, and ``barrier``
    messages (Clear) are replicated into every partition so consumers can
    rendezvous on them.
    """

    def __init__(self, partitions: int = 4):
        self.partitions = partitions
        self._lock = threading.RLock()  # subscribe replays under the lock
        self._logs: dict[str, list[tuple[int, bytes]]] = {}
        self._plogs: dict[str, list[list[bytes]]] = {}
        self._subscribers: dict[str, list[Callable[[bytes], None]]] = {}

    def create_topic(self, topic: str) -> None:
        with self._lock:
            self._logs.setdefault(topic, [])
            self._plogs.setdefault(topic, [[] for _ in range(self.partitions)])

    def publish(
        self, topic: str, key: str, data: bytes, barrier: bool = False
    ) -> None:
        self.create_topic(topic)
        part = hash(key) % self.partitions if key else 0
        with self._lock:
            self._logs[topic].append((part, data))
            if barrier:
                for p in range(self.partitions):
                    self._plogs[topic][p].append(data)
            else:
                self._plogs[topic][part].append(data)
            subs = list(self._subscribers.get(topic, []))
        for cb in subs:
            cb(data)

    def subscribe(self, topic: str, callback: Callable[[bytes], None]) -> None:
        """Register a synchronous consumer; replays the log first (offset 0).

        Replay AND registration happen under the bus lock so a concurrent
        publish can neither sneak between them (delivering a new message
        before older backlog) nor be missed.
        """
        self.create_topic(topic)
        with self._lock:
            for _, data in self._logs[topic]:
                callback(data)
            self._subscribers.setdefault(topic, []).append(callback)

    def unsubscribe(self, topic: str, callback: Callable[[bytes], None]) -> bool:
        """Remove a subscriber registered via :meth:`subscribe`; missing
        registrations are a no-op (idempotent detach)."""
        with self._lock:
            subs = self._subscribers.get(topic, [])
            try:
                subs.remove(callback)
                return True
            except ValueError:
                return False

    # -- consumer-group (polling) API ---------------------------------------
    def poll(self, topic: str, partition: int, offset: int, max_n: int = 256):
        """Messages [offset, offset+max_n) of one partition's log."""
        self.create_topic(topic)
        with self._lock:
            log = self._plogs[topic][partition]
            return log[offset : offset + max_n]

    def end_offset(self, topic: str, partition: int) -> int:
        self.create_topic(topic)
        with self._lock:
            return len(self._plogs[topic][partition])

    def topic_size(self, topic: str) -> int:
        return len(self._logs.get(topic, []))


class StreamingDataStore:
    """Feature store over a message bus (``KafkaDataStore`` role).

    ``expiry_ms``: event-time expiry window for cached features (the
    reference's ``geomesa.kafka.expiry``); ``None`` keeps everything.
    """

    def __init__(
        self,
        bus: MessageBus | None = None,
        expiry_ms: int | None = None,
        async_consumers: int = 0,
    ):
        self.bus = bus if bus is not None else MessageBus()
        self.expiry_ms = expiry_ms
        self.async_consumers = async_consumers
        self._types: dict[str, FeatureType] = {}
        # any serialize/deserialize codec (GeoMessageSerializer or the
        # schema-registry Avro codec from stream/confluent.py)
        self._serializers: dict[str, Any] = {}
        self._caches: dict[str, FeatureCache] = {}
        self._consumers: dict[str, object] = {}
        # standing-query hubs (subscribe_query), one per type — the shared
        # HubRegistry owns the subscribe-before-attach ordering and the
        # leaf-lock discipline (stream/pipeline.py, jax-free at import)
        from geomesa_tpu.stream.pipeline import HubRegistry

        self._hubs = HubRegistry()

    # -- schema --------------------------------------------------------------
    def create_schema(
        self,
        sft: FeatureType | str,
        spec: str | None = None,
        serializer=None,
    ) -> FeatureType:
        """``serializer`` overrides the default binary codec — e.g. an
        :class:`~geomesa_tpu.stream.confluent.AvroGeoMessageSerializer` for
        schema-registry interop (any object with the same
        serialize/deserialize surface plugs in)."""
        if isinstance(sft, str):
            sft = parse_spec(sft, spec)
        if sft.name in self._types:
            raise ValueError(f"schema already exists: {sft.name}")
        bound = getattr(serializer, "sft", sft)
        if bound is not sft and getattr(bound, "to_spec", lambda: 1)() != sft.to_spec():
            raise ValueError(
                f"serializer is bound to schema {getattr(bound, 'name', '?')!r}, "
                f"not {sft.name!r}"
            )
        self._types[sft.name] = sft
        self._serializers[sft.name] = (
            serializer if serializer is not None else GeoMessageSerializer(sft)
        )
        cache = FeatureCache(sft, expiry_ms=self.expiry_ms)
        self._caches[sft.name] = cache
        ser = self._serializers[sft.name]

        if self.async_consumers > 0:
            # parallel partition draining (KafkaCacheLoader role): Clear is a
            # cross-partition barrier — each partition STALLS at its barrier
            # copy (offset not advanced, no thread blocking); the last
            # partition to arrive performs the clear and bumps the barrier
            # generation, and stalled partitions pass on redelivery
            from geomesa_tpu.stream.consumer import ThreadedConsumer

            n_parts = self.bus.partitions
            bstate = {"gen": 0, "arrived": {}}
            blk = threading.Lock()

            def apply(data: bytes, partition: int, _cache=cache, _ser=ser):
                msg = _ser.deserialize(data)
                if isinstance(msg, Put):
                    _cache.put(msg.fid, msg.record, msg.ts)
                    return True
                if isinstance(msg, Delete):
                    _cache.delete(msg.fid)
                    return True
                if isinstance(msg, Clear):
                    with blk:
                        g = bstate["arrived"].get(partition)
                        if g is not None and g < bstate["gen"]:
                            del bstate["arrived"][partition]  # resolved
                            return True
                        if g is None:
                            bstate["arrived"][partition] = bstate["gen"]
                        full = len(bstate["arrived"]) == n_parts and all(
                            v == bstate["gen"] for v in bstate["arrived"].values()
                        )
                        if full:
                            _cache.clear()
                            bstate["gen"] += 1
                            del bstate["arrived"][partition]
                            return True
                        return False
                return True

            self._consumers[sft.name] = ThreadedConsumer(
                self.bus, self._topic(sft.name), apply,
                threads=self.async_consumers,
            )
            return sft

        def consume(data: bytes, _cache=cache, _ser=ser):
            msg = _ser.deserialize(data)
            if isinstance(msg, Put):
                _cache.put(msg.fid, msg.record, msg.ts)
            elif isinstance(msg, Delete):
                _cache.delete(msg.fid)
            elif isinstance(msg, Clear):
                _cache.clear()

        self.bus.subscribe(self._topic(sft.name), consume)
        return sft

    def consumer(self, type_name: str):
        """The ThreadedConsumer for a type (None on the synchronous path)."""
        return self._consumers.get(type_name)

    # -- standing queries (fused device scan) ---------------------------------
    def subscribe_query(self, type_name: str, predicate, callback,
                        **hub_cfg) -> int:
        """Register a STANDING query: ``callback`` receives a
        :class:`~geomesa_tpu.stream.matrix.HitBatch` (count delta + newest
        matched rows) for every appended batch that matches ``predicate``
        (bbox + time-window CQL, decomposed through the planner).

        Unlike per-row host callbacks, all standing queries of a type are
        evaluated together as ONE fused ``(rows × queries)`` device pass
        per chunk (:class:`~geomesa_tpu.stream.pipeline.SubscriptionHub`
        feeding a :class:`~geomesa_tpu.stream.pipeline.DeviceStreamScanner`);
        the first subscription replays the topic backlog through the
        scanner (the bus ``subscribe`` contract), so historical matches
        deliver too. Backpressure is observational: the hub's ``lag()``
        plus the consumer-group ``lag()`` upstream (docs/streaming.md).
        Returns the subscription id."""
        sft = self._types[type_name]
        from geomesa_tpu.stream.pipeline import SubscriptionHub

        topic = self._topic(type_name)

        def attach(hub):
            self.bus.subscribe(topic, hub.ingest)
            # detach handle: close_all stops a shared/reused bus from
            # feeding the closed scanner
            return lambda: self.bus.unsubscribe(topic, hub.ingest)

        return self._hubs.subscribe(
            type_name, predicate, callback,
            make_hub=lambda: SubscriptionHub(
                sft, self._serializers[type_name], topic=topic, **hub_cfg
            ),
            attach=attach,
            cfg=hub_cfg,
        )

    def unsubscribe_query(self, type_name: str, sid: int) -> bool:
        return self._hubs.unsubscribe(type_name, sid)

    def query_hub(self, type_name: str):
        """The type's SubscriptionHub (None before any subscribe_query)."""
        return self._hubs.get(type_name)

    def drain(self, type_name: str, timeout_s: float = 10.0) -> bool:
        """Wait until every published message is VISIBLE end to end: the
        bus tailer has delivered it (``JournalBus.tail_lag`` — an async
        bus dispatches push callbacks from a background thread, so
        ``query``/standing-query deliveries otherwise race the tail),
        async consumers have applied it, and the type's standing-query
        hub (if any) has scanned it."""
        deadline = time.monotonic() + timeout_s
        tail_lag = getattr(self.bus, "tail_lag", None)
        if tail_lag is not None:
            topic = self._topic(type_name)
            while tail_lag(topic) > 0:
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.002)
        c = self._consumers.get(type_name)
        if c is not None and not c.drain(
            max(deadline - time.monotonic(), 0.0)
        ):
            return False
        hub = self.query_hub(type_name)
        if hub is not None and not hub.drain(
            max(deadline - time.monotonic(), 0.0)
        ):
            return False
        return True

    def close(self) -> None:
        self._hubs.close_all()
        for c in self._consumers.values():
            c.close()
        self._consumers.clear()
        # a bus with background machinery (JournalBus tailer) shuts down
        # with the store; the in-process MessageBus has no close
        closer = getattr(self.bus, "close", None)
        if closer is not None:
            closer()

    def get_schema(self, name: str) -> FeatureType:
        return self._types[name]

    def list_schemas(self) -> list[str]:
        return sorted(self._types)

    @staticmethod
    def _topic(type_name: str) -> str:
        return f"geomesa-{type_name}"

    # -- writes (publish change messages) ------------------------------------
    def put(self, type_name: str, fid: str, record: dict, ts: int | None = None) -> None:
        ser = self._serializers[type_name]
        ts = int(time.time() * 1000) if ts is None else ts
        self.bus.publish(self._topic(type_name), fid, ser.serialize(Put(fid, record, ts)))

    def delete(self, type_name: str, fid: str, ts: int | None = None) -> None:
        ser = self._serializers[type_name]
        ts = int(time.time() * 1000) if ts is None else ts
        self.bus.publish(self._topic(type_name), fid, ser.serialize(Delete(fid, ts)))

    def clear(self, type_name: str, ts: int | None = None) -> None:
        ser = self._serializers[type_name]
        ts = int(time.time() * 1000) if ts is None else ts
        # barrier=True replicates the Clear into every partition so the
        # threaded consumer group can rendezvous on it
        self.bus.publish(
            self._topic(type_name), "", ser.serialize(Clear(ts)), barrier=True
        )

    # -- reads (KafkaQueryRunner role) ---------------------------------------
    def cache(self, type_name: str) -> FeatureCache:
        return self._caches[type_name]

    def query(
        self,
        type_name: str,
        q: Query | str | None = None,
        now_ms: int | None = None,
        **kwargs,
    ) -> QueryResult:
        sft = self._types[type_name]
        cache = self._caches[type_name]
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        cache.expire(now_ms)
        if isinstance(q, str) or q is None:
            q = Query(filter=q, **kwargs)

        f = q.resolved_filter()

        # bbox pre-filter through the live spatial index when the filter has
        # spatial bounds; otherwise all current states are candidates
        from geomesa_tpu.filter.bounds import extract

        e = extract(f, sft.geom_field, sft.dtg_field)
        if e.boxes:
            seen: dict[str, object] = {}
            for b in e.boxes:
                for s in cache.query_bbox(b):
                    seen[s.fid] = s
            states = list(seen.values())
        else:
            states = list(cache.states())

        states.sort(key=lambda s: s.fid)
        fids = [s.fid for s in states]
        table = FeatureTable.from_records(sft, [s.record for s in states], fids)
        mask = f.mask(table)
        rows = np.nonzero(mask)[0]
        table = table.take(rows)

        # same post-scan pipeline as the batch store (visibility, sampling,
        # aggregation hints, sort/limit/projection/CRS)
        from geomesa_tpu.store.reduce import reduce_result

        table, rows, density, stats_out, bin_data = reduce_result(sft, table, rows, q)
        return QueryResult(
            table, rows, density=density, stats=stats_out, bin_data=bin_data
        )
