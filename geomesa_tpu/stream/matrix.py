"""Subscription matrix: Q standing queries as ONE ``(rows × queries)``
device problem.

The streaming tier's old delivery path evaluated every standing predicate
per row on the host (``stream/datastore.py`` ``consume`` callbacks) — at Q
concurrent subscriptions each appended row paid Q python predicate
evaluations, which is exactly where BENCH_r05's 1B-row streaming scan fell
to 0.1× the CPU baseline. The many-core evaluation in PAPERS.md shows
batch-parallel filter evaluation is where wide hardware dominates, so this
module turns the subscription set into device-resident QUERY MATRICES:

- every standing query decomposes (``planning.planner.standing_query_payload``
  — the same planner bounds extraction and ``pack_boxes``/``pack_times``
  int-domain encoding the batched count kernels already consume) into one
  row of a packed ``(capacity, B, 4)`` box matrix and ``(capacity, T, 4)``
  time matrix;
- capacity is a POWER-OF-TWO BUCKET (tpulint J003): subscription add and
  remove rewrite rows in place — inactive slots hold an unsatisfiable
  sentinel payload, so membership churn never changes the compiled step's
  shapes, and only crossing a bucket boundary compiles a new (cached,
  per-bucket) executable. The jaxmon recompile census pins the steady
  path at ZERO recompiles (tests/test_stream_matrix.py).
- a scan is one fused count+gather pass
  (:func:`geomesa_tpu.parallel.query.cached_matrix_scan_step` /
  ``ops.pallas_kernels.batched_count_hits``): per-subscription match
  counts (exact) AND a newest-match row-position sample come back from a
  single pass over the chunk.

Semantics: deliveries are INT-DOMAIN matches — the same superset-at-
quantization-boundaries contract as every other int payload in the tree
(``ops/refine.py``). Counts are exact in that domain and byte-equal to a
per-query referee scan over identical payloads.

Locking: ``SubscriptionMatrix._lock`` is a LEAF (docs/concurrency.md) —
device uploads and the scan dispatch run strictly OUTSIDE it; scans use
an immutable snapshot so subscription churn during a scan affects the
NEXT chunk, never a half-applied current one.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from geomesa_tpu.analysis.contracts import cache_surface, dispatch_budget

__all__ = ["SubscriptionMatrix", "HitBatch", "MatrixSnapshot",
           "envelope_hit", "envelope_hits"]

DEFAULT_BOX_SLOTS = 2
DEFAULT_TIME_SLOTS = 2
DEFAULT_TOPK = 64
MIN_CAPACITY = 8


def _unsat_rows(box_slots: int, time_slots: int):
    """The inactive-slot payload: every slot unsatisfiable, so a masked
    slot matches nothing while keeping the matrix shape — and therefore
    the compiled step — fixed. One shared sentinel definition
    (``ops.refine.unsat_rows``, also the planner's disjoint branch)."""
    from geomesa_tpu.ops.refine import unsat_rows

    return unsat_rows(box_slots, time_slots)


def envelope_hit(boxes: np.ndarray, times: np.ndarray, ix1: int, ix2: int,
                 iy1: int, iy2: int, b: int, o: int) -> bool:
    """Host-side int-domain test of one EXTENDED feature — normalized
    envelope ``[ix1, ix2] × [iy1, iy2]`` at time instant ``(bin, offset)``
    — against one subscription's packed payload.

    The device kernel tests point containment; an extended geometry needs
    bbox OVERLAP (its envelope may straddle a query box whose interior
    its center never enters), so the scanner routes these few rows here
    (``DeviceStreamScanner`` wide-row refine) — the point kernel's
    containment widened to envelope overlap, still a superset in the int
    domain. Empty slots (lo > hi: the unsatisfiable sentinel / the
    ``pack_boxes`` pad) are SKIPPED rather than compared — ``[1, 0, 1, 0]``
    is empty under containment but an envelope spanning that corner would
    "overlap" it. Time uses the kernel's exact (bin, offset) window
    comparisons."""
    return bool(envelope_hits(
        boxes, times,
        np.asarray([ix1]), np.asarray([ix2]),
        np.asarray([iy1]), np.asarray([iy2]),
        np.asarray([b]), np.asarray([o]),
    )[0])


def envelope_hits(boxes: np.ndarray, times: np.ndarray,
                  ix1: np.ndarray, ix2: np.ndarray,
                  iy1: np.ndarray, iy2: np.ndarray,
                  b: np.ndarray, o: np.ndarray) -> np.ndarray:
    """Vectorized :func:`envelope_hit`: all W wide rows of a chunk against
    one subscription's payload in O(slots) numpy passes — a W-length bool
    mask, never W×slots interpreted comparisons (the scan thread calls
    this once per subscription per chunk)."""
    in_box = np.zeros(len(ix1), bool)
    for xlo, xhi, ylo, yhi in boxes:
        if xlo > xhi or ylo > yhi:
            continue  # empty slot — never an overlap candidate
        in_box |= (ix1 <= xhi) & (ix2 >= xlo) & (iy1 <= yhi) & (iy2 >= ylo)
    if not in_box.any():
        return in_box
    in_time = np.zeros(len(ix1), bool)
    for blo, olo, bhi, ohi in times:
        after = (b > blo) | ((b == blo) & (o >= olo))
        before = (b < bhi) | ((b == bhi) & (o <= ohi))
        in_time |= after & before
    return in_box & in_time


@dataclass(frozen=True)
class HitBatch:
    """One subscription's delivery for one scanned chunk."""

    sid: int
    predicate: object  # the subscribed predicate (CQL text / Query / None)
    count: int  # matches in this chunk — the count DELTA
    total: int  # cumulative matches delivered to this subscription
    positions: np.ndarray  # newest-match global stream row positions (≤ topk)
    tags: list | None  # caller row tags (fids) for ``positions``, if kept
    chunk: int  # chunk sequence number
    base: int  # global stream position of this chunk's row 0
    rows: int  # true rows in this chunk


class _Sub:
    __slots__ = ("sid", "predicate", "callback", "boxes", "times", "tenant")

    def __init__(self, sid, predicate, callback, boxes, times, tenant=None):
        self.sid = sid
        self.predicate = predicate
        self.callback = callback
        self.boxes = boxes
        self.times = times
        # tenant stamped at subscribe time (usage metering of standing
        # deliveries); None = unmetered (direct matrix users, shadow-plane
        # subscribers)
        self.tenant = tenant


@dataclass(frozen=True)
class MatrixSnapshot:
    """Immutable view of the matrix at one epoch: the scan-side contract.

    ``sids[slot]`` maps matrix row → subscription id (None = masked);
    ``subs`` resolves ids to callbacks. Device arrays are uploaded once
    per epoch and reused until the next membership change — a steady
    matrix pays ZERO h2d per chunk."""

    epoch: int
    capacity: int
    sids: tuple
    subs: dict
    boxes_dev: object
    times_dev: object


@cache_surface(name="matrix-device-mirror", keyed_by="epoch",
               epoch="monotonic")
class SubscriptionMatrix:
    """Registry of standing queries materialized as device query matrices.

    ``sft`` drives predicate decomposition (:meth:`subscribe`); pass
    ``None`` when only pre-packed payloads are registered
    (:meth:`subscribe_packed` — the bench path, whose rows are already
    normalized ints). ``box_slots``/``time_slots`` are the per-subscription
    payload widths (compile-time shapes; a predicate with more boxes
    collapses to its envelope — still a superset)."""

    def __init__(self, sft=None, mesh=None, box_slots: int = DEFAULT_BOX_SLOTS,
                 time_slots: int = DEFAULT_TIME_SLOTS, topk: int = DEFAULT_TOPK,
                 min_capacity: int = MIN_CAPACITY, impl: str = "auto"):
        if min_capacity < 1 or (min_capacity & (min_capacity - 1)):
            raise ValueError("min_capacity must be a power of two")
        if topk < 1:
            raise ValueError("topk must be >= 1")
        self.sft = sft
        self._mesh = mesh
        self.box_slots = box_slots
        self.time_slots = time_slots
        self.topk = topk
        self.min_capacity = min_capacity
        self.impl = impl
        self._unsat_boxes, self._unsat_times = _unsat_rows(
            box_slots, time_slots
        )
        self._lock = threading.Lock()  # leaf — see module docstring
        self._subs: dict[int, _Sub] = {}
        self._slots: list[int | None] = [None] * min_capacity
        self._boxes = np.tile(self._unsat_boxes[None], (min_capacity, 1, 1))
        self._times = np.tile(self._unsat_times[None], (min_capacity, 1, 1))
        self._epoch = 0
        self._dev: tuple | None = None  # (epoch, boxes_dev, times_dev)
        self._next_sid = 1

    @property
    def mesh(self):
        if self._mesh is None:
            from geomesa_tpu.parallel.mesh import default_mesh

            self._mesh = default_mesh()
        return self._mesh

    # -- registry -------------------------------------------------------------
    def subscribe(self, predicate, callback, tenant=None) -> int:
        """Register a standing query (CQL / filter AST / Query); returns the
        subscription id. The predicate decomposes through the planner into
        this matrix's packed row encoding. ``tenant`` (stamped by the
        standing-query front doors) attributes deliveries in the usage
        meter; None leaves them unmetered."""
        if self.sft is None:
            raise ValueError(
                "matrix built without an sft: use subscribe_packed"
            )
        from geomesa_tpu.planning.planner import standing_query_payload

        boxes, times = standing_query_payload(
            self.sft, predicate, self.box_slots, self.time_slots
        )
        return self._add(predicate, callback, boxes, times, tenant)

    def subscribe_packed(self, boxes, times, callback,
                         predicate=None, tenant=None) -> int:
        """Register a pre-packed int-domain payload: ``boxes (≤box_slots,
        4)``, ``times (≤time_slots, 4)`` int32 (the
        ``pack_boxes``/``pack_times`` row encoding)."""
        from geomesa_tpu.ops.refine import pack_boxes, pack_times

        b = np.asarray(boxes, np.int32).reshape(-1, 4)
        t = np.asarray(times, np.int32).reshape(-1, 4)
        return self._add(
            predicate, callback,
            pack_boxes(b, slots=self.box_slots),
            pack_times(t, slots=self.time_slots),
            tenant,
        )

    def _add(self, predicate, callback, boxes, times, tenant=None) -> int:
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            try:
                slot = self._slots.index(None)
            except ValueError:
                slot = len(self._slots)
                self._grow_locked()
            sub = _Sub(sid, predicate, callback, boxes, times, tenant)
            self._subs[sid] = sub
            self._slots[slot] = sid
            self._boxes[slot] = boxes
            self._times[slot] = times
            self._epoch += 1
            self._dev = None
        return sid

    def unsubscribe(self, sid: int) -> bool:
        """Deactivate a subscription: its slot is masked with the
        unsatisfiable payload (no shape change); the bucket shrinks —
        compacting live rows into the next-smaller power of two — once
        occupancy falls to a quarter."""
        with self._lock:
            sub = self._subs.pop(sid, None)
            if sub is None:
                return False
            slot = self._slots.index(sid)
            self._slots[slot] = None
            self._boxes[slot] = self._unsat_boxes
            self._times[slot] = self._unsat_times
            cap = len(self._slots)
            if cap > self.min_capacity and len(self._subs) <= cap // 4:
                self._shrink_locked(cap // 2)
            self._epoch += 1
            self._dev = None
        return True

    def _grow_locked(self) -> None:
        cap = len(self._slots)
        new_cap = cap * 2
        boxes = np.tile(self._unsat_boxes[None], (new_cap, 1, 1))
        times = np.tile(self._unsat_times[None], (new_cap, 1, 1))
        boxes[:cap] = self._boxes
        times[:cap] = self._times
        self._boxes, self._times = boxes, times
        self._slots.extend([None] * cap)

    def _shrink_locked(self, new_cap: int) -> None:
        new_cap = max(new_cap, self.min_capacity)
        boxes = np.tile(self._unsat_boxes[None], (new_cap, 1, 1))
        times = np.tile(self._unsat_times[None], (new_cap, 1, 1))
        slots: list[int | None] = [None] * new_cap
        i = 0
        for sid in self._slots:
            if sid is None:
                continue
            slots[i] = sid
            boxes[i] = self._subs[sid].boxes
            times[i] = self._subs[sid].times
            i += 1
        self._boxes, self._times, self._slots = boxes, times, slots

    def capacity(self) -> int:
        with self._lock:
            return len(self._slots)

    def active_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def slot_bytes(self) -> int:
        """Device bytes ONE subscription slot occupies: its packed box and
        time rows, 4 int32 coordinates each — the stream lens's HBM
        bytes-per-subscription figure (extrapolated ×1M in the scale
        report's capacity section)."""
        return (self.box_slots + self.time_slots) * 4 * 4

    def standing(self) -> list:
        """``[(sid, predicate), ...]`` for every active subscription —
        the auditor's standing-count cross-check surface."""
        with self._lock:
            return [(sid, sub.predicate) for sid, sub in self._subs.items()]

    def validate_sentinels(self) -> list[str]:
        """Invariant-sweep surface (obs/audit.py): every MASKED slot must
        hold the unsatisfiable sentinel payload — a freed slot that
        could still match would deliver ghost hits against whatever
        subscription later reuses it. Returns violation strings."""
        out: list[str] = []
        with self._lock:
            for slot, sid in enumerate(self._slots):
                if sid is not None:
                    continue
                if not (np.array_equal(self._boxes[slot], self._unsat_boxes)
                        and np.array_equal(self._times[slot],
                                           self._unsat_times)):
                    out.append(f"slot {slot}: masked but payload differs "
                               "from the unsat sentinel")
                    continue
                # defense in depth: the sentinel itself must be
                # unsatisfiable — every box slot empty (xlo > xhi), so no
                # row can pass the spatial test whatever the time rows say
                b = self._boxes[slot]
                if not (b[:, 0] > b[:, 1]).all():
                    out.append(f"slot {slot}: sentinel box rows satisfiable")
        return out

    # -- scan side ------------------------------------------------------------
    def snapshot(self) -> MatrixSnapshot:
        """The scan-side view: slot→sid map plus device-resident query
        matrices. The device upload happens OUTSIDE the matrix lock (jax
        dispatch never runs under it) and is cached per epoch, so a steady
        subscription set stages its matrices exactly once."""
        with self._lock:
            epoch = self._epoch
            cap = len(self._slots)
            sids = tuple(self._slots)
            subs = {sid: self._subs[sid] for sid in sids if sid is not None}
            dev = self._dev if (self._dev and self._dev[0] == epoch) else None
            host = None if dev else (self._boxes.copy(), self._times.copy())
        if dev is None:
            import jax.numpy as jnp

            from geomesa_tpu.obs.jaxmon import count_h2d

            host_b, host_t = host
            # matrix uploads belong to the STREAM, not to whichever query
            # happens to be profiled concurrently (ISSUE 7's pool rule)
            count_h2d(host_b, host_t, label="stream")
            dev = (epoch, jnp.asarray(host_b), jnp.asarray(host_t))
            with self._lock:
                if self._epoch == epoch:
                    self._dev = dev
        return MatrixSnapshot(
            epoch=epoch, capacity=cap, sids=sids, subs=subs,
            boxes_dev=dev[1], times_dev=dev[2],
        )

    @dispatch_budget(1)
    def scan_chunk(self, snapshot: MatrixSnapshot, x, y, bins, offs, true_n):
        """One fused pass of staged device columns against the snapshot's
        matrices → ``(counts (cap,) int64, positions (cap, D, topk))``
        materialized on host. Callers map slot → sid via the snapshot."""
        from geomesa_tpu.parallel.query import cached_matrix_scan_step

        step = cached_matrix_scan_step(
            self.mesh, self.topk, snapshot.capacity, self.impl
        )
        counts, pos = step(
            x, y, bins, offs, true_n, snapshot.boxes_dev, snapshot.times_dev
        )
        return np.asarray(counts).astype(np.int64), np.asarray(pos)

    def scan_host(self, x, y, bins, offs):
        """Convenience single-shot scan of HOST int32 columns (tests, small
        batches): pads/shards, runs the fused pass, returns ``(snapshot,
        counts, positions (cap, ≤topk) per-slot matched positions, newest
        first)``. The production streaming path uses
        :class:`~geomesa_tpu.stream.pipeline.DeviceStreamScanner` instead,
        which double-buffers transfers."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from geomesa_tpu.obs.jaxmon import count_h2d
        from geomesa_tpu.ops.pallas_kernels import LANES
        from geomesa_tpu.parallel.mesh import DATA_AXIS, data_shards

        n = len(x)
        shards = data_shards(self.mesh)
        unit = shards * LANES
        padded = ((max(n, 1) + unit - 1) // unit) * unit
        cols = []
        for a in (x, y, bins, offs):
            a = np.asarray(a, np.int32)
            if padded != n:
                a = np.concatenate(
                    [a, np.zeros(padded - n, np.int32)]
                )
            cols.append(a)
        count_h2d(*cols, label="stream")
        sh = NamedSharding(self.mesh, P(DATA_AXIS))
        dev = [jax.device_put(a, sh) for a in cols]
        snap = self.snapshot()
        counts, pos = self.scan_chunk(snap, *dev, jnp.int32(n))
        merged = [merge_positions(pos[s], self.topk)
                  for s in range(snap.capacity)]
        return snap, counts, merged


def merge_positions(pos_shards: np.ndarray, topk: int) -> np.ndarray:
    """Merge one slot's per-shard position lanes ``(D, topk)`` into the
    newest-first global sample (drop -1 pads, descending, ≤ topk)."""
    p = pos_shards.reshape(-1)
    p = p[p >= 0]
    if len(p) > 1:
        p = np.sort(p)[::-1]
    return p[:topk]
