"""Cross-host journal client: the Kafka-broker-over-the-network role.

Role parity: the reference streams between hosts through Kafka brokers
(``geomesa-kafka/.../KafkaDataStore.scala:52``); here the broker is another
process's :class:`~geomesa_tpu.stream.journal.JournalBus` exposed over
``/api/journal`` (:mod:`geomesa_tpu.web.app`). :class:`RemoteJournal`
implements the :class:`~geomesa_tpu.stream.datastore.MessageBus` surface —
``publish`` / ``poll`` / ``end_offset`` / ``subscribe`` / ``partitions`` —
so a :class:`~geomesa_tpu.stream.datastore.StreamingDataStore` on a host
with NO shared mount consumes another host's live stream unchanged:

    bus = RemoteJournal("http://feeder:8080")
    store = StreamingDataStore(bus=bus)          # tails the remote topics

``subscribe`` tails the TOTAL-ORDER log (the journal's on-disk frame
order), matching the in-process bus's synchronous-subscriber semantics —
barriers included exactly once. The per-partition ``poll`` path is the
consumer-group protocol (per-key ordering, barriers replicated per
partition), identical to the local ``JournalBus`` contract.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.parse
from typing import Callable

from geomesa_tpu.obs import trace as _trace
from geomesa_tpu.resilience import http as rhttp
from geomesa_tpu.resilience.policy import CircuitBreaker, RetryPolicy

__all__ = ["RemoteJournal"]


class RemoteJournal:
    """MessageBus-surface client over a remote ``/api/journal`` endpoint.

    Resilience (docs/resilience.md): every round trip runs through the
    shared HTTP choke point with this client's ``retry`` policy and
    per-endpoint ``breaker``; the subscriber tail loop additionally backs
    off between retry-exhausted rounds with the policy's
    decorrelated-jitter schedule (NOT a fixed sleep — a hard-down broker
    must not be hammered at poll frequency) and surfaces its health
    through ``metrics``: ``remote_journal.consecutive_failures`` /
    ``remote_journal.healthy`` gauges and a
    ``remote_journal.transient_errors`` counter, alongside the
    ``last_error`` attribute."""

    def __init__(self, base_url: str, timeout_s: float = 30.0,
                 poll_interval_s: float = 0.1,
                 retry: RetryPolicy | None = None,
                 breaker: CircuitBreaker | None = None,
                 metrics=None):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.poll_interval_s = poll_interval_s
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = (
            breaker if breaker is not None
            else CircuitBreaker(endpoint=self.base_url)
        )
        if metrics is None:
            from geomesa_tpu.utils.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        self.metrics.gauge("remote_journal.healthy").set(1.0)
        self._partitions: int | None = None
        self._stop = threading.Event()
        self._tailers: list[threading.Thread] = []
        # last transport error seen by any tailer (None = healthy); a 4xx
        # stops that tail — see subscribe()
        self.last_error: Exception | None = None
        # retry-exhausted rounds since the last good poll (mirrored in the
        # consecutive_failures gauge); several topic tailers share it, so
        # the read-modify-write is guarded (leaf lock, metrics tier)
        self._health_lock = threading.Lock()
        self.consecutive_failures = 0

    # -- plumbing ------------------------------------------------------------
    def _url(self, topic: str, op: str) -> str:
        return (f"{self.base_url}/api/journal/"
                f"{urllib.parse.quote(topic, safe='')}/{op}")

    def _get(self, topic: str, op: str, **params) -> dict:
        # map_errors=False: subscribe() classifies raw HTTPError codes
        # (4xx = misconfiguration stops the tail) — don't pre-map them
        raw = rhttp.request(
            "GET", self._url(topic, op), params=params or None,
            timeout_s=self.timeout_s, retry=self.retry,
            breaker=self.breaker, idempotent=True, map_errors=False,
        )
        return json.loads(raw)

    # -- MessageBus surface --------------------------------------------------
    @property
    def partitions(self) -> int:
        if self._partitions is None:
            # any topic name works: /end answers with the bus-wide count
            self._partitions = int(self._get("_", "end")["partitions"])
        return self._partitions

    def create_topic(self, topic: str) -> None:
        """Topics materialize on first publish server-side; nothing to do."""

    def publish(self, topic: str, key: str, data: bytes,
                barrier: bool = False) -> None:
        # a MUTATION: idempotent=False retries only connect-before-send
        # failures (replaying a publish the broker already appended would
        # duplicate the record)
        rhttp.request(
            "POST", self._url(topic, "publish"),
            body={
                "key": key,
                "data_b64": base64.b64encode(data).decode(),
                "barrier": barrier,
            },
            timeout_s=self.timeout_s, retry=self.retry,
            breaker=self.breaker, idempotent=False, map_errors=False,
        )

    def poll(self, topic: str, partition: int, offset: int,
             max_n: int = 256) -> list[bytes]:
        out = self._get(topic, "poll", partition=partition, offset=offset,
                        max_n=max_n)
        return [base64.b64decode(p) for p in out["payloads"]]

    def end_offset(self, topic: str, partition: int) -> int:
        return int(self._get(topic, "end", partition=partition)["end"])

    def topic_size(self, topic: str) -> int:
        return int(self._get(topic, "end")["size"])

    def total_poll(self, topic: str, offset: int,
                   max_n: int = 256) -> list[bytes]:
        out = self._get(topic, "tpoll", offset=offset, max_n=max_n)
        return [base64.b64decode(p) for p in out["payloads"]]

    def total_poll_cursor(self, topic: str,
                          cursor: int) -> tuple[list[bytes], int]:
        """Byte-cursor total-order tail: (payloads, next cursor). Each call
        reads only new journal bytes server-side — the long-lived
        subscriber path (start at 0, pass the returned cursor back)."""
        out = self._get(topic, "tpoll", cursor=cursor)
        return [base64.b64decode(p) for p in out["payloads"]], int(out["cursor"])

    def subscribe(self, topic: str, callback: Callable[[bytes], None]) -> None:
        """Tail the remote topic's total-order log from the start (replay,
        then live) on a daemon thread — the in-process bus's subscriber
        contract across the HTTP boundary. Callback errors drop that
        record for that subscriber (same at-most-once posture as the
        journal's tailer), never the tail itself.

        Transport failures are NOT silently absorbed: a configuration
        error (HTTP 4xx — e.g. the server has no journal attached) stops
        the tail immediately, and any transport error is recorded on
        ``self.last_error`` AND in metrics
        (``remote_journal.consecutive_failures`` gauge /
        ``remote_journal.transient_errors`` counter); ``healthy()`` is
        the liveness signal. Transient 5xx/connection errors keep
        retrying with the policy's decorrelated-jitter backoff between
        rounds (each round already retried ``retry.max_attempts`` times
        inside the transport).

        Tracing: the tail session owns ONE stable root span
        (``journal.tail``) for its whole lifetime — per-poll RPC spans
        nest under it and consecutive-failure/backoff state attaches as
        span EVENTS, instead of every poll minting a fresh orphan root
        that floods the trace buffer. Old poll children are trimmed so a
        long-lived session's tree stays bounded."""

        def _note_failure(e: Exception, session, delay_s: float | None) -> int:
            with self._health_lock:
                self.last_error = e
                self.consecutive_failures += 1
                n = self.consecutive_failures
            self.metrics.counter("remote_journal.transient_errors").inc()
            self.metrics.gauge("remote_journal.consecutive_failures").set(
                float(n))
            self.metrics.gauge("remote_journal.healthy").set(0.0)
            session.event(
                "tail_error", error=type(e).__name__, consecutive=n,
                backoff_ms=round((delay_s or 0.0) * 1000.0, 2))
            return n

        def _tail() -> None:
            import urllib.error

            cursor = 0
            delay: float | None = None
            polls = 0
            failing = False
            # the session's stable root span: this thread's context is
            # empty, so it IS a root; it closes (and lands in the trace
            # buffer) when the tail stops. Managed manually (not `with`)
            # because tracing may be enabled mid-session — the loop then
            # opens the session LATE, so per-poll rpc spans still nest
            # under one root instead of flooding the buffer as orphans.
            session = _trace.span("journal.tail", topic=topic,
                                  endpoint=self.base_url)
            session.__enter__()
            try:

                def _trim() -> None:
                    # bound the long-lived tree on BOTH the healthy and
                    # the failing path — a days-long outage appends one
                    # rpc child + one tail_error event per round, so the
                    # trim must not hide behind a successful poll
                    # (single-writer trim; exporters snapshot via list())
                    if isinstance(session, _trace.Span):
                        if len(session.children) > 64:
                            del session.children[:-64]
                        if len(session.events) > 128:
                            del session.events[:-128]

                while not self._stop.is_set():
                    if session is _trace.NOOP and _trace.enabled():
                        # tracing turned on mid-session: open the stable
                        # root NOW (this thread's context is still empty)
                        session = _trace.span(
                            "journal.tail", topic=topic,
                            endpoint=self.base_url)
                        session.__enter__()
                    try:
                        batch, cursor = self.total_poll_cursor(topic, cursor)
                        polls += 1
                        with self._health_lock:
                            self.last_error = None
                            self.consecutive_failures = 0
                        self.metrics.gauge(
                            "remote_journal.consecutive_failures").set(0.0)
                        self.metrics.gauge("remote_journal.healthy").set(1.0)
                        delay = None
                        if failing:
                            failing = False
                            session.event("tail_recovered", polls=polls)
                        if isinstance(session, _trace.Span):
                            session.set(polls=polls, cursor=cursor)
                        _trim()
                    except urllib.error.HTTPError as e:
                        # 4xx = misconfiguration (wrong server, no
                        # journal): retrying forever would just look like
                        # an idle stream
                        failing = True
                        if 400 <= e.code < 500:
                            _note_failure(e, session, None)
                            session.event("tail_stopped", status=e.code)
                            return
                        delay = self.retry.next_delay(delay)
                        _note_failure(e, session, delay)
                        _trim()
                        self._stop.wait(delay)
                        continue
                    except (OSError, ValueError) as e:
                        # transient transport trouble (incl. an open
                        # breaker) or a torn/garbage JSON body: back off,
                        # keep tailing
                        failing = True
                        delay = self.retry.next_delay(delay)
                        _note_failure(e, session, delay)
                        _trim()
                        self._stop.wait(delay)
                        continue
                    if not batch:
                        self._stop.wait(self.poll_interval_s)
                        continue
                    for data in batch:
                        try:
                            callback(data)
                        except Exception:  # noqa: BLE001 — one bad consumer
                            pass
            finally:
                # close the session root (it lands in the trace buffer);
                # NOOP when tracing never came on
                session.__exit__(None, None, None)

        t = threading.Thread(target=_tail, daemon=True,
                             name=f"remote-journal-tail-{topic}")
        self._tailers.append(t)
        t.start()

    def healthy(self) -> bool:
        """True while every tailer thread is alive and the last transport
        round-trip succeeded."""
        return self.last_error is None and all(
            t.is_alive() for t in self._tailers
        )

    def close(self) -> None:
        self._stop.set()
        for t in self._tailers:
            t.join(timeout=5.0)
