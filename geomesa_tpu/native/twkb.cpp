// Native batch TWKB decode (the TwkbSerialization hot path, SURVEY.md §2.4).
//
// Python's per-coordinate varint loop dominates geometry load time; this
// decodes a whole column of TWKB blobs in one call into flat arrays the
// Python side reassembles into geometry objects:
//
//   twkb_scan:   sizes pass — total points / parts / polygons
//   twkb_decode: fill types, per-geometry part counts, per-polygon ring
//                counts, per-part point counts, and packed (x, y) f64 coords
//
// Format exactly matches geometry/twkb.py: head byte = type | zigzag(prec)<<4,
// meta byte (0x10 = empty), then counts + zigzag-varint deltas (shared
// running "last" across parts of one geometry).

#include <cstdint>
#include <cmath>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  // each coordinate is two varints of >= 1 byte each: a claimed count
  // bigger than remaining_bytes/2 is malformed (also bounds the totals
  // against overflow, since counts are capped by the buffer size).
  // Division form: `2 * k` would wrap for k >= 2^63, letting a crafted
  // count pass the check and over-run the arrays sized by twkb_scan.
  bool count_ok(uint64_t k) {
    if (k > (uint64_t)(end - p) / 2) { fail = true; return false; }
    return true;
  }

  uint64_t varu() {
    uint64_t out = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      out |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
      if (shift > 63) break;
    }
    fail = true;
    return 0;
  }

  int64_t zz() {
    uint64_t v = varu();
    return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
  }
};

inline int unzigzag4(int v) { return (v >> 1) ^ -(v & 1); }

}  // namespace

extern "C" {

// Sizes pass. Returns 0 ok, -1 on malformed input.
int twkb_scan(const uint8_t* buf, const int64_t* offs, int64_t n,
              int64_t* total_pts, int64_t* total_parts, int64_t* total_polys) {
  int64_t pts = 0, parts = 0, polys = 0;
  for (int64_t i = 0; i < n; ++i) {
    Reader r{buf + offs[i], buf + offs[i + 1]};
    if (r.end - r.p < 2) return -1;
    uint8_t head = *r.p++;
    uint8_t meta = *r.p++;
    int t = head & 0x0F;
    if (meta & 0x10) continue;  // empty
    switch (t) {
      case 1: pts += 1; parts += 1; break;
      case 2: {
        uint64_t k = r.varu();
        if (!r.count_ok(k)) return -1;
        pts += k; parts += 1; break;
      }
      case 3: {
        uint64_t nr = r.varu();
        if (!r.count_ok(nr)) return -1;
        polys += 1; parts += nr;
        for (uint64_t j = 0; j < nr && !r.fail; ++j) {
          uint64_t k = r.varu();
          if (!r.count_ok(k)) return -1;
          pts += k;
          for (uint64_t c = 0; c < 2 * k && !r.fail; ++c) r.varu();
        }
        break;
      }
      case 4: {
        uint64_t k = r.varu();
        if (!r.count_ok(k)) return -1;
        pts += k; parts += k; break;
      }
      case 5: {
        uint64_t np_ = r.varu();
        if (!r.count_ok(np_)) return -1;
        parts += np_;
        for (uint64_t j = 0; j < np_ && !r.fail; ++j) {
          uint64_t k = r.varu();
          if (!r.count_ok(k)) return -1;
          pts += k;
          for (uint64_t c = 0; c < 2 * k && !r.fail; ++c) r.varu();
        }
        break;
      }
      case 6: {
        uint64_t np_ = r.varu();
        if (!r.count_ok(np_)) return -1;
        polys += np_;
        for (uint64_t j = 0; j < np_ && !r.fail; ++j) {
          uint64_t nr = r.varu();
          if (!r.count_ok(nr)) return -1;
          parts += nr;
          for (uint64_t q = 0; q < nr && !r.fail; ++q) {
            uint64_t k = r.varu();
            if (!r.count_ok(k)) return -1;
            pts += k;
            for (uint64_t c = 0; c < 2 * k && !r.fail; ++c) r.varu();
          }
        }
        break;
      }
      default: return -1;
    }
    if (r.fail) return -1;
  }
  *total_pts = pts;
  *total_parts = parts;
  *total_polys = polys;
  return 0;
}

// Decode pass; arrays sized from twkb_scan. types: 0=empty/None, else 1..6.
int twkb_decode(const uint8_t* buf, const int64_t* offs, int64_t n,
                int8_t* types, int32_t* geom_part_counts, int32_t* npolys,
                int32_t* poly_ring_counts, int32_t* part_sizes,
                double* coords) {
  int64_t pi = 0;   // part_sizes cursor
  int64_t ri = 0;   // poly_ring_counts cursor
  int64_t ci = 0;   // coords cursor (pairs)
  for (int64_t i = 0; i < n; ++i) {
    Reader r{buf + offs[i], buf + offs[i + 1]};
    if (r.end - r.p < 2) return -1;
    uint8_t head = *r.p++;
    uint8_t meta = *r.p++;
    int t = head & 0x0F;
    double scale = std::pow(10.0, (double)unzigzag4(head >> 4));
    if (meta & 0x10) {
      types[i] = 0; geom_part_counts[i] = 0; npolys[i] = 0;
      continue;
    }
    types[i] = (int8_t)t;
    int64_t lx = 0, ly = 0;
    auto read_part = [&](uint64_t k) {
      if (!r.count_ok(k)) return;
      part_sizes[pi++] = (int32_t)k;
      for (uint64_t c = 0; c < k && !r.fail; ++c) {
        lx += r.zz(); ly += r.zz();
        coords[2 * ci] = (double)lx / scale;
        coords[2 * ci + 1] = (double)ly / scale;
        ++ci;
      }
    };
    switch (t) {
      case 1: geom_part_counts[i] = 1; npolys[i] = 0; read_part(1); break;
      case 2: geom_part_counts[i] = 1; npolys[i] = 0; read_part(r.varu()); break;
      case 3: {
        uint64_t nr = r.varu();
        geom_part_counts[i] = (int32_t)nr; npolys[i] = 1;
        poly_ring_counts[ri++] = (int32_t)nr;
        for (uint64_t j = 0; j < nr && !r.fail; ++j) read_part(r.varu());
        break;
      }
      case 4: {
        uint64_t k = r.varu();
        geom_part_counts[i] = (int32_t)k; npolys[i] = 0;
        for (uint64_t j = 0; j < k && !r.fail; ++j) read_part(1);
        break;
      }
      case 5: {
        uint64_t np_ = r.varu();
        geom_part_counts[i] = (int32_t)np_; npolys[i] = 0;
        for (uint64_t j = 0; j < np_ && !r.fail; ++j) read_part(r.varu());
        break;
      }
      case 6: {
        uint64_t np_ = r.varu();
        npolys[i] = (int32_t)np_;
        int32_t parts = 0;
        for (uint64_t j = 0; j < np_ && !r.fail; ++j) {
          uint64_t nr = r.varu();
          poly_ring_counts[ri++] = (int32_t)nr;
          parts += (int32_t)nr;
          for (uint64_t q = 0; q < nr && !r.fail; ++q) read_part(r.varu());
        }
        geom_part_counts[i] = parts;
        break;
      }
      default: return -1;
    }
    if (r.fail) return -1;
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Encode pass: flat arrays (same layout twkb_decode produces) -> concatenated
// TWKB blobs. out_offs gets n+1 entries; returns total bytes or -1 when
// out_buf (capacity cap) is too small. Rounding matches numpy (nearest-even).
int64_t twkb_encode(const int8_t* types, const int32_t* geom_part_counts,
                    const int32_t* npolys, const int32_t* poly_ring_counts,
                    const int32_t* part_sizes, const double* coords,
                    int64_t n, int precision,
                    uint8_t* out_buf, int64_t cap, int64_t* out_offs) {
  double scale = std::pow(10.0, (double)precision);
  int zzprec = (precision << 1) ^ (precision >> 31);
  int64_t pi = 0, ri = 0, ci = 0, w = 0;
  auto put = [&](uint8_t b) -> bool {
    if (w >= cap) return false;
    out_buf[w++] = b;
    return true;
  };
  auto varu = [&](uint64_t v) -> bool {
    while (true) {
      uint8_t b = v & 0x7F;
      v >>= 7;
      if (v) { if (!put(b | 0x80)) return false; }
      else return put(b);
    }
  };
  auto zz = [&](int64_t v) -> bool {
    return varu(((uint64_t)v << 1) ^ (uint64_t)(v >> 63));
  };
  for (int64_t i = 0; i < n; ++i) {
    out_offs[i] = w;
    int t = types[i];
    if (t == 0) {  // None/empty: empty point, matching to_twkb(None)
      if (!put((uint8_t)(1 | (zzprec << 4))) || !put(0x10)) return -1;
      continue;
    }
    if (!put((uint8_t)(t | (zzprec << 4))) || !put(0)) return -1;
    int64_t lx = 0, ly = 0;
    auto part = [&](int32_t k, bool with_count) -> bool {
      if (with_count && !varu((uint64_t)k)) return false;
      for (int32_t c = 0; c < k; ++c) {
        int64_t x = (int64_t)std::nearbyint(coords[2 * ci] * scale);
        int64_t y = (int64_t)std::nearbyint(coords[2 * ci + 1] * scale);
        ++ci;
        if (!zz(x - lx) || !zz(y - ly)) return false;
        lx = x; ly = y;
      }
      return true;
    };
    bool ok = true;
    switch (t) {
      case 1: ok = part(part_sizes[pi++], false); break;
      case 2: ok = part(part_sizes[pi++], true); break;
      case 3: {
        int32_t nr = poly_ring_counts[ri++];
        ok = varu((uint64_t)nr);
        for (int32_t j = 0; j < nr && ok; ++j) ok = part(part_sizes[pi++], true);
        break;
      }
      case 4: {
        int32_t k = geom_part_counts[i];
        ok = varu((uint64_t)k);
        for (int32_t j = 0; j < k && ok; ++j) ok = part(part_sizes[pi++], false);
        break;
      }
      case 5: {
        int32_t k = geom_part_counts[i];
        ok = varu((uint64_t)k);
        for (int32_t j = 0; j < k && ok; ++j) ok = part(part_sizes[pi++], true);
        break;
      }
      case 6: {
        int32_t np_ = npolys[i];
        ok = varu((uint64_t)np_);
        for (int32_t j = 0; j < np_ && ok; ++j) {
          int32_t nr = poly_ring_counts[ri++];
          ok = varu((uint64_t)nr);
          for (int32_t q = 0; q < nr && ok; ++q) ok = part(part_sizes[pi++], true);
        }
        break;
      }
      default: return -1;
    }
    if (!ok) return -1;
  }
  out_offs[n] = w;
  return w;
}

}  // extern "C"
