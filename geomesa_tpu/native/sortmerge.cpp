// Native sort/merge kernels for the host-side index build path.
//
// Role parity: the reference's ingest hot loop is feature -> key encode ->
// sorted write into the distributed sorted map (SURVEY.md §3.2); here the
// analogous cost is the (bin, z) lexsort that orders the columnar store
// before device upload, and the sorted-merge that folds a delta tier into
// the main tier during compaction (LSM pattern, SURVEY.md §2.11).
//
// Build: g++ -O2 -shared -fPIC (see geomesa_tpu/native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>

namespace {

// LSD radix over 16-bit digits, struct-of-arrays (key array + index array
// ping-pong) — 4 passes for a full 64-bit key, fewer when the key's top
// bytes are zero. Stable and linear; sized for the 10M+ row sorts of the
// GDELT ingest path on memory-bound hosts.
constexpr int kDigitBits = 16;
constexpr int64_t kBuckets = 1ll << kDigitBits;

int significant_digits(uint64_t maxv) {
    int d = 1;
    while (maxv >>= kDigitBits) d++;
    return d;
}

void radix_pass(const uint64_t* key_src, const int64_t* idx_src,
                uint64_t* key_dst, int64_t* idx_dst, int64_t n, int shift,
                int64_t* count) {
    std::memset(count, 0, kBuckets * sizeof(int64_t));
    for (int64_t i = 0; i < n; i++) count[(key_src[i] >> shift) & (kBuckets - 1)]++;
    int64_t acc = 0;
    for (int64_t d = 0; d < kBuckets; d++) {
        int64_t c = count[d];
        count[d] = acc;
        acc += c;
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t p = count[(key_src[i] >> shift) & (kBuckets - 1)]++;
        key_dst[p] = key_src[i];
        idx_dst[p] = idx_src[i];
    }
}

// Sort (idx permutation) by 64-bit key; returns which buffer holds the
// result (0 = a-side, 1 = b-side).
int radix_sort(uint64_t* ka, int64_t* ia, uint64_t* kb, int64_t* ib,
               int64_t n, uint64_t maxv) {
    int passes = significant_digits(maxv);
    int64_t* count = new int64_t[kBuckets];
    int side = 0;
    for (int p = 0; p < passes; p++) {
        if (side == 0)
            radix_pass(ka, ia, kb, ib, n, p * kDigitBits, count);
        else
            radix_pass(kb, ib, ka, ia, n, p * kDigitBits, count);
        side ^= 1;
    }
    delete[] count;
    return side;
}

}  // namespace

extern "C" {

// Sort permutation by composite key (bins asc, z asc). Writes n int64
// indices into out_perm. Equal keys keep input order (stable).
void geomesa_sort_bin_z(const int32_t* bins, const uint64_t* zs, int64_t n,
                        int64_t* out_perm) {
    if (n == 0) return;
    uint64_t* ka = new uint64_t[n];
    int64_t* ia = new int64_t[n];
    uint64_t* kb = new uint64_t[n];
    int64_t* ib = new int64_t[n];
    uint64_t zmax = 0;
    for (int64_t i = 0; i < n; i++) {
        ka[i] = zs[i];
        ia[i] = i;
        if (zs[i] > zmax) zmax = zs[i];
    }
    // z passes first, then bin passes: LSD stability makes the final order
    // (bin, z) lexicographic
    int side = radix_sort(ka, ia, kb, ib, n, zmax);
    uint64_t* ks = side ? kb : ka;
    int64_t* is = side ? ib : ia;
    uint64_t* kd = side ? ka : kb;
    int64_t* id = side ? ia : ib;
    uint64_t binmax = 0;
    for (int64_t i = 0; i < n; i++) {
        ks[i] = (uint32_t)bins[is[i]];
        if (ks[i] > binmax) binmax = ks[i];
    }
    int passes = significant_digits(binmax);
    int64_t* count = new int64_t[kBuckets];
    for (int p = 0; p < passes; p++) {
        radix_pass(ks, is, kd, id, n, p * kDigitBits, count);
        std::swap(ks, kd);
        std::swap(is, id);
    }
    delete[] count;
    std::memcpy(out_perm, is, n * sizeof(int64_t));
    delete[] ka;
    delete[] ia;
    delete[] kb;
    delete[] ib;
}

// Sort permutation by a single uint64 key (the z2/xz2 case).
void geomesa_sort_u64(const uint64_t* keys, int64_t n, int64_t* out_perm) {
    if (n == 0) return;
    uint64_t* ka = new uint64_t[n];
    int64_t* ia = new int64_t[n];
    uint64_t* kb = new uint64_t[n];
    int64_t* ib = new int64_t[n];
    uint64_t zmax = 0;
    for (int64_t i = 0; i < n; i++) {
        ka[i] = keys[i];
        ia[i] = i;
        if (keys[i] > zmax) zmax = keys[i];
    }
    int side = radix_sort(ka, ia, kb, ib, n, zmax);
    std::memcpy(out_perm, side ? ib : ia, n * sizeof(int64_t));
    delete[] ka;
    delete[] ia;
    delete[] kb;
    delete[] ib;
}

// Linear merge of two (bin, z)-sorted runs -> gather permutation over the
// concatenated [main | delta] ordering (delta indices offset by n_main).
// The LSM compaction path: O(n) instead of re-sorting the whole store.
void geomesa_merge_bin_z(const int32_t* bins_a, const uint64_t* zs_a,
                         int64_t n_a, const int32_t* bins_b,
                         const uint64_t* zs_b, int64_t n_b,
                         int64_t* out_perm) {
    int64_t i = 0, j = 0, k = 0;
    while (i < n_a && j < n_b) {
        bool take_a = (bins_a[i] != bins_b[j]) ? (bins_a[i] < bins_b[j])
                                               : (zs_a[i] <= zs_b[j]);
        if (take_a) {
            out_perm[k++] = i++;
        } else {
            out_perm[k++] = n_a + j++;
        }
    }
    while (i < n_a) out_perm[k++] = i++;
    while (j < n_b) out_perm[k++] = n_a + j++;
}

}  // extern "C"
