// Native z-range decomposition: cover a query box with Morton-curve intervals.
//
// The C++ core of the planner's hot spot (the external sfcurve library role —
// SURVEY.md §2.1 "CRITICAL external dependency"): a BFS over the implicit
// quad/oct tree of Morton prefix cells, bit-identical to the Python fallback
// in geomesa_tpu/curve/zranges.py (the tests assert exact agreement). Exposed
// through ctypes (geomesa_tpu/native/__init__.py builds and loads it).
//
// Build: g++ -O2 -shared -fPIC -o libzranges.so zranges.cpp

#include <cstdint>
#include <vector>
#include <algorithm>
#include <cstring>

namespace {

struct Cell {
    uint64_t dims[3];
    int level;
};

inline uint64_t spread2(uint64_t x) {
    x &= 0x00000000FFFFFFFFULL;
    x = (x | (x << 16)) & 0x0000FFFF0000FFFFULL;
    x = (x | (x << 8)) & 0x00FF00FF00FF00FFULL;
    x = (x | (x << 4)) & 0x0F0F0F0F0F0F0F0FULL;
    x = (x | (x << 2)) & 0x3333333333333333ULL;
    x = (x | (x << 1)) & 0x5555555555555555ULL;
    return x;
}

inline uint64_t spread3(uint64_t x) {
    x &= 0x00000000001FFFFFULL;
    x = (x | (x << 32)) & 0x001F00000000FFFFULL;
    x = (x | (x << 16)) & 0x001F0000FF0000FFULL;
    x = (x | (x << 8)) & 0x100F00F00F00F00FULL;
    x = (x | (x << 4)) & 0x10C30C30C30C30C3ULL;
    x = (x | (x << 2)) & 0x1249249249249249ULL;
    return x;
}

inline uint64_t encode(int dims, const uint64_t* v) {
    if (dims == 2) return spread2(v[0]) | (spread2(v[1]) << 1);
    return spread3(v[0]) | (spread3(v[1]) << 1) | (spread3(v[2]) << 2);
}

// classify cell vs box per dim: 0 disjoint, 1 overlap, 2 contained
inline int classify(const Cell& c, int dims, int precision,
                    const uint64_t* lows, const uint64_t* highs) {
    int s = precision - c.level;
    bool contained = true;
    for (int d = 0; d < dims; d++) {
        uint64_t clo = c.dims[d] << s;
        uint64_t chi = clo | ((s >= 64) ? ~0ULL : ((1ULL << s) - 1));
        if (chi < lows[d] || clo > highs[d]) return 0;
        if (clo < lows[d] || chi > highs[d]) contained = false;
    }
    return contained ? 2 : 1;
}

inline void emit(std::vector<std::pair<uint64_t, uint64_t>>& out, const Cell& c,
                 int dims, int precision) {
    int s = precision - c.level;
    uint64_t corner[3];
    for (int d = 0; d < dims; d++) corner[d] = c.dims[d] << s;
    uint64_t zlo = encode(dims, corner);
    uint64_t span = (dims * s >= 64) ? ~0ULL : ((1ULL << (dims * s)) - 1);
    out.emplace_back(zlo, zlo | span);
}

}  // namespace

extern "C" {

// Returns the number of (lo, hi) pairs written to `out` (capacity `cap`
// pairs), or -1 if `out` was too small. Inclusive uint64 intervals, sorted
// and merged. Inverted boxes return 0.
long geomesa_zranges(int dims, const uint64_t* lows, const uint64_t* highs,
                     int precision, long max_ranges, long max_recurse,
                     uint64_t* out, long cap) {
    if (dims < 2 || dims > 3 || precision < 1 || precision > 31) return -1;
    for (int d = 0; d < dims; d++)
        if (highs[d] < lows[d]) return 0;

    // whole-domain short-circuit
    uint64_t full = (1ULL << precision) - 1;
    bool whole = true;
    for (int d = 0; d < dims; d++)
        if (lows[d] != 0 || highs[d] != full) { whole = false; break; }
    if (whole) {
        if (cap < 1) return -1;
        out[0] = 0;
        out[1] = (dims * precision >= 64) ? ~0ULL : ((1ULL << (dims * precision)) - 1);
        return 1;
    }

    int max_level = precision < max_recurse ? precision : (int)max_recurse;
    std::vector<std::pair<uint64_t, uint64_t>> ranges;
    ranges.reserve(max_ranges > 0 ? max_ranges : 64);
    std::vector<Cell> frontier;
    frontier.push_back(Cell{{0, 0, 0}, 0});
    size_t head = 0;

    while (head < frontier.size()) {
        long remaining = (long)(frontier.size() - head);
        if ((long)ranges.size() + remaining >= max_ranges) {
            // budget: drain, still classifying so disjoint cells are dropped
            for (; head < frontier.size(); head++) {
                const Cell& c = frontier[head];
                if (classify(c, dims, precision, lows, highs) != 0)
                    emit(ranges, c, dims, precision);
            }
            break;
        }
        Cell c = frontier[head++];
        int cls = classify(c, dims, precision, lows, highs);
        if (cls == 0) continue;
        if (cls == 2 || c.level >= max_level) {
            emit(ranges, c, dims, precision);
            continue;
        }
        for (int child = 0; child < (1 << dims); child++) {
            Cell nc;
            nc.level = c.level + 1;
            for (int d = 0; d < dims; d++)
                nc.dims[d] = (c.dims[d] << 1) | ((child >> d) & 1);
            frontier.push_back(nc);
        }
        // compact the consumed prefix occasionally to bound memory
        if (head > 4096) {
            frontier.erase(frontier.begin(), frontier.begin() + head);
            head = 0;
        }
    }

    std::sort(ranges.begin(), ranges.end());
    long n = 0;
    for (size_t i = 0; i < ranges.size(); i++) {
        // overflow-safe adjacency: merge when first <= prev_hi, or when
        // first == prev_hi + 1 and prev_hi + 1 does not wrap past 2^64-1
        if (n > 0 && (ranges[i].first <= out[2 * (n - 1) + 1] ||
                      (out[2 * (n - 1) + 1] != ~0ULL &&
                       ranges[i].first <= out[2 * (n - 1) + 1] + 1))) {
            uint64_t hi = ranges[i].second;
            if (hi > out[2 * (n - 1) + 1]) out[2 * (n - 1) + 1] = hi;
        } else {
            if (n >= cap) return -1;
            out[2 * n] = ranges[i].first;
            out[2 * n + 1] = ranges[i].second;
            n++;
        }
    }
    return n;
}

}  // extern "C"
