"""Native (C++) runtime components, loaded via ctypes.

Build-on-first-use: ``g++ -O2`` compiles each ``.cpp`` in this directory into
a shared library alongside it the first time it's needed (cached by mtime);
everything degrades to the pure-Python implementations when no toolchain is
available. Components (SURVEY.md §2.9 native checklist):

- ``zranges.cpp`` — z-range decomposition (the sfcurve ``zranges`` role)
- ``sortmerge.cpp`` — (bin, z) lexsort + LSM sorted-merge for index builds
  and delta-tier compaction
- ``delimited.cpp`` — one-pass typed column extraction from delimited text
  (the ingest data-loader hot path)
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_libs: dict[str, object] = {}  # name -> CDLL | None (None = load failed)


def _load_lib(name: str):
    """Compile (if stale) and dlopen ``<name>.cpp`` → ``lib<name>.so``."""
    if name in _libs:
        return _libs[name]
    src = _DIR / f"{name}.cpp"
    lib_path = _DIR / f"lib{name}.so"
    fresh = lib_path.exists() and (
        not src.exists() or lib_path.stat().st_mtime >= src.stat().st_mtime
    )
    if not fresh:
        if not src.exists():
            _libs[name] = None
            return None
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", str(lib_path), str(src)],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            _libs[name] = None
            return None
    try:
        _libs[name] = ctypes.CDLL(str(lib_path))
    except OSError:
        _libs[name] = None
    return _libs[name]


def available() -> bool:
    return _zranges_lib() is not None


# -- zranges -----------------------------------------------------------------

def _zranges_lib():
    lib = _load_lib("zranges")
    if lib is not None and not getattr(lib, "_configured", False):
        lib.geomesa_zranges.restype = ctypes.c_long
        lib.geomesa_zranges.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_long,
        ]
        lib._configured = True
    return lib


def zranges_native(
    lows, highs, precision: int, max_ranges: int = 2000, max_recurse: int = 32
):
    """C++ z-range decomposition; returns (R, 2) uint64 or None if unavailable."""
    lib = _zranges_lib()
    if lib is None:
        return None
    dims = len(lows)
    lo = (ctypes.c_uint64 * dims)(*[int(v) for v in lows])
    hi = (ctypes.c_uint64 * dims)(*[int(v) for v in highs])
    cap = max(int(max_ranges) * 4 + 64, 256)
    out = np.empty(cap * 2, dtype=np.uint64)
    n = lib.geomesa_zranges(
        dims,
        lo,
        hi,
        precision,
        int(max_ranges),
        int(max_recurse),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cap,
    )
    if n < 0:  # output buffer too small: retry once with a big buffer
        cap = cap * 8
        out = np.empty(cap * 2, dtype=np.uint64)
        n = lib.geomesa_zranges(
            dims, lo, hi, precision, int(max_ranges), int(max_recurse),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), cap,
        )
        if n < 0:
            return None
    return out[: 2 * n].reshape(n, 2).copy()


# -- sort / merge -------------------------------------------------------------

def _sortmerge_lib():
    lib = _load_lib("sortmerge")
    if lib is not None and not getattr(lib, "_configured", False):
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.geomesa_sort_bin_z.restype = None
        lib.geomesa_sort_bin_z.argtypes = [i32p, u64p, ctypes.c_int64, i64p]
        lib.geomesa_sort_u64.restype = None
        lib.geomesa_sort_u64.argtypes = [u64p, ctypes.c_int64, i64p]
        lib.geomesa_merge_bin_z.restype = None
        lib.geomesa_merge_bin_z.argtypes = [
            i32p, u64p, ctypes.c_int64, i32p, u64p, ctypes.c_int64, i64p,
        ]
        lib._configured = True
    return lib


def lexsort_bin_z(bins: np.ndarray, zs: np.ndarray) -> np.ndarray:
    """Stable sort permutation by (bin, z); native, else ``np.lexsort``."""
    lib = _sortmerge_lib()
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    zs = np.ascontiguousarray(zs, dtype=np.uint64)
    if lib is None:
        return np.lexsort((zs, bins))
    perm = np.empty(len(zs), dtype=np.int64)
    lib.geomesa_sort_bin_z(
        bins.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        zs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(zs),
        perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return perm


def sort_u64(keys: np.ndarray) -> np.ndarray:
    """Stable sort permutation of uint64 keys.

    numpy's stable argsort on integer keys is already an optimized radix
    sort and measures faster than the C path for single keys, so this stays
    numpy; the native win is the *fused* composite sort
    (:func:`lexsort_bin_z`), which replaces two stable passes with one.
    """
    return np.argsort(np.ascontiguousarray(keys, dtype=np.uint64), kind="stable")


def merge_bin_z(bins_a, zs_a, bins_b, zs_b) -> np.ndarray:
    """Gather permutation merging two (bin, z)-sorted runs; indices into the
    concatenation [a | b] (LSM compaction path). Falls back to lexsort."""
    a_bins = np.ascontiguousarray(bins_a, dtype=np.int32)
    a_zs = np.ascontiguousarray(zs_a, dtype=np.uint64)
    b_bins = np.ascontiguousarray(bins_b, dtype=np.int32)
    b_zs = np.ascontiguousarray(zs_b, dtype=np.uint64)
    lib = _sortmerge_lib()
    if lib is None:
        return np.lexsort(
            (np.concatenate([a_zs, b_zs]), np.concatenate([a_bins, b_bins]))
        )
    out = np.empty(len(a_zs) + len(b_zs), dtype=np.int64)
    lib.geomesa_merge_bin_z(
        a_bins.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        a_zs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(a_zs),
        b_bins.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        b_zs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(b_zs),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


# -- delimited loader ---------------------------------------------------------

F64, I64, DATE_YYYYMMDD = 0, 1, 2


def _delimited_lib():
    lib = _load_lib("delimited")
    if lib is not None and not getattr(lib, "_configured", False):
        lib.geomesa_count_lines.restype = ctypes.c_int64
        lib.geomesa_count_lines.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.geomesa_parse_delimited.restype = ctypes.c_int64
        lib.geomesa_parse_delimited.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_char,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
        ]
        lib._configured = True
    return lib


def parse_delimited(data: bytes, delim: str, columns: list[tuple[int, int]]):
    """One-pass typed extraction of ``columns`` = [(zero_based_index, type)]
    from a delimited byte buffer. Types: F64, I64, DATE_YYYYMMDD (→ epoch
    ms). Returns ``(arrays, valid)`` per column, or None when the native
    loader is unavailable. Column indices must be ascending.
    """
    lib = _delimited_lib()
    if lib is None:
        return None
    idxs = [c for c, _ in columns]
    if idxs != sorted(idxs):
        raise ValueError("column indices must be ascending")
    n_rows = lib.geomesa_count_lines(data, len(data))
    n_cols = len(columns)
    bufs = [np.zeros(max(n_rows, 1), dtype=np.float64) for _ in columns]
    valid = np.zeros((n_cols, max(n_rows, 1)), dtype=np.uint8)
    out_ptrs = (ctypes.POINTER(ctypes.c_double) * n_cols)(
        *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for b in bufs]
    )
    got = lib.geomesa_parse_delimited(
        data,
        len(data),
        delim.encode()[0:1],
        n_cols,
        (ctypes.c_int32 * n_cols)(*idxs),
        (ctypes.c_int32 * n_cols)(*[t for _, t in columns]),
        out_ptrs,
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        max(n_rows, 1),
    )
    arrays = []
    for buf, (_, typ) in zip(bufs, columns):
        a = buf[:got]
        if typ != F64:
            a = a.view(np.int64)[: len(a)]
        arrays.append(a.copy())
    return arrays, valid[:, :got].astype(bool)


# -- twkb batch decode --------------------------------------------------------

def _twkb_lib():
    lib = _load_lib("twkb")
    if lib is not None and not getattr(lib, "_configured", False):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i8p = ctypes.POINTER(ctypes.c_int8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.twkb_scan.restype = ctypes.c_int
        lib.twkb_scan.argtypes = [u8p, i64p, ctypes.c_int64, i64p, i64p, i64p]
        lib.twkb_decode.restype = ctypes.c_int
        lib.twkb_decode.argtypes = [
            u8p, i64p, ctypes.c_int64, i8p, i32p, i32p, i32p, i32p, f64p,
        ]
        lib._configured = True
    return lib


def twkb_decode_batch(buf: bytes, offsets: np.ndarray):
    """Decode ``n`` concatenated TWKB blobs (``offsets``: (n+1,) int64 into
    ``buf``) → (types i8 (n,), geom_part_counts i32 (n,), npolys i32 (n,),
    poly_ring_counts i32, part_sizes i32, coords f64 (pts, 2)) or None when
    the native library is unavailable or the input is malformed."""
    lib = _twkb_lib()
    if lib is None:
        return None
    n = len(offsets) - 1
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    raw = np.frombuffer(buf, dtype=np.uint8)
    total = np.zeros(3, dtype=np.int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    rc = lib.twkb_scan(
        raw.ctypes.data_as(u8p), offs.ctypes.data_as(i64p), n,
        total[0:].ctypes.data_as(i64p), total[1:].ctypes.data_as(i64p),
        total[2:].ctypes.data_as(i64p),
    )
    if rc != 0:
        return None
    pts, parts, polys = (int(v) for v in total)
    # a well-formed blob stream cannot claim more coordinates than bytes;
    # negative/overflowed totals mean malformed counts slipped past the scan
    if min(pts, parts, polys) < 0 or max(pts, parts, polys) > len(raw):
        return None
    types = np.empty(n, dtype=np.int8)
    gpc = np.empty(n, dtype=np.int32)
    npolys = np.empty(n, dtype=np.int32)
    prc = np.empty(max(polys, 1), dtype=np.int32)
    psz = np.empty(max(parts, 1), dtype=np.int32)
    coords = np.empty((max(pts, 1), 2), dtype=np.float64)
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    rc = lib.twkb_decode(
        raw.ctypes.data_as(u8p), offs.ctypes.data_as(i64p), n,
        types.ctypes.data_as(i8p), gpc.ctypes.data_as(i32p),
        npolys.ctypes.data_as(i32p), prc.ctypes.data_as(i32p),
        psz.ctypes.data_as(i32p), coords.ctypes.data_as(f64p),
    )
    if rc != 0:
        return None
    return types, gpc, npolys, prc[:polys], psz[:parts], coords[:pts]


def twkb_encode_batch(types, gpc, npolys, prc, psz, coords, precision: int = 7):
    """Encode flat geometry arrays (layout of :func:`twkb_decode_batch`) →
    (buf uint8 array, offsets (n+1,) int64), or None when unavailable."""
    lib = _twkb_lib()
    if lib is None:
        return None
    if not getattr(lib, "_enc_configured", False):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i8p = ctypes.POINTER(ctypes.c_int8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.twkb_encode.restype = ctypes.c_int64
        lib.twkb_encode.argtypes = [
            i8p, i32p, i32p, i32p, i32p, f64p,
            ctypes.c_int64, ctypes.c_int, u8p, ctypes.c_int64, i64p,
        ]
        lib._enc_configured = True
    n = len(types)
    types = np.ascontiguousarray(types, dtype=np.int8)
    gpc = np.ascontiguousarray(gpc, dtype=np.int32)
    npolys = np.ascontiguousarray(npolys, dtype=np.int32)
    prc = np.ascontiguousarray(prc, dtype=np.int32) if len(prc) else np.zeros(1, np.int32)
    psz = np.ascontiguousarray(psz, dtype=np.int32) if len(psz) else np.zeros(1, np.int32)
    coords = np.ascontiguousarray(coords, dtype=np.float64)
    pts = len(coords)
    # worst case: 2B header + 10B per count varint + 2x10B per coordinate
    cap = 2 * n + 10 * (len(psz) + len(prc) + n) + 20 * max(pts, 1)
    buf = np.empty(cap, dtype=np.uint8)
    offs = np.empty(n + 1, dtype=np.int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    total = lib.twkb_encode(
        types.ctypes.data_as(i8p), gpc.ctypes.data_as(i32p),
        npolys.ctypes.data_as(i32p), prc.ctypes.data_as(i32p),
        psz.ctypes.data_as(i32p),
        coords.ctypes.data_as(f64p) if pts else np.zeros((1, 2)).ctypes.data_as(f64p),
        n, int(precision),
        buf.ctypes.data_as(u8p), cap, offs.ctypes.data_as(i64p),
    )
    if total < 0:
        return None
    return buf[:total], offs
