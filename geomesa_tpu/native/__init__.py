"""Native (C++) planner components, loaded via ctypes.

Build-on-first-use: ``g++ -O2`` compiles :file:`zranges.cpp` into the package
directory the first time it's needed (cached by mtime); everything degrades to
the pure-Python implementations when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_SRC = _DIR / "zranges.cpp"
_LIB = _DIR / "libzranges.so"

_lib = None
_load_failed = False


def _ensure_built() -> bool:
    if _LIB.exists() and (
        not _SRC.exists() or _LIB.stat().st_mtime >= _SRC.stat().st_mtime
    ):
        return True  # prebuilt .so shipped without source is fine
    if not _SRC.exists():
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", str(_LIB), str(_SRC)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if not _ensure_built():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(_LIB))
        lib.geomesa_zranges.restype = ctypes.c_long
        lib.geomesa_zranges.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_long,
        ]
        _lib = lib
    except OSError:
        _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def zranges_native(
    lows, highs, precision: int, max_ranges: int = 2000, max_recurse: int = 32
):
    """C++ z-range decomposition; returns (R, 2) uint64 or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    dims = len(lows)
    lo = (ctypes.c_uint64 * dims)(*[int(v) for v in lows])
    hi = (ctypes.c_uint64 * dims)(*[int(v) for v in highs])
    cap = max(int(max_ranges) * 4 + 64, 256)
    out = np.empty(cap * 2, dtype=np.uint64)
    n = lib.geomesa_zranges(
        dims,
        lo,
        hi,
        precision,
        int(max_ranges),
        int(max_recurse),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cap,
    )
    if n < 0:  # output buffer too small: retry once with a big buffer
        cap = cap * 8
        out = np.empty(cap * 2, dtype=np.uint64)
        n = lib.geomesa_zranges(
            dims, lo, hi, precision, int(max_ranges), int(max_recurse),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), cap,
        )
        if n < 0:
            return None
    return out[: 2 * n].reshape(n, 2).copy()
