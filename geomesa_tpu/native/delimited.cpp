// Native delimited-text column extractor: the ingest data-loader hot path.
//
// Role parity: the reference's converter framework parses delimited exports
// (GDELT TSV et al.) on the JVM (SURVEY.md §2.16); the equivalent hot loop
// here extracts typed numeric/date columns straight from the raw byte
// buffer in one pass — no per-cell Python objects, no intermediate string
// columns — feeding the columnar store directly.
//
// Column types: 0 = f64 (strtod), 1 = i64 (strtoll),
//               2 = yyyyMMdd integer date -> epoch millis.
// Empty / unparseable cells write 0 and clear the valid bit.
//
// Build: g++ -O2 -shared -fPIC (see geomesa_tpu/native/__init__.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// days since 1970-01-01 for a (y, m, d) civil date (Howard Hinnant's algo)
int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
    y -= m <= 2;
    const int64_t era = (y >= 0 ? y : y - 399) / 400;
    const int64_t yoe = y - era * 400;
    const int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    const int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + doe - 719468;
}

}  // namespace

extern "C" {

// Count lines (records) in buf; a trailing line without '\n' counts.
int64_t geomesa_count_lines(const char* buf, int64_t len) {
    int64_t n = 0;
    for (int64_t i = 0; i < len; i++)
        if (buf[i] == '\n') n++;
    if (len > 0 && buf[len - 1] != '\n') n++;
    return n;
}

// Parse up to max_rows records. wanted_cols: zero-based column indices
// (ascending). For each wanted column c and row r:
//   out[c][r] receives the parsed value (f64 array for type 0, i64 view
//   for types 1/2 — caller passes f64* buffers and reinterprets),
//   valid[c*max_rows + r] = 1 when the cell parsed.
// Returns the number of rows consumed.
int64_t geomesa_parse_delimited(const char* buf, int64_t len, char delim,
                                int32_t n_wanted, const int32_t* wanted_cols,
                                const int32_t* col_types, double** out,
                                uint8_t* valid, int64_t max_rows) {
    int64_t row = 0;
    int64_t pos = 0;
    while (pos < len && row < max_rows) {
        // one record: walk fields, capturing the wanted ones
        int32_t col = 0;
        int32_t w = 0;  // next wanted slot
        while (pos <= len) {
            int64_t start = pos;
            while (pos < len && buf[pos] != delim && buf[pos] != '\n') pos++;
            if (w < n_wanted && col == wanted_cols[w]) {
                const char* s = buf + start;
                int64_t flen = pos - start;
                uint8_t ok = 0;
                double fval = 0.0;
                int64_t ival = 0;
                if (flen > 0) {
                    char tmp[64];
                    if (flen < 63) {
                        std::memcpy(tmp, s, flen);
                        tmp[flen] = 0;
                        char* end = nullptr;
                        if (col_types[w] == 0) {
                            fval = std::strtod(tmp, &end);
                            ok = (end == tmp + flen);
                        } else {
                            ival = std::strtoll(tmp, &end, 10);
                            ok = (end == tmp + flen);
                            if (ok && col_types[w] == 2) {
                                int64_t y = ival / 10000;
                                int64_t m = (ival / 100) % 100;
                                int64_t d = ival % 100;
                                if (m >= 1 && m <= 12 && d >= 1 && d <= 31) {
                                    ival = days_from_civil(y, m, d) * 86400000LL;
                                } else {
                                    ok = 0;
                                }
                            }
                        }
                    }
                }
                if (col_types[w] == 0) {
                    out[w][row] = ok ? fval : 0.0;
                } else {
                    reinterpret_cast<int64_t*>(out[w])[row] = ok ? ival : 0;
                }
                valid[(int64_t)w * max_rows + row] = ok;
                w++;
            }
            if (pos >= len || buf[pos] == '\n') {
                pos++;
                break;
            }
            pos++;  // skip delimiter
            col++;
        }
        // wanted columns beyond the record's field count -> invalid
        for (; w < n_wanted; w++) {
            if (col_types[w] == 0)
                out[w][row] = 0.0;
            else
                reinterpret_cast<int64_t*>(out[w])[row] = 0;
            valid[(int64_t)w * max_rows + row] = 0;
        }
        row++;
    }
    return row;
}

}  // extern "C"
