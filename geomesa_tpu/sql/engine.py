"""SQL(-subset) engine over a datastore: the Spark-SQL integration analog.

Role parity: ``geomesa-spark-sql`` (SURVEY.md §2.14/§3.5) — the reference
registers a DataSource relation whose catalyst rules push spatial predicates
(``st_contains`` etc.) down into the GeoMesa query planner, evaluates residual
``ST_*`` UDFs per row, and runs SQL aggregates on the scanned RDD. Here the
equivalent pipeline is: SQL text → (CQL-pushdown WHERE, projection, aggregate
plan) → planned datastore query → vectorized numpy aggregation.

Supported grammar:

    SELECT <item, ...> FROM <type>
      [WHERE <predicates>] [GROUP BY <col, ...>]
      [ORDER BY <col> [ASC|DESC]] [LIMIT <n>] [OFFSET <k>]

    SELECT <alias.col|alias.*|agg, ...> FROM <t1> <a> JOIN <t2> <b>
      ON ST_Within|ST_Contains|ST_Intersects(<alias.geom>, <alias.geom>)
      [WHERE <left-alias predicates>]
      [GROUP BY <alias.col, ...>] [HAVING agg(alias.col|*) <op> number]
      [ORDER BY <name> [ASC|DESC], ...] [LIMIT <n>]

    SELECT <alias.col|alias.*|agg, ...> FROM <t1> <a> JOIN <t2> <b>
      ON <alias>.<attr> = <alias>.<attr>        -- attribute equi-join
      [[LEFT [OUTER]] JOIN <tN> <x>
        ON <bound-alias>.<attr> = <x>.<attr>]... -- N-way chains
      [WHERE <conjuncts, each referencing exactly one alias>]
      [GROUP BY <alias.col, ...>] [HAVING agg(alias.col|*) <op> number]
      [ORDER BY <name> [ASC|DESC], ...] [LIMIT <n>]

    item      := * | col | agg | fn(col) [AS alias]
    agg       := COUNT(*) | COUNT(col) | COUNT(DISTINCT col)
                 | SUM/MIN/MAX/AVG(col)
    fn        := any single-argument ST_* registry UDF (ST_X, ST_Y,
                 ST_AsText, ST_GeoHash fast paths; ST_Area, ST_Centroid,
                 ST_GeometryType, ... via spatial/st_functions.ST —
                 geometry-valued results surface as WKT text)
    predicate := CQL comparisons/temporal ops, plus spark-jts spatial calls:
                 ST_Contains/ST_Within/ST_Intersects/ST_Disjoint(col, g),
                 ST_DWithin(col, g, dist); g := ST_GeomFromText('wkt')|'wkt'

The WHERE clause is rewritten to CQL and fed to the normal query planner, so
spatial/temporal/attribute predicates ride the Z/XZ/attribute indexes exactly
like any other query (the reference's pushdown seam, ``GeoMesaRelation
.buildScan``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from geomesa_tpu.planning.planner import Query

__all__ = ["sql", "SqlResult", "SqlError"]


class SqlError(ValueError):
    pass


@dataclass
class SqlResult:
    """Ordered named columns (numpy arrays / object arrays)."""

    columns: dict[str, np.ndarray]

    def __len__(self) -> int:
        return len(next(iter(self.columns.values()))) if self.columns else 0

    def rows(self) -> list[tuple]:
        names = list(self.columns)
        return [
            tuple(
                v.item() if isinstance((v := self.columns[c][i]), np.generic) else v
                for c in names
            )
            for i in range(len(self))
        ]


_CLAUSES = re.compile(
    r"^\s*select\s+(?P<distinct>distinct\s+)?(?P<select>.+?)\s+from\s+(?P<from>\w+)"
    r"(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+group\s+by\s+(?P<group>.+?))?"
    r"(?:\s+having\s+(?P<having>.+?))?"
    r"(?:\s+order\s+by\s+(?P<order>.+?))?"
    r"(?:\s+limit\s+(?P<limit>\d+))?"
    r"(?:\s+offset\s+(?P<offset>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)
_HAVING = re.compile(
    r"^\s*(?P<expr>\w+\s*\(\s*(?:\*|[\w.]+)\s*\))\s*(?P<op><>|<=|>=|=|<|>)\s*"
    r"(?P<lit>-?\d+(?:\.\d+)?)\s*$"
)
# trajectory table functions (docs/trajectory.md § SQL surface):
#   SELECT * FROM TUBE_SELECT('type', 'x y t, x y t, ...', buffer,
#                             time_buffer_ms [, 'cql']) [LIMIT n]
#   SELECT * FROM TRACK_STATS('type', 'track_field' [, 'cql']) [LIMIT n]
#   SELECT * FROM ST_LINK('ltype', 'rtype', 'pred' [, distance
#                         [, time_buffer_ms]]) [LIMIT n]
_TABLE_FN = re.compile(
    r"^\s*select\s+\*\s+from\s+(?P<fn>tube_select|track_stats|st_link)"
    r"\s*\((?P<args>.*)\)\s*(?:limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)


def _mask_quotes(s: str) -> str:
    """Blank the INSIDE of quoted literals (same length) so clause-keyword
    regexes can't match words like HAVING/GROUP inside a string literal;
    spans found on the mask are then sliced from the original."""
    out = []
    q = None
    for ch in s:
        if q is not None:
            out.append(ch if ch == q else "_")
            if ch == q:
                q = None
        else:
            if ch in ("'", '"'):
                q = ch
            out.append(ch)
    return "".join(out)


def _clause(m: "re.Match", original: str, name: str) -> str | None:
    a, b = m.span(name)
    return None if a == -1 else original[a:b]
_AGGS = ("count", "sum", "min", "max", "avg")
_SPATIAL = {
    "st_contains": "CONTAINS",
    "st_within": "WITHIN",
    "st_intersects": "INTERSECTS",
    "st_disjoint": "DISJOINT",
    "st_dwithin": "DWITHIN",
}


def _split_top(s: str, sep: str = ",") -> list[str]:
    out, depth, cur, q = [], 0, [], None
    for ch in s:
        if q:
            cur.append(ch)
            if ch == q:
                q = None
        elif ch in "'\"":
            q = ch
            cur.append(ch)
        elif ch == "(":
            depth += 1
            cur.append(ch)
        elif ch == ")":
            depth -= 1
            cur.append(ch)
        elif ch == sep and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return [p for p in out if p]


def _split_conjuncts(s: str) -> list[str]:
    """Split on top-level ``AND`` (case-insensitive, outside quotes and
    parentheses) — the WHERE-routing unit for the equi-join grammar."""
    out, depth, q, i, start = [], 0, None, 0, 0
    low = s.lower()
    while i < len(s):
        ch = s[i]
        if q:
            if ch == q:
                q = None
        elif ch in "'\"":
            q = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif (
            depth == 0
            and low.startswith("and", i)
            and (i == 0 or not (s[i - 1].isalnum() or s[i - 1] == "_"))
            and (
                i + 3 >= len(s)
                or not (s[i + 3].isalnum() or s[i + 3] == "_")
            )
        ):
            out.append(s[start:i])
            start = i + 3
            i += 3
            continue
        i += 1
    out.append(s[start:])
    return [p.strip() for p in out if p.strip()]


def _strip_geom_literal(arg: str) -> str:
    """``ST_GeomFromText('wkt')`` | ``'wkt'`` | bare WKT → bare WKT."""
    a = arg.strip()
    m = re.match(r"^st_geomfromtext\s*\(\s*(.+)\s*\)$", a, re.IGNORECASE | re.DOTALL)
    if m:
        a = m.group(1).strip()
    if a and a[0] in "'\"":
        a = a[1:-1]
    return a.strip()


def _rewrite_where(where: str) -> str:
    """Replace spark-jts spatial calls with their CQL spellings."""
    out = []
    i = 0
    lower = where.lower()
    while i < len(where):
        m = re.compile(r"st_(contains|within|intersects|disjoint|dwithin)\s*\(").match(
            lower, i
        )
        if not m:
            out.append(where[i])
            i += 1
            continue
        # balanced-paren scan for the call body
        depth = 1
        j = m.end()
        q = None
        while j < len(where) and depth:
            ch = where[j]
            if q:
                if ch == q:
                    q = None
            elif ch in "'\"":
                q = ch
            elif ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            j += 1
        if depth:
            raise SqlError(f"unbalanced parens in spatial call at {i}")
        body = where[m.end() : j - 1]
        args = _split_top(body)
        name = "st_" + m.group(1)
        cql_op = _SPATIAL[name]
        if name == "st_dwithin":
            if len(args) != 3:
                raise SqlError("ST_DWithin(col, geom, distance)")
            col, g, d = args
            out.append(f"{cql_op}({col}, {_strip_geom_literal(g)}, {d}, degrees)")
        else:
            if len(args) != 2:
                raise SqlError(f"{name}(col, geom)")
            col, g = args
            out.append(f"{cql_op}({col}, {_strip_geom_literal(g)})")
        i = j
    return "".join(out)


@dataclass
class _Item:
    kind: str  # "star" | "col" | "agg" | "fn"
    name: str  # output column name
    arg: str | None = None  # source column
    fn: str | None = None  # agg/scalar function name


def _parse_item(item: str) -> _Item:
    m = re.match(r"^(.*?)\s+as\s+(\w+)\s*$", item, re.IGNORECASE | re.DOTALL)
    alias = None
    if m:
        item, alias = m.group(1).strip(), m.group(2)
    if item == "*":
        return _Item("star", "*")
    call = re.match(r"^(\w+)\s*\(\s*(.*?)\s*\)$", item, re.DOTALL)
    if call:
        fn = call.group(1).lower()
        arg = call.group(2)
        if fn in _AGGS:
            if re.match(r"^distinct\b", arg, re.IGNORECASE):
                if fn != "count":
                    raise SqlError(
                        f"DISTINCT inside {fn.upper()}() is not supported")
                dm = re.match(r"^distinct\s+(\w+)$", arg, re.IGNORECASE)
                if not dm:
                    raise SqlError(
                        f"COUNT(DISTINCT ...) takes exactly one column: "
                        f"{arg!r}")
                return _Item(
                    "agg", alias or f"count(distinct {dm.group(1)})",
                    dm.group(1), "count_distinct",
                )
            return _Item("agg", alias or f"{fn}({arg})", arg, fn)
        if fn in _UNARY_ST and re.match(r"^\w+$", arg):
            # unary geometry→value registry UDFs ride the select list (the
            # reference registers the whole spark-jts library as SQL UDFs,
            # geomesa-spark-jts/.../DataFrameFunctions.scala); multi-arg /
            # non-geometry-input UDFs (st_buffer, st_makepoint, casts from
            # text, predicates) are rejected HERE so a bad query fails as
            # SqlError at parse, not TypeError at execution
            return _Item("fn", alias or f"{fn}({arg})", arg, fn)
        raise SqlError(f"unsupported function {fn!r} in select list")
    if not re.match(r"^\w+$", item):
        raise SqlError(f"unsupported select item {item!r}")
    return _Item("col", alias or item, item)


# select-list ST UDFs: exactly one geometry argument, scalar/geometry out.
# Multi-arg (st_buffer, st_distance, st_geometryn, ...), text-input
# constructors, and predicate forms are excluded — the registry carries no
# arity metadata, so the safe unary surface is enumerated explicitly.
_UNARY_ST = frozenset({
    "st_x", "st_y", "st_astext", "st_geohash", "st_asbinary", "st_asgeojson",
    "st_aslatlontext", "st_area", "st_centroid", "st_length",
    "st_lengthsphere", "st_boundary", "st_coorddim", "st_dimension",
    "st_envelope", "st_exteriorring", "st_geometrytype", "st_isclosed",
    "st_iscollection", "st_isempty", "st_isring", "st_issimple",
    "st_isvalid", "st_numgeometries", "st_numpoints", "st_convexhull",
    "st_antimeridiansafegeom", "st_idlsafegeom", "st_casttogeometry",
})


def _scalar_fn(fn: str, table, col: str) -> np.ndarray:
    gc = table.columns[col]
    if fn in ("st_x", "st_y"):
        if gc.x is None:
            raise SqlError(f"{fn} requires a Point column")
        return (gc.x if fn == "st_x" else gc.y).copy()
    geoms = gc.geometries()
    if fn == "st_astext":
        from geomesa_tpu.geometry.wkt import to_wkt

        return np.array(
            [None if g is None else to_wkt(g) for g in geoms], dtype=object
        )
    if fn == "st_geohash":
        from geomesa_tpu.spatial.st_functions import st_geohash

        return np.array(
            [None if g is None else st_geohash(g) for g in geoms], dtype=object
        )
    # generic single-arg registry UDF; geometry-valued results surface as
    # WKT (this is a textual SQL result set — the reference's show() does
    # the same via JTS toString)
    from geomesa_tpu.geometry.types import Geometry
    from geomesa_tpu.geometry.wkt import to_wkt
    from geomesa_tpu.spatial.st_functions import ST

    udf = ST.get(fn)
    if udf is None or fn not in _UNARY_ST:
        raise SqlError(f"unknown scalar function {fn!r}")
    out = []
    for g in geoms:
        if g is None:
            out.append(None)
            continue
        try:
            v = udf(g)
        except Exception as e:  # keep the sql() error contract
            raise SqlError(f"{fn}({col}) failed: {e}") from e
        out.append(to_wkt(v) if isinstance(v, Geometry) else v)
    return np.array(out, dtype=object)


def _agg_value(fn: str, arg: str, table, idx: np.ndarray):
    if fn == "count":
        if arg == "*":
            return len(idx)
        col = table.columns[arg]
        return int(col.is_valid()[idx].sum())
    if fn == "count_distinct":
        col = table.columns[arg]
        valid = col.is_valid()[idx]
        if col.type.is_geometry:
            # point layers keep values=None (x/y arrays); geometries()
            # materializes either layout, dedup on the wkt-ish repr
            geoms = col.geometries()[idx][valid]
            return len({str(g) for g in geoms})
        vals = col.values[idx][valid]
        try:
            return int(len(np.unique(vals)))
        except TypeError:  # mixed/unorderable object values
            return len({str(v) for v in vals})
    col = table.columns[arg]
    valid = col.is_valid()[idx]
    vals = col.values[idx][valid]
    if len(vals) == 0:
        return None
    if fn == "sum":
        return vals.sum().item()
    if fn == "min":
        return vals.min().item() if hasattr(vals.min(), "item") else min(vals)
    if fn == "max":
        return vals.max().item() if hasattr(vals.max(), "item") else max(vals)
    if fn == "avg":
        return float(np.mean(vals.astype(np.float64)))
    raise SqlError(f"unknown aggregate {fn!r}")


_JOIN = re.compile(
    r"^\s*select\s+(?P<select>.+?)\s+"
    r"from\s+(?P<t1>\w+)\s+(?P<a1>\w+)\s+"
    r"join\s+(?P<t2>\w+)\s+(?P<a2>\w+)\s+"
    r"on\s+(?P<pred>st_within|st_contains|st_intersects)\s*\(\s*"
    r"(?P<xa>\w+)\.(?P<xc>\w+)\s*,\s*(?P<ya>\w+)\.(?P<yc>\w+)\s*\)"
    r"(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+group\s+by\s+(?P<group>.+?))?"
    r"(?:\s+having\s+(?P<having>.+?))?"
    r"(?:\s+order\s+by\s+(?P<order>.+?))?"
    r"(?:\s+limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)

# predicate seen from the LEFT row when the args arrive (right, left)
_FLIP = {"within": "contains", "contains": "within", "intersects": "intersects"}


def _map_unquoted(s: str, fn) -> str:
    """Apply ``fn`` to the non-string-literal segments of a CQL/SQL text
    (single-quoted literals pass through untouched)."""
    out, cur, q = [], [], False
    for ch in s:
        if ch == "'":
            seg = "".join(cur)
            out.append(seg if q else fn(seg))
            out.append(ch)
            cur = []
            q = not q
        else:
            cur.append(ch)
    seg = "".join(cur)
    out.append(seg if q else fn(seg))
    return "".join(out)


def _join_pairs(ds, t1: str, rgeoms, left_pred: str, base_cql,
                count_only: bool = False, auths=None):
    """Join executor: the DISTRIBUTED mesh path when it applies, else the
    per-geometry index-planned host scan.

    ``count_only`` (requires ``base_cql is None``): the device path skips
    the matched-row materialization (``main.take``) and yields
    ``(right_index, match_count)`` ints — the "points per zone" fast path
    where only counts are consumed. The host fallback still yields tables
    (its materialization IS the scan) — callers must handle both.

    Mesh path (``GeoMesaRelation.scala:94``/``SQLRules.scala`` role,
    VERDICT r2 item 6): one batched block-sparse candidate gather on the
    device mesh for ALL right geometries + exact host residual
    (:func:`geomesa_tpu.process.join.join_rows_device`); the WHERE
    predicate evaluates as a vectorized AST mask on each candidate set, so
    pushdown still applies. Any structural mismatch (non-TPU backend, no
    point layout, unsupported predicate) or device failure falls back to
    :func:`geomesa_tpu.process.join.join_scan` — same yielded
    ``(right_index, left_table)`` contract either way."""
    from geomesa_tpu.process.join import join_rows_device, join_scan

    base = None
    if base_cql is not None:
        from geomesa_tpu.filter.cql import parse as _parse_cql

        base = _parse_cql(base_cql)
    pairs = None
    # merged views / remote stores lack the device machinery entirely —
    # an explicit structural test, not exception-driven (a broad
    # AttributeError catch would also swallow genuine bugs)
    # the device gather reads store tables directly and cannot apply row
    # visibility — restricted callers take the auths-aware host scan
    if auths is None and hasattr(ds, "_state") and hasattr(ds, "backend"):
        try:
            main, pairs = join_rows_device(ds, t1, rgeoms, left_pred)
        except ValueError:
            pairs = None  # structural: this store can't take the mesh path
        except Exception as e:  # noqa: BLE001 — device outage → host fallback
            if not ds._is_device_error(e):
                raise
            ds._trip_device_circuit(e)
            ds.metrics.counter("store.query.device_failovers").inc()
            pairs = None
    if pairs is None:
        yield from join_scan(ds, t1, rgeoms, left_pred, base_cql,
                             auths=auths)
        return
    ds._note_device_ok()
    for i, rows in pairs:
        if len(rows) == 0:
            yield i, None
            continue
        if count_only and base is None:
            yield i, int(len(rows))
            continue
        lt = main.take(rows)
        if base is not None:
            mask = np.asarray(base.mask(lt), dtype=bool)
            if not mask.all():
                lt = lt.take(np.nonzero(mask)[0])
        yield i, lt


class _JoinedTable:
    """Minimal ``table.columns`` shim over materialized join columns so
    :func:`_agg_value` serves the join fold unchanged."""

    def __init__(self, columns):
        self.columns = columns


def _group_first_occurrence(keys):
    """Tie rows to first-occurrence groups: ``keys`` (iterable of hashables)
    → (unique keys in first-seen order, per-group row-index lists). The one
    grouping idiom shared by DISTINCT, the single-table host fold, and the
    join fold — the tie-to-first-occurrence semantics must not drift."""
    seen: dict = {}
    groups: list[list[int]] = []
    for i, k in enumerate(keys):
        g = seen.get(k)
        if g is None:
            g = seen[k] = len(groups)
            groups.append([])
        groups[g].append(i)
    return list(seen), groups


def _parse_join_grouped(m, original, alias_sfts, select_text=None):
    """Shared ``JOIN ... GROUP BY`` clause machinery for EVERY join form
    (spatial ON ST_*, attribute equi-join, N-way chains): parse + validate
    group keys, select items, HAVING, ORDER BY, LIMIT; compute the
    materialization set ``need`` and its attribute types.
    ``alias_sfts``: ordered {alias: FeatureType}. One parser so the join
    forms' grammar and fold semantics cannot drift."""
    gcols: list[tuple[str, str]] = []
    for raw in _split_top(_clause(m, original, "group")):
        gm = re.match(r"^(\w+)\.(\w+)$", raw.strip())
        if not gm:
            raise SqlError(f"join GROUP BY keys must be alias.col: {raw!r}")
        gcols.append((gm.group(1), gm.group(2)))

    def _attr(alias, col, agg=False):
        sft = alias_sfts.get(alias)
        if sft is None:
            raise SqlError(f"unknown alias {alias!r}")
        attr = next((a for a in sft.attributes if a.name == col), None)
        if attr is None:
            raise SqlError(f"unknown column {alias}.{col}")
        if agg and attr.type.is_geometry:
            raise SqlError(f"cannot aggregate geometry column {alias}.{col}")
        return attr

    for alias, col in gcols:
        _attr(alias, col)

    # select items: group keys, COUNT(*), COUNT(DISTINCT alias.col), or
    # fn(alias.col); value computation delegates to _agg_value so the join
    # fold can never diverge from the single-table fold (null masks, float64
    # AVG, distinct semantics)
    items: list[tuple[str, str, str | None, str, str | None]] = []
    # multi-join: the select list lives in the HEAD match, not the tail
    for raw in _split_top(select_text if select_text is not None
                          else m.group("select")):
        raw = raw.strip()
        am = re.match(r"^(.*?)\s+as\s+(\w+)$", raw, re.IGNORECASE | re.DOTALL)
        expr, out = (am.group(1).strip(), am.group(2)) if am else (raw, None)
        call = re.match(r"^(count|sum|avg|min|max)\s*\(\s*(.+?)\s*\)$",
                        expr, re.IGNORECASE | re.DOTALL)
        if call:
            fn, arg = call.group(1).lower(), call.group(2).strip()
            if arg == "*":
                if fn != "count":
                    raise SqlError(f"{fn}(*) is not supported")
                items.append(("agg", out or "count(*)", None, "*", fn))
                continue
            dm = re.match(r"^distinct\s+(.+)$", arg, re.IGNORECASE)
            if dm:
                if fn != "count":
                    raise SqlError("DISTINCT is only supported in COUNT()")
                fn, arg = "count_distinct", dm.group(1).strip()
            cm = re.match(r"^(\w+)\.(\w+)$", arg)
            if not cm:
                raise SqlError(
                    f"join aggregate argument must be alias.col: {arg!r}")
            # the geometry guard applies only to arithmetic aggregates —
            # COUNT / COUNT(DISTINCT) over geometries match the
            # single-table fold
            _attr(cm.group(1), cm.group(2),
                  agg=fn in ("sum", "avg", "min", "max"))
            items.append(
                ("agg", out or f"{fn}({arg})", cm.group(1), cm.group(2), fn))
            continue
        cm = re.match(r"^(\w+)\.(\w+)$", expr)
        if not cm or (cm.group(1), cm.group(2)) not in gcols:
            raise SqlError(
                f"non-aggregate join select item must be a GROUP BY key: "
                f"{expr!r}")
        items.append(("key", out or expr, cm.group(1), cm.group(2), None))

    having = _clause(m, original, "having")
    hit = hop = hlit = None
    if having:
        hit, hop, hlit = _having_parts(having)
        if hit.arg != "*":
            hm2 = re.match(r"^(\w+)\.(\w+)$", hit.arg)
            if not hm2:
                raise SqlError(
                    f"join HAVING argument must be alias.col: {hit.arg!r}")
            _attr(hm2.group(1), hm2.group(2),
                  agg=hit.fn in ("sum", "avg", "min", "max"))
    order = _parse_order(m.group("order"), dotted=True)
    limit = int(m.group("limit")) if m.group("limit") else None
    need = list(dict.fromkeys(
        gcols
        + [(al, c) for k, _, al, c, _ in items if k == "agg" and al]
        + ([tuple(hit.arg.split(".", 1))]
           if hit is not None and hit.arg != "*" else [])))
    types = {
        (alias, col): _attr(alias, col).type for alias, col in need
    }
    return gcols, items, hit, hop, hlit, order, limit, need, types


def _grouped_fold_output(joined, gcols, items, hit, hop, hlit, order,
                         limit) -> SqlResult:
    """Shared fold tail for both join forms: first-occurrence grouping over
    the materialized join columns, HAVING filter through the single-table
    _having_parts/_agg_value pair, pre-sort LIMIT truncation, aggregate
    evaluation, ORDER BY over the output columns."""
    shim = _JoinedTable(joined)
    kvals = [joined[f"{alias}.{col}"] for alias, col in gcols]
    kvalid = [c.is_valid() for c in kvals]
    nrows = len(kvals[0]) if kvals else 0
    keys = [
        tuple(
            c.values[i] if ok[i] else None
            for c, ok in zip(kvals, kvalid)
        )
        for i in range(nrows)
    ]
    gkeys, groups = _group_first_occurrence(keys)
    if hit is not None:
        kept = [
            (k, g) for k, g in zip(gkeys, groups)
            if _having_passes(
                hit, hop, hlit,
                _agg_value(hit.fn, hit.arg, shim,
                           np.asarray(g, dtype=np.int64)),
            )
        ]
        gkeys = [k for k, _ in kept]
        groups = [g for _, g in kept]
    if limit is not None and not order:
        # truncation before aggregation is only sound when no sort can
        # reorder groups afterwards (HAVING already filtered above)
        gkeys, groups = gkeys[:limit], groups[:limit]
    cols: dict[str, np.ndarray] = {}
    for kind, name, alias, col, fn in items:
        if kind == "key":
            gi = gcols.index((alias, col))
            cols[name] = np.array([k[gi] for k in gkeys], dtype=object)
            continue
        arg = "*" if col == "*" else f"{alias}.{col}"
        cols[name] = np.array(
            [
                _agg_value(fn, arg, shim, np.asarray(g, dtype=np.int64))
                for g in groups
            ],
            dtype=object,
        )
    return _apply_order_limit(SqlResult(cols), order, limit)


def _join_grouped_fold(ds, m, original, t1, a1, sft1, a2, sft2,
                       left_pred, base_cql, auths=None) -> SqlResult:
    """``JOIN ... GROUP BY``: first-occurrence host fold over the streamed
    join pairs — the single-table host fold's semantics applied to the
    joined relation ("points per zone"). The reference composes these
    freely through Spark Catalyst (`geomesa-spark-sql/.../SQLRules.scala`);
    here the join scan stays index-pruned and only the group keys and
    aggregate argument columns are materialized (streaming — values AND
    validity, so sentinel-valued NULLs neither pollute aggregates nor
    conflate with real zeros in group keys)."""
    from geomesa_tpu.schema.columnar import Column, GeometryColumn

    gcols, items, hit, hop, hlit, order, limit, need, types = \
        _parse_join_grouped(m, original, {a1: sft1, a2: sft2})
    right = ds.query(m.group("t2"), Query(auths=auths)).table
    rgeoms = right.geom_column().geometries()
    vals_acc: dict[tuple[str, str], list] = {kc: [] for kc in need}
    valid_acc: dict[tuple[str, str], list] = {kc: [] for kc in need}
    # right-table columns are constant across pairs: fetch values/validity
    # once, index [j] inside the loop
    rcols = {}
    for alias, col in need:
        if alias != a1:
            c = right.columns[col]
            rcols[col] = (
                c.geometries() if c.type.is_geometry else c.values,
                c.is_valid(),
            )
    # "points per zone" fast path: no left columns consumed and no WHERE —
    # the device join need only return match counts, never the rows
    count_only = base_cql is None and all(alias != a1 for alias, _ in need)
    for j, lt in _join_pairs(ds, t1, rgeoms, left_pred, base_cql,
                             count_only=count_only, auths=auths):
        if lt is None:
            continue
        n = lt if isinstance(lt, int) else len(lt)
        if n == 0:
            continue
        for alias, col in need:
            if alias == a1:
                c = lt.columns[col]
                v = c.geometries() if c.type.is_geometry else c.values
                vals_acc[(alias, col)].extend(v)
                valid_acc[(alias, col)].extend(c.is_valid())
            else:
                rv, rvalid = rcols[col]
                vals_acc[(alias, col)].extend([rv[j]] * n)
                valid_acc[(alias, col)].extend([bool(rvalid[j])] * n)

    def _joined_column(kc):
        t = types[kc]
        valid = np.asarray(valid_acc[kc], dtype=bool)
        if t.is_geometry:
            # GeometryColumn so count_distinct's geometries() works
            return GeometryColumn(
                t, np.array(vals_acc[kc], dtype=object), valid)
        obj = t.name in ("STRING", "UUID", "BYTES")
        return Column(
            t,
            np.array(vals_acc[kc], dtype=object) if obj
            else np.asarray(vals_acc[kc]),
            valid,
        )

    joined = {
        f"{alias}.{col}": _joined_column((alias, col))
        for alias, col in need
    }
    return _grouped_fold_output(
        joined, gcols, items, hit, hop, hlit, order, limit)


def _sql_join(ds, m, original: str | None = None, auths=None) -> SqlResult:
    """Spatial JOIN: each right-table geometry becomes an index-planned scan
    of the left table (delegating to :func:`geomesa_tpu.process.join
    .join_scan` — the JoinProcess core, never a cartesian pass), pairs
    streamed into alias-qualified columns. Right side should be the smaller
    relation (polygon sets). ``m`` may be a match on the quote-masked
    statement; ``original`` supplies literal-bearing clause text."""
    original = original if original is not None else m.string
    t1, a1, t2, a2 = m.group("t1"), m.group("a1"), m.group("t2"), m.group("a2")
    if a1 == a2:
        raise SqlError(f"duplicate join alias {a1!r}")
    pred = m.group("pred").lower().removeprefix("st_")
    xa, xc, ya, yc = m.group("xa"), m.group("xc"), m.group("ya"), m.group("yc")
    if {xa, ya} != {a1, a2}:
        raise SqlError("ON predicate must reference both join aliases")
    # normalize to pred(left.geom, right.geom)
    if xa == a1:
        left_col, right_col, left_pred = xc, yc, pred
    else:
        left_col, right_col, left_pred = yc, xc, _FLIP[pred]
    sft1 = ds.get_schema(t1)
    sft2 = ds.get_schema(t2)
    if left_col != sft1.geom_field:
        raise SqlError(f"{a1}.{left_col} is not {t1}'s geometry column")
    if right_col != sft2.geom_field:
        raise SqlError(f"{a2}.{right_col} is not {t2}'s geometry column")

    # WHERE pushes to the LEFT scan (strip the alias); right-side or mixed
    # predicates are not supported in v1 of the join grammar. Alias checks
    # and rewrites apply outside string literals only.
    base_cql = None
    if m.group("where"):
        w = _clause(m, original, "where")
        found_right = False

        def _check(seg):
            nonlocal found_right
            if re.search(rf"\b{a2}\s*\.", seg):
                found_right = True
            return seg

        _map_unquoted(w, _check)
        if found_right:
            raise SqlError("JOIN WHERE may reference only the left alias")
        base_cql = _rewrite_where(
            _map_unquoted(w, lambda seg: re.sub(rf"\b{a1}\s*\.", "", seg))
        )

    if m.group("group"):
        return _join_grouped_fold(
            ds, m, original, t1, a1, sft1, a2, sft2, left_pred, base_cql,
            auths=auths,
        )
    if m.group("having"):
        raise SqlError("HAVING requires GROUP BY")
    order = _parse_order(m.group("order"), dotted=True)

    # select items: alias.col or alias.* (duplicates collapse, order kept)
    items: list[tuple[str, str]] = []
    for raw in _split_top(m.group("select")):
        im = re.match(r"^(\w+)\.(\w+|\*)$", raw.strip())
        if not im:
            raise SqlError(f"join select items must be alias.col: {raw!r}")
        items.append((im.group(1), im.group(2)))
    expanded: list[tuple[str, str]] = []
    for alias, col in items:
        if alias not in (a1, a2):
            raise SqlError(f"unknown alias {alias!r}")
        sft = sft1 if alias == a1 else sft2
        if col == "*":
            expanded.extend((alias, attr.name) for attr in sft.attributes)
        elif col not in {attr.name for attr in sft.attributes}:
            raise SqlError(f"unknown column {alias}.{col}")
        else:
            expanded.append((alias, col))
    expanded = list(dict.fromkeys(expanded))

    limit = int(m.group("limit")) if m.group("limit") else None
    # a sort reorders rows: streaming early-exit on LIMIT is only sound
    # without ORDER BY (limit then applies after the sort instead)
    stream_limit = None if order else limit
    right = ds.query(t2, Query(auths=auths)).table
    rgeoms = right.geom_column().geometries()

    from geomesa_tpu.process.join import join_scan

    out: dict[str, list] = {f"{alias}.{col}": [] for alias, col in expanded}
    total = 0
    for j, lt in _join_pairs(ds, t1, rgeoms, left_pred, base_cql,
                             auths=auths):
        n = 0 if lt is None else len(lt)
        if n == 0:
            continue
        if stream_limit is not None:
            n = min(n, stream_limit - total)
        for alias, col in expanded:
            key = f"{alias}.{col}"
            if alias == a1:
                c = lt.columns[col]
                vals = c.geometries() if c.type.is_geometry else c.values
                out[key].extend(vals[:n])
            else:
                c = right.columns[col]
                v = (
                    c.geometries()[j] if c.type.is_geometry else c.values[j]
                )
                out[key].extend([v] * n)
        total += n
        if stream_limit is not None and total >= stream_limit:
            break
    return _apply_order_limit(
        SqlResult({k: np.asarray(v, dtype=object) for k, v in out.items()}),
        order, limit if order else None,
    )


def _equi_key_arrays(lcol, rcol, a1, a2, lc, rc):
    """Join-key columns → (lkeys, lvalid, rkeys, rvalid) in one comparable,
    C-sortable domain. Numeric/Date/Boolean pairs meet in int64 when both
    are integral (exact at any magnitude), else float64; strings meet as
    fixed-width unicode — numpy's lexical order IS the attribute
    lexicoder's total order (`index/attribute.py` sorts the same way), so
    the sorted-merge below walks the same key-space the reference's
    join index serves lookups from (``AccumuloJoinIndex.scala:45``)."""
    from geomesa_tpu.schema.sft import AttributeType as T

    for alias, col, t in ((a1, lc, lcol.type), (a2, rc, rcol.type)):
        if t.is_geometry:
            raise SqlError(
                f"equi-join key {alias}.{col} is a geometry column — use "
                f"the spatial ON ST_*(...) form")
    stringy = {T.STRING, T.UUID}
    integral = {T.INT, T.LONG, T.DATE, T.BOOLEAN}
    numeric = integral | {T.FLOAT, T.DOUBLE}

    def _cast(col, dtype):
        valid = col.is_valid()
        vals = col.values
        if dtype is str:
            # non-str values (e.g. uuid.UUID objects) key on their str()
            # form — the lexicoder's canonical text; only INVALID slots may
            # collapse to "" (they never match: validity gates the merge)
            out = np.asarray(
                [(v if isinstance(v, str) else str(v)) if ok else ""
                 for v, ok in zip(vals, valid)], dtype=str)
        else:
            out = np.where(valid, vals, 0).astype(dtype)
        return out, valid

    if lcol.type in stringy and rcol.type in stringy:
        lk, lv = _cast(lcol, str)
        rk, rv = _cast(rcol, str)
        # meet in one unicode width or searchsorted compares truncated keys
        width = max(lk.dtype.itemsize, rk.dtype.itemsize) // 4 or 1
        return (lk.astype(f"U{width}"), lv, rk.astype(f"U{width}"), rv)
    if lcol.type in numeric and rcol.type in numeric:
        dt = (np.int64 if lcol.type in integral and rcol.type in integral
              else np.float64)
        lk, lv = _cast(lcol, dt)
        rk, rv = _cast(rcol, dt)
        return lk, lv, rk, rv
    raise SqlError(
        f"incompatible equi-join key types {lcol.type.value} vs "
        f"{rcol.type.value}")


def _equi_pairs(lkeys, lvalid, rkeys, rvalid):
    """Vectorized sorted-merge inner join → (li, rj) row-index arrays.

    Sort the right side once (O(m log m)), binary-search every left key
    into it (O(n log m)), expand the hit runs without a Python loop. SQL
    NULL semantics: invalid keys on either side match nothing. Pair order
    is left-major with right matches in right-table order (stable sort),
    so results are deterministic."""
    ridx = np.flatnonzero(rvalid)
    order = ridx[np.argsort(rkeys[ridx], kind="stable")]
    rs = rkeys[order]
    lo = np.searchsorted(rs, lkeys, side="left")
    hi = np.searchsorted(rs, lkeys, side="right")
    cnt = np.where(lvalid, hi - lo, 0).astype(np.int64)
    total = int(cnt.sum())
    li = np.repeat(np.arange(len(lkeys), dtype=np.int64), cnt)
    starts = np.repeat(lo, cnt)
    run = np.arange(total, dtype=np.int64) - np.repeat(
        np.concatenate(([0], np.cumsum(cnt)[:-1])), cnt)
    rj = order[starts + run]
    return li, rj


def _equi_grouped_fold(m, original, alias_sfts, pair_column,
                       select_text=None) -> SqlResult:
    """Equi-join GROUP BY (2-way and N-way): the shared join-grammar parse
    + fold tail (:func:`_parse_join_grouped` / :func:`_grouped_fold_output`
    — the same helpers the spatial join streams through), fed by
    vectorized joined columns from the sorted-merge pairing."""
    from geomesa_tpu.schema.columnar import Column, GeometryColumn

    gcols, items, hit, hop, hlit, order, limit, need, types = \
        _parse_join_grouped(m, original, alias_sfts,
                            select_text=select_text)
    joined = {}
    for alias, col in need:
        t, vals, valid = pair_column(alias, col)
        if t.is_geometry:
            joined[f"{alias}.{col}"] = GeometryColumn(
                t, np.asarray(vals, dtype=object), valid)
        else:
            obj = t.name in ("STRING", "UUID", "BYTES")
            joined[f"{alias}.{col}"] = Column(
                t,
                np.asarray(vals, dtype=object) if obj else np.asarray(vals),
                valid,
            )
    return _grouped_fold_output(
        joined, gcols, items, hit, hop, hlit, order, limit)


_MJ_HEAD = re.compile(
    r"^\s*select\s+(?P<select>.+?)\s+from\s+(?P<t1>\w+)\s+(?P<a1>\w+)"
    r"(?=\s+(?:left\s+(?:outer\s+)?)?join\b)",
    re.IGNORECASE | re.DOTALL,
)
_MJ_SEG = re.compile(
    r"\s+(?P<left>left\s+(?:outer\s+)?)?join\s+(?P<t>\w+)\s+(?P<a>\w+)\s+"
    r"on\s+(?P<xa>\w+)\.(?P<xc>\w+)\s*=\s*(?P<ya>\w+)\.(?P<yc>\w+)",
    re.IGNORECASE,
)
_MJ_TAIL = re.compile(
    r"^(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+group\s+by\s+(?P<group>.+?))?"
    r"(?:\s+having\s+(?P<having>.+?))?"
    r"(?:\s+order\s+by\s+(?P<order>.+?))?"
    r"(?:\s+limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)


def _sql_multi_join(ds, masked: str, original: str, auths=None) -> SqlResult:
    """N-way attribute equi-join: ``FROM t1 a JOIN t2 b ON a.x = b.x JOIN
    t3 c ON b.y = c.y ...`` — the arbitrary relational join chains the
    reference reaches through Spark Catalyst
    (``GeoMesaRelation.scala:47``). Executed as a LEFT-DEEP chain of
    vectorized sorted-merges: each ON links the newly joined table to one
    already-bound alias; the running result is a set of per-alias row
    index arrays, re-indexed by each merge (no materialization until the
    select list). ``LEFT [OUTER] JOIN`` keeps unmatched bound rows with a
    -1 sentinel for the new alias — its columns surface as SQL NULL and
    its keys never match downstream joins (NULL-propagation semantics).
    WHERE conjuncts referencing exactly one alias push down to that
    alias's index-planned scan — EXCEPT conjuncts on a LEFT-JOIN-introduced
    alias, which evaluate after the join (pushdown would pre-filter the
    right side and let failing matches survive as NULL-extended rows;
    standard SQL drops them). NULL-extended rows evaluate such conjuncts
    over an all-null row — ``IS NULL`` passes, comparisons fail — the same
    two-valued null semantics as the single-table WHERE. GROUP BY/HAVING/
    ORDER BY/LIMIT compose through the shared join-grammar helpers."""
    m1 = _MJ_HEAD.match(masked)
    if not m1:
        raise SqlError(f"cannot parse multi-join: {original!r}")
    pos = m1.end()
    segs = []
    while True:
        sm = _MJ_SEG.match(masked, pos)
        if sm is None:
            break
        segs.append(sm)
        pos = sm.end()
    if not segs:
        raise SqlError(f"cannot parse join: {original!r}")
    tm = _MJ_TAIL.match(masked[pos:])
    if tm is None:
        raise SqlError(f"cannot parse join tail: {original[pos:]!r}")
    # tail spans are relative to masked[pos:] — pair them with the SAME
    # slice of the original for _clause's span slicing
    tail_original = original[pos:]

    a1 = m1.group("a1")
    aliases: dict[str, str] = {a1: m1.group("t1")}
    for sm in segs:
        a = sm.group("a")
        if a in aliases:
            raise SqlError(f"duplicate join alias {a!r}")
        aliases[a] = sm.group("t")
    sfts = {a: ds.get_schema(t) for a, t in aliases.items()}
    left_aliases = {sm.group("a") for sm in segs if sm.group("left")}

    # WHERE: each conjunct routes to the one alias it references. Conjuncts
    # on LEFT-JOIN aliases apply post-join; everything else pushes down.
    per_alias: dict[str, list[str]] = {a: [] for a in aliases}
    post_join: dict[str, list[str]] = {a: [] for a in aliases}
    if tm.group("where"):
        w = _clause(tm, tail_original, "where")
        for part in _split_conjuncts(w):
            refs = set()

            def _scan(seg):
                for am in re.finditer(r"\b(\w+)\s*\.", seg):
                    refs.add(am.group(1))
                return seg

            _map_unquoted(part, _scan)
            refs &= set(aliases)
            if len(refs) != 1:
                raise SqlError(
                    f"multi-join WHERE conjunct must reference exactly one "
                    f"alias: {part.strip()!r}")
            al = refs.pop()
            stripped = _map_unquoted(
                part, lambda seg: re.sub(rf"\b{al}\s*\.", "", seg))
            (post_join if al in left_aliases else per_alias)[al].append(
                stripped)
    tables = {
        a: ds.query(
            aliases[a],
            Query(
                filter=_rewrite_where(" AND ".join(cs)) if cs else None,
                auths=auths,
            ),
        ).table
        for a, cs in per_alias.items()
    }

    def _check_col(alias, col):
        if col not in {at.name for at in sfts[alias].attributes}:
            raise SqlError(f"unknown column {alias}.{col}")

    def _take_masked(col, idx):
        """Column at ``idx`` with -1 sentinels (unmatched LEFT-JOIN rows)
        reading as NULL: value slot 0, validity cleared."""
        miss = idx < 0
        if not miss.any():
            return col.take(idx)
        if len(col) == 0:
            # LEFT-joined empty table: every idx is the sentinel — there is
            # no slot 0 to mask, so synthesize the all-null column outright
            from geomesa_tpu.schema.columnar import null_column

            return null_column(col.type, len(idx))
        out = col.take(np.where(miss, 0, idx))
        valid = out.is_valid() & ~miss
        out.valid = valid
        return out

    bound: dict[str, np.ndarray] | None = None
    bound_aliases = {a1}
    for sm in segs:
        xa, xc = sm.group("xa"), sm.group("xc")
        ya, yc = sm.group("ya"), sm.group("yc")
        new_a = sm.group("a")
        if xa == new_a and ya in bound_aliases:
            ba, bc, nc = ya, yc, xc
        elif ya == new_a and xa in bound_aliases:
            ba, bc, nc = xa, xc, yc
        else:
            raise SqlError(
                f"ON for {new_a!r} must link it to an already-bound alias")
        _check_col(ba, bc)
        _check_col(new_a, nc)
        lcol = tables[ba].columns[bc]
        nl = len(lcol) if bound is None else len(bound[ba])
        if bound is not None:
            lcol = _take_masked(lcol, bound[ba])
        li, rj = _equi_pairs(*_equi_key_arrays(
            lcol, tables[new_a].columns[nc], ba, new_a, bc, nc))
        if sm.group("left"):
            # unmatched bound rows survive with a -1 sentinel for new_a
            unmatched = np.setdiff1d(np.arange(nl, dtype=np.int64), li)
            li = np.concatenate([li, unmatched])
            rj = np.concatenate(
                [rj, np.full(len(unmatched), -1, dtype=rj.dtype)])
            keep = np.argsort(li, kind="stable")  # left-major determinism
            li, rj = li[keep], rj[keep]
        if bound is None:
            bound = {ba: li}
        else:
            bound = {al: v[li] for al, v in bound.items()}
        bound[new_a] = rj
        bound_aliases.add(new_a)

    post = {a: cs for a, cs in post_join.items() if cs}
    if post:
        from geomesa_tpu.filter.cql import parse as _parse_cql
        from geomesa_tpu.schema.columnar import FeatureTable, null_column

        nrows = len(next(iter(bound.values())))
        keep = np.ones(nrows, dtype=bool)
        for al, cs in post.items():
            filt = _parse_cql(_rewrite_where(" AND ".join(cs)))
            t = tables[al]
            idx = bound[al]
            miss = idx < 0
            # NULL-extended rows see the predicate over an all-null row
            # (IS NULL passes, comparisons fail — the engine's two-valued
            # null semantics); filters that cannot evaluate on nulls at
            # all (spatial ops) simply drop those rows
            try:
                nt = FeatureTable(
                    t.sft, np.asarray(["_null"], dtype=object),
                    {n: null_column(c.type, 1)
                     for n, c in t.columns.items()},
                )
                null_pass = bool(filt.mask(nt)[0])
            except Exception:  # noqa: BLE001 — fail closed on null rows
                null_pass = False
            if len(t):
                ok = filt.mask(t)[np.where(miss, 0, idx)]
            else:
                ok = np.zeros(nrows, dtype=bool)
            keep &= np.where(miss, null_pass, ok)
        bound = {a: v[keep] for a, v in bound.items()}

    def pair_column(alias, col):
        c = tables[alias].columns[col]
        idx = bound[alias]
        miss = idx < 0
        if len(c) == 0:
            # LEFT-joined empty table: idx is all sentinels; synthesize the
            # NULL-extended output instead of indexing a slot that isn't
            # there (object array: np.empty initializes to None)
            return (c.type, np.empty(len(idx), dtype=object),
                    np.zeros(len(idx), dtype=bool))
        safe = np.where(miss, 0, idx)
        v = c.geometries() if c.type.is_geometry else c.values
        return c.type, np.asarray(v)[safe], c.is_valid()[safe] & ~miss

    if tm.group("group"):
        return _equi_grouped_fold(tm, tail_original, sfts, pair_column,
                                  select_text=m1.group("select"))
    if tm.group("having"):
        raise SqlError("HAVING requires GROUP BY")
    order = _parse_order(tm.group("order"), dotted=True)
    limit = int(tm.group("limit")) if tm.group("limit") else None
    if limit is not None and not order:
        bound = {al: v[:limit] for al, v in bound.items()}

    expanded: list[tuple[str, str]] = []
    for raw in _split_top(m1.group("select")):
        im = re.match(r"^(\w+)\.(\w+|\*)$", raw.strip())
        if not im:
            raise SqlError(f"join select items must be alias.col: {raw!r}")
        alias, col = im.group(1), im.group(2)
        if alias not in aliases:
            raise SqlError(f"unknown alias {alias!r}")
        if col == "*":
            expanded.extend(
                (alias, at.name) for at in sfts[alias].attributes)
        else:
            _check_col(alias, col)
            expanded.append((alias, col))
    expanded = list(dict.fromkeys(expanded))
    out = {}
    for alias, col in expanded:
        _, vals, valid = pair_column(alias, col)
        vo = np.empty(len(vals), dtype=object)
        vo[:] = vals
        vo[~valid] = None
        out[f"{alias}.{col}"] = vo
    return _apply_order_limit(SqlResult(out), order, limit if order else None)


_MESH_AGG_TYPES = (
    "Integer", "Long", "Float", "Double", "Boolean", "Date",
)


def _parse_order(text: str | None, dotted: bool = False):
    """ORDER BY clause → [(name, desc)] or None; ``dotted`` admits
    alias-qualified names (the join grammar). One parser for every path —
    the single-table and join grammars must not drift."""
    if not text:
        return None
    pat = r"^([\w.]+)(?:\s+(asc|desc))?$" if dotted else \
        r"^(\w+)(?:\s+(asc|desc))?$"
    order = []
    for part in _split_top(text):
        om = re.match(pat, part.strip(), re.IGNORECASE)
        if not om:
            raise SqlError(f"unsupported ORDER BY {part!r}")
        order.append(
            (om.group(1), bool(om.group(2) and om.group(2).lower() == "desc"))
        )
    if not order:
        raise SqlError(f"unsupported ORDER BY {text!r}")
    return order


def _having_parts(having: str):
    """Parse a HAVING clause → (agg item, comparison op, literal); shared
    between the host fold and the mesh path so their validation errors and
    comparison semantics can never diverge."""
    import operator as _op

    hm = _HAVING.match(having)
    if not hm:
        raise SqlError(f"unsupported HAVING {having!r} "
                       "(expected agg(col) <op> number)")
    hit = _parse_item(hm.group("expr"))
    if hit.kind != "agg":
        raise SqlError("HAVING supports aggregate comparisons only")
    if hit.arg == "*" and hit.fn != "count":
        raise SqlError(f"{hit.fn.upper()}(*) is not supported")
    ops = {"=": _op.eq, "<>": _op.ne, "<": _op.lt, "<=": _op.le,
           ">": _op.gt, ">=": _op.ge}
    return hit, ops[hm.group("op")], float(hm.group("lit"))


def _having_passes(hit, op, lit: float, v) -> bool:
    if v is None:
        return False
    try:
        return bool(op(float(v), lit))
    except (TypeError, ValueError):
        raise SqlError(
            f"HAVING {hit.fn.upper()}({hit.arg}) is not numeric"
        ) from None


def _apply_order_limit(res: SqlResult, order, limit, offset: int = 0) -> SqlResult:
    """``order`` is a list of (column, desc) pairs — multi-key sorts apply
    keys last-to-first with stable sorts (lexicographic order). Tie
    behavior is the store's (``store.reduce.stable_order``), so engine
    paths are order-indistinguishable. OFFSET skips rows AFTER the sort
    (SQL semantics), before LIMIT truncates."""
    from geomesa_tpu.store.reduce import stable_order

    cols = res.columns
    if order:
        for col_name, desc in reversed(order):
            if col_name not in cols:
                raise SqlError(f"ORDER BY {col_name!r} not in select list")
            perm = stable_order(cols[col_name], desc)
            cols = {k: v[perm] for k, v in cols.items()}
        res = SqlResult(cols)
    if offset or limit is not None:
        end = None if limit is None else offset + limit
        res = SqlResult({k: v[offset:end] for k, v in res.columns.items()})
    return res


def _mesh_agg_cast(sft, col: str, fn: str, v):
    """Mirror the host fold's Python result types from the device's f64
    partials: integral columns return ints for sum/min/max, AVG is float."""
    if v is None or fn == "avg":
        return v
    t = next(a.type.value for a in sft.attributes if a.name == col)
    if t in ("Integer", "Long", "Date"):
        return int(round(v))
    if t == "Boolean":
        return int(round(v)) if fn == "sum" else bool(round(v))
    return float(v)


def _mesh_aggregate(ds, type_name: str, cql, items, group_by, having,
                    order, limit, offset: int = 0, auths=None):
    """Route the aggregate fold to ``DataStore.aggregate_many`` (the fused
    mesh segment-reduce). Returns the assembled SqlResult, or None when the
    query cannot ride the device path — the caller's host fold serves it
    (and raises its own errors, so validation here only ever declines)."""
    agg = getattr(ds, "aggregate_many", None)
    if agg is None:
        return None
    try:
        sft = ds.get_schema(type_name)
    except Exception:  # noqa: BLE001 — host path raises the real error
        return None
    attr_types = {a.name: a.type.value for a in sft.attributes}
    specs = [i for i in items if i.kind == "agg"]
    hit = hop = lit = None
    if having:
        hit, hop, lit = _having_parts(having)
        specs = specs + [hit]
    value_cols = []
    for it in specs:
        if it.fn not in ("count", "sum", "min", "max", "avg"):
            return None
        if it.arg == "*":
            if it.fn != "count":
                return None
            continue
        if attr_types.get(it.arg) not in _MESH_AGG_TYPES:
            return None  # strings/geometries: host fold
        if it.arg not in value_cols:
            value_cols.append(it.arg)
    for g in group_by or []:
        t = attr_types.get(g)
        if t is None or t not in (*_MESH_AGG_TYPES, "String", "UUID"):
            return None
    res = agg(
        type_name, [Query(filter=cql, auths=auths)], group_by=group_by,
        value_cols=value_cols,
    )[0]
    if res is None:
        return None
    groups = res["groups"]
    cnt = res["count"]
    vcols = res["cols"]

    def _value(it, g: int):
        if it.arg == "*":
            return int(cnt[g])
        d = vcols[it.arg]
        n = int(d["count"][g])
        if it.fn == "count":
            return n
        if n == 0:
            return None
        if it.fn == "sum":
            return _mesh_agg_cast(sft, it.arg, "sum", float(d["sum"][g]))
        if it.fn == "avg":
            return float(d["sum"][g]) / n
        v = float(d["min" if it.fn == "min" else "max"][g])
        return _mesh_agg_cast(sft, it.arg, it.fn, v)

    idx = list(range(len(groups)))
    if not group_by and not idx:
        # no-GROUP-BY over zero rows still yields ONE result row
        # (COUNT = 0, other aggregates NULL) — host-fold parity
        groups = [()]
        cnt = np.zeros(1, dtype=np.int64)
        vcols = {
            c: {k: np.zeros(1) for k in ("count", "sum", "min", "max")}
            for c in vcols
        }
        idx = [0]
    if hit is not None:
        idx = [
            g for g in idx if _having_passes(hit, hop, lit, _value(hit, g))
        ]
    cols: dict[str, np.ndarray] = {}
    for it in items:
        if it.kind == "col":
            gi = group_by.index(it.arg)
            cols[it.name] = np.array(
                [groups[g][gi] for g in idx], dtype=object
            )
        else:
            cols[it.name] = np.array(
                [_value(it, g) for g in idx], dtype=object
            )
    return _apply_order_limit(SqlResult(cols), order, limit, offset)


def _fn_args(m: "re.Match", original: str) -> list:
    """Parse a table function's argument list (span sliced from the
    ORIGINAL statement — the mask blanked quoted content): quoted
    strings → str, bare numerics → int/float."""
    a, b = m.span("args")
    out = []
    for part in _split_top(original[a:b]):
        p = part.strip()
        if len(p) >= 2 and p[0] in "'\"" and p[-1] == p[0]:
            out.append(p[1:-1])
        else:
            try:
                out.append(int(p))
            except ValueError:
                try:
                    out.append(float(p))
                except ValueError:
                    raise SqlError(
                        f"bad table-function argument {p!r}") from None
    return out


def _parse_track(text: str) -> list:
    """'x y t, x y t, ...' (or ';'-separated) → [(lon, lat, epoch_ms)]."""
    out = []
    for wp in re.split(r"[,;]", text):
        wp = wp.strip()
        if not wp:
            continue
        parts = wp.split()
        if len(parts) != 3:
            raise SqlError(
                f"tube waypoint must be 'x y epoch_ms', got {wp!r}")
        out.append((float(parts[0]), float(parts[1]), int(float(parts[2]))))
    return out


def _table_cols(table) -> dict:
    """FeatureTable → SqlResult column dict (fid + every attribute, the
    ``SELECT *`` materialization rule)."""
    cols: dict = {"__fid__": np.asarray(table.fids, dtype=object)}
    for a in table.sft.attributes:
        c = table.columns[a.name]
        cols[a.name] = c.geometries() if a.type.is_geometry else c.values
    return cols


def _sql_table_function(ds, m: "re.Match", original: str,
                        auths=None) -> SqlResult:
    """The trajectory plane's SQL surface (docs/trajectory.md):
    ``TUBE_SELECT`` (corridor engine), ``TRACK_STATS`` (batched
    per-entity aggregation), ``ST_LINK`` (two-store interlink — both
    sides resolve against ``ds``, which for a federated view is the
    merged surface)."""
    fn = m.group("fn").lower()
    args = _fn_args(m, original)
    limit = int(m.group("limit")) if m.group("limit") else None

    def need(lo: int, hi: int, sig: str):
        if not (lo <= len(args) <= hi):
            raise SqlError(f"{fn.upper()} expects {sig}")

    if fn == "tube_select":
        need(4, 5, "('type', 'x y t, ...', buffer_deg, time_buffer_ms"
                   " [, 'cql'])")
        from geomesa_tpu.trajectory.corridor import tube_select_device

        table = tube_select_device(
            ds, str(args[0]), _parse_track(str(args[1])), float(args[2]),
            int(args[3]), filter=(str(args[4]) if len(args) > 4 else None),
            auths=auths)
        res = SqlResult(_table_cols(table))
    elif fn == "track_stats":
        need(2, 3, "('type', 'track_field' [, 'cql'])")
        from geomesa_tpu.trajectory.state import track_stats

        res = SqlResult(track_stats(
            ds, str(args[0]), str(args[1]),
            filter=(str(args[2]) if len(args) > 2 else None),
            auths=auths))
    else:  # st_link
        need(3, 5, "('ltype', 'rtype', 'pred' [, distance"
                   " [, time_buffer_ms]])")
        from geomesa_tpu.trajectory.interlink import interlink

        pairs = interlink(
            ds, str(args[0]), ds, str(args[1]), pred=str(args[2]).lower(),
            distance=(float(args[3]) if len(args) > 3 else 0.0),
            time_buffer_ms=(int(args[4]) if len(args) > 4 else None),
            auths=auths)
        res = SqlResult({
            "left_fid": np.asarray([p[0] for p in pairs], dtype=object),
            "right_fid": np.asarray([p[1] for p in pairs], dtype=object),
        })
    return _apply_order_limit(res, None, limit, 0)


def sql(ds, statement: str, auths=None) -> SqlResult:
    """Execute a SQL statement against ``ds`` (DataStore or merged view).

    ``auths``: caller visibility authorizations, threaded into EVERY
    internal store query (the serving layer's restricted callers see only
    their rows). Paths that cannot apply row visibility — the fused mesh
    aggregation and the device join gather — decline automatically and the
    auths-aware host paths serve instead."""
    from geomesa_tpu import obs

    # one span per statement; the store queries/aggregations it issues
    # nest underneath, so a slow statement decomposes in the trace
    with obs.span("sql", statement=statement[:200]):
        return _run_statement(ds, statement, auths)


def _run_statement(ds, statement: str, auths=None) -> SqlResult:
    # clause keywords are matched on a quote-masked shadow so a WHERE
    # literal containing e.g. 'having' cannot hijack clause splitting; the
    # spans are then sliced from the original statement
    masked = _mask_quotes(statement)
    tf = _TABLE_FN.match(masked)
    if tf:
        return _sql_table_function(ds, tf, statement, auths=auths)
    jm = _JOIN.match(masked)
    if jm:
        return _sql_join(ds, jm, statement, auths=auths)
    # attribute equi-join chains (2-way and N-way): dispatch on STRUCTURE
    # (head + at least one ON a.x = b.y segment), never on token counts —
    # a column literally named "join" must keep parsing via _CLAUSES
    mh = _MJ_HEAD.match(masked)
    if mh is not None:
        mpos = mh.end()
        nsegs = 0
        while (msm := _MJ_SEG.match(masked, mpos)) is not None:
            nsegs += 1
            mpos = msm.end()
        if nsegs >= 1:
            return _sql_multi_join(ds, masked, statement, auths=auths)
    m = _CLAUSES.match(masked)
    if not m:
        raise SqlError(f"cannot parse: {statement!r}")
    items = [_parse_item(i) for i in _split_top(_clause(m, statement, "select"))]
    type_name = m.group("from")
    where = _clause(m, statement, "where")
    group_raw = _clause(m, statement, "group")
    group_by = [g.strip() for g in group_raw.split(",")] if group_raw else None
    limit = int(m.group("limit")) if m.group("limit") else None
    offset = int(m.group("offset")) if m.group("offset") else 0
    order = _parse_order(m.group("order"))

    cql = _rewrite_where(where) if where else None
    has_agg = any(i.kind == "agg" for i in items)
    distinct = bool(m.group("distinct"))
    having = _clause(m, statement, "having")
    if having and not group_by:
        raise SqlError("HAVING requires GROUP BY")
    if distinct and (has_agg or group_by):
        raise SqlError("DISTINCT is not supported with aggregates/GROUP BY")

    # GROUP BY without aggregate select items is only meaningful with a
    # HAVING filter (SELECT name ... GROUP BY name HAVING COUNT(*) > n)
    if not has_agg and not (group_by and having):
        if group_by:
            raise SqlError("GROUP BY requires aggregate select items")
        # DISTINCT over plain columns IS a GROUP BY with no aggregates —
        # ride the fused mesh fold (zero row materialization, first-
        # occurrence order, NaN/unsupported types decline to the host
        # dedup below). ORDER BY is held for the distinct row set.
        if (
            distinct and items
            and all(i.kind == "col" for i in items)
            # the host path can ORDER BY a non-selected column through the
            # store's sort pushdown; the mesh fold only has the key columns
            and all(o[0] in {i.name for i in items} for o in order or [])
        ):
            mesh_res = _mesh_aggregate(
                ds, type_name, cql, items, [i.arg for i in items],
                None, order, limit, offset,
            )
            if mesh_res is not None:
                return mesh_res
        # single-key ORDER BY pushes to the store (aliases resolved to
        # source columns); multi-key sorts here after materialization
        push_sort = post_sort = None
        if order and len(order) == 1:
            fld, desc = order[0]
            src = next(
                (i.arg for i in items if i.kind == "col" and i.name == fld),
                fld,
            )
            push_sort = (src, desc)
        elif order:
            post_sort = order
        # projection pushdown only when every item is a plain column; scalar
        # fns need their source column materialized. DISTINCT dedupes after
        # the scan, so the limit must not truncate pre-dedup. A multi-key
        # sort may reference UNSELECTED schema columns — materialize them
        # too (they feed the sort keys, never the output columns).
        props = None
        if all(i.kind == "col" for i in items):
            props = [i.arg for i in items]
            if post_sort:
                sel = {i.name for i in items}
                for f, _ in post_sort:
                    if f not in sel and f not in props:
                        props.append(f)
        q = Query(
            filter=cql, properties=props, sort_by=push_sort, auths=auths,
            limit=None if (distinct or post_sort or limit is None)
            else limit + offset,
        )
        r = ds.query(type_name, q)
        cols: dict[str, np.ndarray] = {}
        for it in items:
            if it.kind == "star":
                for a in r.table.sft.attributes:
                    c = r.table.columns[a.name]
                    cols[a.name] = (
                        c.geometries() if a.type.is_geometry else c.values
                    )
            elif it.kind == "col":
                c = r.table.columns[it.arg]
                cols[it.name] = c.geometries() if c.type.is_geometry else c.values
            else:
                cols[it.name] = _scalar_fn(it.fn, r.table, it.arg)
        if distinct:
            names = list(cols)
            nrows = len(next(iter(cols.values()))) if cols else 0
            _, groups = _group_first_occurrence(
                tuple(str(cols[c][i]) for c in names) for i in range(nrows)
            )
            idx = np.asarray([g[0] for g in groups], dtype=np.int64)
            cols = {c: v[idx] for c, v in cols.items()}
            # DISTINCT collapses rows: ordering by an unselected column is
            # ill-defined, so the select-list-only rule applies (SQL's own)
            return _apply_order_limit(
                SqlResult(cols), post_sort, limit, offset)
        if post_sort:
            # multi-key sort may reference UNSELECTED schema columns — the
            # keys come from the materialized table, the perm applies to
            # the projected output; successive stable sorts, least-
            # significant key first, give lexicographic order
            from geomesa_tpu.store.reduce import stable_order

            n_rows = len(next(iter(cols.values()))) if cols else 0
            perm = np.arange(n_rows)
            for f, desc in reversed(post_sort):
                if f in cols:
                    keys = np.asarray(cols[f])
                elif f in r.table.columns:
                    keys = np.asarray(r.table.columns[f].values)
                else:
                    raise SqlError(f"ORDER BY {f!r}: unknown column")
                perm = perm[stable_order(keys[perm], desc)]
            cols = {k: np.asarray(v)[perm] for k, v in cols.items()}
        return _apply_order_limit(SqlResult(cols), None, limit, offset)

    # aggregate path: scan (with pushdown filter), then vectorized fold
    for it in items:
        if it.kind in ("star", "fn"):
            raise SqlError("cannot mix aggregates with non-aggregated columns")
        if it.kind == "col" and (not group_by or it.arg not in group_by):
            raise SqlError(f"column {it.arg!r} must appear in GROUP BY")

    # SELECT COUNT(*) alone: the batched-EXACT device count (fused int scan
    # + edge-bucket residual, count_many(loose=False)) — no row
    # materialization; count_many itself degrades to the exact query path
    # for filters/stores the fused pass can't serve
    if (
        not group_by
        and not having
        and len(items) == 1
        and items[0].kind == "agg"
        and items[0].fn == "count"
        and items[0].arg == "*"
    ):
        counter = getattr(ds, "count_many", None)
        if counter is not None:
            n = counter(
                type_name, [Query(filter=cql, auths=auths)], loose=False)[0]
            return _apply_order_limit(
                SqlResult({items[0].name: np.array([n], dtype=object)}),
                None, limit, offset,
            )

    # distributed aggregation: the fused mesh segment-reduce serves pure
    # bbox+time-filtered GROUP BY / SUM / MIN / MAX / AVG / COUNT / HAVING
    # without materializing rows; anything it declines falls through to the
    # host fold below (which also owns all validation errors)
    mesh_res = _mesh_aggregate(
        ds, type_name, cql, items, group_by, having, order, limit, offset,
        auths=auths,
    )
    if mesh_res is not None:
        return mesh_res

    r = ds.query(type_name, Query(filter=cql, auths=auths))
    t = r.table

    if not group_by:
        cols = {
            it.name: np.array([_agg_value(it.fn, it.arg, t, np.arange(len(t)))], dtype=object)
            for it in items
        }
        # same ORDER BY/LIMIT tail as the grouped and mesh paths — the two
        # engines must be indistinguishable result-wise
        return _apply_order_limit(SqlResult(cols), order, limit, offset)

    keys = [t.columns[g].values.astype(object) for g in group_by]
    combo = np.array(list(zip(*keys)), dtype=object)
    group_keys, groups = _group_first_occurrence(
        tuple(combo[i]) for i in range(len(t))
    )
    if having:
        hit, hop, lit = _having_parts(having)
        if hit.arg != "*" and hit.arg not in t.columns:
            raise SqlError(f"unknown HAVING column {hit.arg!r}")
        kept = [
            (k, g) for k, g in zip(group_keys, groups)
            if _having_passes(
                hit, hop, lit,
                _agg_value(hit.fn, hit.arg, t, np.asarray(g, np.int64)),
            )
        ]
        group_keys = [k for k, _ in kept]
        groups = [g for _, g in kept]
    cols = {}
    for it in items:
        if it.kind == "col":
            gi = group_by.index(it.arg)
            cols[it.name] = np.array([k[gi] for k in group_keys], dtype=object)
        else:
            cols[it.name] = np.array(
                [
                    _agg_value(it.fn, it.arg, t, np.asarray(g, dtype=np.int64))
                    for g in groups
                ],
                dtype=object,
            )
    return _apply_order_limit(SqlResult(cols), order, limit, offset)
