"""SQL query layer with spatial predicate pushdown."""

from geomesa_tpu.sql.engine import SqlResult, sql

__all__ = ["sql", "SqlResult"]
