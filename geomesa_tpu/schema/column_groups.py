"""Column-family projection groups.

Role parity: ``geomesa-index-api/.../conf/ColumnGroups.scala`` (142 LoC —
SURVEY.md §2.3): a schema can declare named attribute subsets (stored as
reduced column-family copies in the reference); a query whose projection and
filter touch only a group's attributes scans the reduced copy. Here a group
is a reduced set of resident columns — the scan touches fewer HBM arrays —
declared in SFT user data:

    geomesa.column.groups = "track:name,dtg;viz:name"

The default geometry and date attributes are implicitly part of every group
(they key the indexes, as in the reference).
"""

from __future__ import annotations

import dataclasses

from geomesa_tpu.filter import ast
from geomesa_tpu.schema.sft import FeatureType

__all__ = ["ColumnGroups", "filter_attributes"]

_KEY = "geomesa.column.groups"


def filter_attributes(f: ast.Filter | None) -> set[str]:
    """Attribute names referenced anywhere in a filter AST."""
    out: set[str] = set()
    if f is None:
        return out
    stack = [f]
    while stack:
        node = stack.pop()
        for fld in dataclasses.fields(node) if dataclasses.is_dataclass(node) else ():
            v = getattr(node, fld.name)
            if fld.name == "prop" and isinstance(v, str):
                out.add(v)
            elif isinstance(v, ast.Filter):
                stack.append(v)
            elif isinstance(v, (list, tuple)):
                stack.extend(x for x in v if isinstance(x, ast.Filter))
    return out


class ColumnGroups:
    """Named attribute subsets for one schema."""

    def __init__(self, sft: FeatureType):
        self.sft = sft
        always = {n for n in (sft.geom_field, sft.dtg_field) if n}
        self.groups: dict[str, set[str]] = {}
        spec = sft.user_data.get(_KEY, "")
        for part in str(spec).split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, cols = part.partition(":")
            attrs = {c.strip() for c in cols.split(",") if c.strip()}
            unknown = attrs - {a.name for a in sft.attributes}
            if unknown:
                raise ValueError(f"column group {name!r} names unknown attributes {sorted(unknown)}")
            self.groups[name.strip()] = attrs | always
        # the implicit default group: everything
        self._all = {a.name for a in sft.attributes}

    def group_for(self, properties, f: ast.Filter | None) -> tuple[str, set[str]]:
        """Smallest group covering the query's projection + filter attributes;
        falls back to the full ('default') set. Without a projection the
        default group is read (reference behavior: reduced column families
        only serve transform queries)."""
        if properties is None:
            return "default", set(self._all)
        needed = set(properties) | filter_attributes(f)
        needed &= self._all  # 'id' and synthetic names don't bind columns
        best = None
        for name, attrs in self.groups.items():
            if needed <= attrs and (best is None or len(attrs) < len(self.groups[best])):
                best = name
        if best is None:
            return "default", set(self._all)
        return best, set(self.groups[best])

    def reduced_sft(self, group: str) -> FeatureType:
        """A schema containing only the group's attributes (original order) —
        the reference's reduced column-family copy, as a reduced SFT. Used by
        catalog loads that materialize just one group's columns."""
        if group == "default":
            return self.sft
        keep = self.groups[group]
        return FeatureType(
            name=self.sft.name,
            attributes=[a for a in self.sft.attributes if a.name in keep],
            default_geom=self.sft.geom_field if self.sft.geom_field in keep else None,
            user_data={k: v for k, v in self.sft.user_data.items() if k != _KEY},
        )

    def project(self, table, group: str):
        """Reduced-column view of a table for a named group."""
        if group == "default":
            return table
        keep = self.groups[group]
        from geomesa_tpu.schema.columnar import FeatureTable

        return FeatureTable(
            table.sft, table.fids, {k: c for k, c in table.columns.items() if k in keep}
        )
