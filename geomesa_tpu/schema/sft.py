"""Feature-type schema DSL: the ``name:Type:opt=...`` spec string.

Capability parity with the reference's ``SimpleFeatureTypes`` spec system
(``geomesa-utils/src/main/scala/org/locationtech/geomesa/utils/geotools/
SimpleFeatureTypes.scala`` — SURVEY.md §2.18, "the de-facto schema DSL"):

    "name:String:index=true,dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval='week'"

- ``*`` marks the default geometry attribute.
- per-attribute options after a second ``:`` (``index=true``, ``srid=4326``,
  ``cardinality=high``...).
- schema-level user data after ``;`` (``geomesa.z3.interval``,
  ``geomesa.xz.precision``, ``geomesa.z.splits``, ``geomesa.indices``...).

The schema drives index selection, key-space configuration and the columnar
layout (:mod:`geomesa_tpu.schema.columnar`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from geomesa_tpu.curve.binned_time import TimePeriod


class AttributeType(str, Enum):
    STRING = "String"
    INT = "Integer"
    LONG = "Long"
    FLOAT = "Float"
    DOUBLE = "Double"
    BOOLEAN = "Boolean"
    DATE = "Date"
    UUID = "UUID"
    BYTES = "Bytes"
    POINT = "Point"
    LINESTRING = "LineString"
    POLYGON = "Polygon"
    MULTIPOINT = "MultiPoint"
    MULTILINESTRING = "MultiLineString"
    MULTIPOLYGON = "MultiPolygon"
    GEOMETRY = "Geometry"

    @property
    def is_geometry(self) -> bool:
        return self in _GEOM_TYPES

    @property
    def is_numeric(self) -> bool:
        return self in (
            AttributeType.INT,
            AttributeType.LONG,
            AttributeType.FLOAT,
            AttributeType.DOUBLE,
        )


_GEOM_TYPES = {
    AttributeType.POINT,
    AttributeType.LINESTRING,
    AttributeType.POLYGON,
    AttributeType.MULTIPOINT,
    AttributeType.MULTILINESTRING,
    AttributeType.MULTIPOLYGON,
    AttributeType.GEOMETRY,
}

_TYPE_ALIASES = {t.value.lower(): t for t in AttributeType}
_TYPE_ALIASES.update({"int": AttributeType.INT, "str": AttributeType.STRING})


@dataclass(frozen=True)
class AttributeDescriptor:
    name: str
    type: AttributeType
    options: dict = field(default_factory=dict)

    @property
    def indexed(self) -> bool:
        v = str(self.options.get("index", "false")).lower()
        return v in ("true", "full", "join")


@dataclass
class FeatureType:
    """Schema: ordered attributes + index configuration user-data."""

    name: str
    attributes: list[AttributeDescriptor]
    default_geom: str | None = None
    user_data: dict = field(default_factory=dict)

    def __post_init__(self):
        names = [a.name for a in self.attributes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate attribute names in schema: {names}")
        if self.default_geom is None:
            for a in self.attributes:
                if a.type.is_geometry:
                    self.default_geom = a.name
                    break

    # -- lookups ------------------------------------------------------------
    def attr(self, name: str) -> AttributeDescriptor:
        for a in self.attributes:
            if a.name == name:
                return a
        raise KeyError(f"no such attribute: {name!r} in {self.name}")

    def index_of(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        raise KeyError(f"no such attribute: {name!r} in {self.name}")

    def __contains__(self, name: str) -> bool:
        return any(a.name == name for a in self.attributes)

    @property
    def geom_field(self) -> str | None:
        return self.default_geom

    @property
    def dtg_field(self) -> str | None:
        """Default date attribute: explicit user-data override, else first Date.

        An explicit EMPTY override pins 'no default date' — schema evolution
        uses it so appending a Date attribute can't retroactively become the
        dtg of a store that never had one."""
        explicit = self.user_data.get("geomesa.index.dtg")
        if explicit is not None:
            return explicit or None
        for a in self.attributes:
            if a.type == AttributeType.DATE:
                return a.name
        return None

    @property
    def geom_is_points(self) -> bool:
        return (
            self.default_geom is not None
            and self.attr(self.default_geom).type == AttributeType.POINT
        )

    # -- index configuration (reference: RichSimpleFeatureType) -------------
    @property
    def z3_interval(self) -> TimePeriod:
        return TimePeriod(self.user_data.get("geomesa.z3.interval", "week"))

    @property
    def xz_precision(self) -> int:
        return int(self.user_data.get("geomesa.xz.precision", 12))

    @property
    def index_layout(self) -> str:
        """Index-layout version (``geomesa.index.layout``): ``current``
        (default) or ``legacy`` — selects the curve generation, the
        reference's legacy key-space role
        (``geomesa-index-api/.../index/z3/legacy/``,
        ``AttributeIndexV7.scala``); persistence stamps it in the catalog
        manifest so a reload plans with the math the data was indexed
        under."""
        v = str(self.user_data.get("geomesa.index.layout", "current"))
        return "legacy" if v in ("legacy", "1") else "current"

    @property
    def shards(self) -> int:
        """Hash-shard count for hot-spot spreading (``geomesa.z.splits``)."""
        return int(self.user_data.get("geomesa.z.splits", 4))

    @property
    def configured_indices(self) -> list[str] | None:
        v = self.user_data.get("geomesa.indices")
        if not v:
            return None
        return [s.strip() for s in v.split(",") if s.strip()]

    # -- spec round-trip -----------------------------------------------------
    def to_spec(self) -> str:
        parts = []
        for a in self.attributes:
            star = "*" if a.name == self.default_geom and a.type.is_geometry else ""
            s = f"{star}{a.name}:{a.type.value}"
            if a.options:
                s += ":" + ":".join(f"{k}={v}" for k, v in a.options.items())
            parts.append(s)
        spec = ",".join(parts)
        if self.user_data:
            spec += ";" + ",".join(f"{k}='{v}'" for k, v in self.user_data.items())
        return spec


def parse_spec(name: str, spec: str) -> FeatureType:
    """Parse a ``SimpleFeatureTypes``-style spec string into a FeatureType."""
    spec = spec.strip()
    if ";" in spec:
        attr_part, ud_part = spec.split(";", 1)
    else:
        attr_part, ud_part = spec, ""

    attributes: list[AttributeDescriptor] = []
    default_geom = None
    for chunk in _split_top(attr_part, ","):
        chunk = chunk.strip()
        if not chunk:
            continue
        is_default = chunk.startswith("*")
        if is_default:
            chunk = chunk[1:]
        fields = chunk.split(":")
        if len(fields) < 2:
            raise ValueError(f"invalid attribute spec: {chunk!r}")
        aname, atype = fields[0].strip(), fields[1].strip()
        try:
            typ = _TYPE_ALIASES[atype.lower()]
        except KeyError:
            raise ValueError(f"unknown attribute type {atype!r} in {chunk!r}") from None
        options = {}
        for opt in fields[2:]:
            if "=" in opt:
                k, v = opt.split("=", 1)
                options[k.strip()] = v.strip()
        attributes.append(AttributeDescriptor(aname, typ, options))
        if is_default:
            if not typ.is_geometry:
                raise ValueError(f"default-geometry marker on non-geometry: {chunk!r}")
            default_geom = aname

    user_data = {}
    if ud_part:
        for kv in _split_top(ud_part, ","):
            if "=" in kv:
                k, v = kv.split("=", 1)
                user_data[k.strip()] = v.strip().strip("'\"")

    return FeatureType(name, attributes, default_geom, user_data)


def _split_top(s: str, sep: str) -> list[str]:
    """Split on ``sep`` outside of quotes."""
    out, cur, q = [], [], None
    for ch in s:
        if q:
            if ch == q:
                q = None
            cur.append(ch)
        elif ch in "'\"":
            q = ch
            cur.append(ch)
        elif ch == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out
