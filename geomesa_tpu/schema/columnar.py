"""Struct-of-arrays feature table: the host-side columnar store.

This wholesale replaces the reference's row-oriented feature serializers
(Kryo lazy features, ``geomesa-features`` — SURVEY.md §2.4): where the JVM
design fakes columnar access with per-attribute byte offsets
(``KryoBufferSimpleFeature``), we store features as real columns — numeric
arrays, epoch-millis dates, dictionary-encodable strings, and geometry columns
that always carry vectorized bbox arrays (plus x/y fast paths for points).
Device-side stores (:mod:`geomesa_tpu.store.tpu_backend`) are typed views of
these columns; Arrow IPC interchange is a zero-copy re-labeling
(:mod:`geomesa_tpu.io.arrow`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable

import numpy as np

from geomesa_tpu.geometry.types import Point
from geomesa_tpu.schema.sft import AttributeType, FeatureType

_NUMERIC_DTYPES = {
    AttributeType.INT: np.int32,
    AttributeType.LONG: np.int64,
    AttributeType.FLOAT: np.float32,
    AttributeType.DOUBLE: np.float64,
    AttributeType.BOOLEAN: np.bool_,
}


@dataclass
class Column:
    """One attribute's storage; ``valid`` is None when all values are set."""

    type: AttributeType
    values: np.ndarray  # typed array; object array for strings/geoms
    valid: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self.values)

    def take(self, idx: np.ndarray) -> "Column":
        return Column(
            self.type,
            self.values[idx],
            None if self.valid is None else self.valid[idx],
        )

    def is_valid(self) -> np.ndarray:
        if self.valid is None:
            return np.ones(len(self), dtype=bool)  # len() works for lazy geometry columns too
        return self.valid

    def dictionary(self):
        """Cached (sorted vocab, codes int32) for string columns — the
        ``ArrowDictionary`` role. Predicates evaluate against the (small)
        vocab once and compare int codes per row instead of strings
        (``ArrowFilterOptimizer.scala:1`` pushdown); None for non-strings.
        """
        if self.type not in (AttributeType.STRING, AttributeType.UUID):
            return None
        cached = self.__dict__.get("_dict")
        if cached is not None:
            return cached
        if self.valid is None and all(
            type(v) is str for v in self.values
        ):
            # genuinely all-str: vectorized C-level cast. The type sweep is
            # ~10x cheaper than the guarded listcomp+astype below, and it
            # guards semantics: a stray non-str value must keep mapping to
            # "" (str(v) here would change filter/grouping results)
            flat = np.asarray(self.values, dtype=str)
        else:
            flat = np.array(
                [v if isinstance(v, str) else "" for v in self.values],
                dtype=object,
            ).astype(str)
        vocab, codes = np.unique(flat, return_inverse=True)
        out = (vocab, codes.astype(np.int32))
        self.__dict__["_dict"] = out
        return out


@dataclass
class GeometryColumn(Column):
    """Geometry storage: object array of Geometry + always-on bbox SoA.

    For Point columns ``x``/``y`` are the primary storage (values may be
    lazily materialized); bboxes degenerate to the points themselves.
    """

    x: np.ndarray | None = None  # f64, points only
    y: np.ndarray | None = None
    bounds: np.ndarray | None = None  # (N, 4) f64: xmin, ymin, xmax, ymax

    def take(self, idx: np.ndarray) -> "GeometryColumn":
        return GeometryColumn(
            self.type,
            self.values[idx] if self.values is not None else None,
            None if self.valid is None else self.valid[idx],
            x=None if self.x is None else self.x[idx],
            y=None if self.y is None else self.y[idx],
            bounds=None if self.bounds is None else self.bounds[idx],
        )

    def __len__(self) -> int:
        if self.values is not None:
            return len(self.values)
        return len(self.x)

    def geometries(self) -> np.ndarray:
        """Materialize the object array (lazily for point columns)."""
        if self.values is None:
            vals = np.empty(len(self.x), dtype=object)
            for i in range(len(self.x)):
                vals[i] = Point(float(self.x[i]), float(self.y[i]))
            self.values = vals
        return self.values


def _geometry_column(typ: AttributeType, geoms: Iterable[Any]) -> GeometryColumn:
    geoms = list(geoms)
    if any(isinstance(g, str) for g in geoms):
        # WKT strings accepted anywhere a geometry is (GeoTools convention)
        from geomesa_tpu.geometry.wkt import from_wkt

        geoms = [from_wkt(g) if isinstance(g, str) else g for g in geoms]
    n = len(geoms)
    if typ == AttributeType.POINT:
        x = np.empty(n, dtype=np.float64)
        y = np.empty(n, dtype=np.float64)
        valid = np.ones(n, dtype=bool)
        vals = np.empty(n, dtype=object)
        for i, g in enumerate(geoms):
            if g is None:
                valid[i] = False
                x[i] = np.nan
                y[i] = np.nan
            else:
                vals[i] = g
                x[i] = g.x
                y[i] = g.y
        bounds = np.stack([x, y, x, y], axis=1)
        return GeometryColumn(
            typ, vals, None if valid.all() else valid, x=x, y=y, bounds=bounds
        )
    vals = np.empty(n, dtype=object)
    bounds = np.full((n, 4), np.nan, dtype=np.float64)
    valid = np.ones(n, dtype=bool)
    for i, g in enumerate(geoms):
        vals[i] = g
        if g is None:
            valid[i] = False
        else:
            bounds[i] = g.bbox
    return GeometryColumn(typ, vals, None if valid.all() else valid, bounds=bounds)


def point_column(x: np.ndarray, y: np.ndarray, valid=None) -> GeometryColumn:
    """Fast-path Point column straight from coordinate arrays (bulk ingest)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    bounds = np.stack([x, y, x, y], axis=1)
    return GeometryColumn(AttributeType.POINT, None, valid, x=x, y=y, bounds=bounds)


def null_column(typ: AttributeType, n: int) -> Column:
    """An all-null column of length ``n`` (schema-evolution backfill)."""
    valid = np.zeros(n, dtype=bool)
    if typ in _NUMERIC_DTYPES:
        return Column(typ, np.zeros(n, dtype=_NUMERIC_DTYPES[typ]), valid)
    if typ == AttributeType.DATE:
        return Column(typ, np.zeros(n, dtype=np.int64), valid)
    return Column(typ, np.empty(n, dtype=object), valid)


def _scalar_column(typ: AttributeType, values: Iterable[Any]) -> Column:
    values = list(values)
    n = len(values)
    if typ in _NUMERIC_DTYPES:
        dtype = _NUMERIC_DTYPES[typ]
        arr = np.zeros(n, dtype=dtype)
        valid = np.ones(n, dtype=bool)
        for i, v in enumerate(values):
            if v is None:
                valid[i] = False
            else:
                arr[i] = v
        return Column(typ, arr, None if valid.all() else valid)
    if typ == AttributeType.DATE:
        arr = np.zeros(n, dtype=np.int64)
        valid = np.ones(n, dtype=bool)
        for i, v in enumerate(values):
            if v is None:
                valid[i] = False
            else:
                arr[i] = _to_millis(v)
        return Column(typ, arr, None if valid.all() else valid)
    # strings / uuid / bytes: object array
    arr = np.empty(n, dtype=object)
    valid = np.ones(n, dtype=bool)
    for i, v in enumerate(values):
        arr[i] = v
        if v is None:
            valid[i] = False
    return Column(typ, arr, None if valid.all() else valid)


def _to_millis(v) -> int:
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, np.datetime64):
        return int(v.astype("datetime64[ms]").astype(np.int64))
    if isinstance(v, str):
        return int(
            np.datetime64(v.rstrip("Z"), "ms").astype(np.int64)
        )
    import datetime

    if isinstance(v, datetime.datetime):
        if v.tzinfo is None:
            v = v.replace(tzinfo=datetime.timezone.utc)
        return int(v.timestamp() * 1000)
    raise TypeError(f"cannot convert to epoch millis: {v!r}")


@dataclass
class FeatureTable:
    """An ordered batch of features as columns; the unit of ingest/scan/result."""

    sft: FeatureType
    fids: np.ndarray  # object array of str
    columns: dict[str, Column]

    def __post_init__(self):
        n = len(self.fids)
        for name, col in self.columns.items():
            if len(col) != n:
                raise ValueError(
                    f"column {name!r} length {len(col)} != feature count {n}"
                )

    def __len__(self) -> int:
        return len(self.fids)

    @property
    def n(self) -> int:
        return len(self.fids)

    # -- constructors --------------------------------------------------------
    @staticmethod
    def from_records(
        sft: FeatureType, records: list[dict], fids: list[str] | None = None
    ) -> "FeatureTable":
        cols: dict[str, Column] = {}
        for a in sft.attributes:
            vals = [r.get(a.name) for r in records]
            if a.type.is_geometry:
                cols[a.name] = _geometry_column(a.type, vals)
            else:
                cols[a.name] = _scalar_column(a.type, vals)
        if fids is None:
            fids = [str(i) for i in range(len(records))]
        return FeatureTable(sft, np.asarray(fids, dtype=object), cols)

    @staticmethod
    def from_columns(
        sft: FeatureType, fids, columns: dict[str, Column]
    ) -> "FeatureTable":
        return FeatureTable(sft, np.asarray(fids, dtype=object), columns)

    # -- row access ----------------------------------------------------------
    def record(self, i: int) -> dict:
        out = {}
        for name, col in self.columns.items():
            if col.valid is not None and not col.valid[i]:
                out[name] = None
            elif isinstance(col, GeometryColumn):
                out[name] = col.geometries()[i]
            else:
                v = col.values[i]
                out[name] = v.item() if isinstance(v, np.generic) else v
        return out

    def take(self, idx) -> "FeatureTable":
        idx = np.asarray(idx)
        return FeatureTable(
            self.sft,
            self.fids[idx],
            {k: c.take(idx) for k, c in self.columns.items()},
        )

    # -- geometry / time accessors (the scan hot path) -----------------------
    def geom_column(self) -> GeometryColumn:
        if self.sft.geom_field is None:
            raise ValueError(f"schema {self.sft.name} has no geometry")
        return self.columns[self.sft.geom_field]  # type: ignore[return-value]

    def dtg_millis(self) -> np.ndarray:
        if self.sft.dtg_field is None:
            raise ValueError(f"schema {self.sft.name} has no date attribute")
        return self.columns[self.sft.dtg_field].values

    @staticmethod
    def concat(tables: list["FeatureTable"]) -> "FeatureTable":
        if not tables:
            raise ValueError("nothing to concat")
        sft = tables[0].sft
        fids = np.concatenate([t.fids for t in tables])
        cols: dict[str, Column] = {}
        for name in tables[0].columns:
            parts = [t.columns[name] for t in tables]
            if isinstance(parts[0], GeometryColumn):
                # mixed lazy (values=None) and materialized parts: keep lazy
                # only when ALL parts are lazy, else materialize everything
                if any(p.values is None for p in parts):
                    if all(p.values is None for p in parts):
                        vals = None
                    else:
                        vals = np.concatenate([p.geometries() for p in parts])
                else:
                    vals = np.concatenate([p.values for p in parts])
            else:
                vals = np.concatenate([p.values for p in parts])
            if any(p.valid is not None for p in parts):
                valid = np.concatenate([p.is_valid() for p in parts])
            else:
                valid = None
            if isinstance(parts[0], GeometryColumn):
                cols[name] = GeometryColumn(
                    parts[0].type,
                    vals,
                    valid,
                    x=_cat([p.x for p in parts]),
                    y=_cat([p.y for p in parts]),
                    bounds=_cat([p.bounds for p in parts]),
                )
            else:
                cols[name] = Column(parts[0].type, vals, valid)
        return FeatureTable(sft, fids, cols)


def _cat(arrs):
    if any(a is None for a in arrs):
        return None
    return np.concatenate(arrs)


def representative_xy(table: FeatureTable) -> tuple[np.ndarray, np.ndarray]:
    """Representative point coords for each feature: true point coords, or
    bbox centroids for extended geometries (shared by density/BIN aggregates
    and the track-oriented processes)."""
    col = table.geom_column()
    if col.x is not None:
        return col.x, col.y
    b = col.bounds
    return (b[:, 0] + b[:, 2]) * 0.5, (b[:, 1] + b[:, 3]) * 0.5
