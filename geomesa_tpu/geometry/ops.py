"""Geometry measures, constructive ops, and DE-9IM topology.

Role parity: the JTS operations backing the reference's ST_* Spark UDF library
(``geomesa-spark-jts/.../udf/GeometricAccessorFunctions.scala``,
``GeometricProcessingFunctions.scala``, ``SpatialRelationFunctions.scala``,
SURVEY.md §2.14) and geometry utils (``geomesa-utils/.../GeometryUtils.scala``).
Everything here is from-scratch planar computational geometry over numpy
arrays; :func:`relate` computes the DE-9IM intersection matrix by splitting
each geometry's skeleton at crossings with the other and classifying the
resulting pieces/points against interior/boundary/exterior.
"""

from __future__ import annotations

import math

import numpy as np

from geomesa_tpu.geometry.predicates import (
    BOUNDARY,
    EXTERIOR,
    INTERIOR,
    _points_dist2_segments,
    classify_points_polygon,
    distance,
    intersects,
)
from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    _Multi,
)

__all__ = [
    "area",
    "length",
    "length_sphere",
    "distance_sphere",
    "centroid",
    "convex_hull",
    "envelope",
    "boundary",
    "closest_point",
    "translate",
    "buffer_point",
    "buffer_geometry",
    "antimeridian_safe",
    "is_closed",
    "is_ring",
    "is_simple",
    "is_valid",
    "is_empty",
    "dimension",
    "num_points",
    "num_geometries",
    "geometry_n",
    "point_n",
    "exterior_ring",
    "interior_ring_n",
    "relate",
    "relate_bool",
    "equals",
    "touches",
    "crosses",
    "overlaps",
    "covers",
    "covered_by",
]

EARTH_RADIUS_M = 6371008.7714  # WGS84 mean radius


# ---------------------------------------------------------------------------
# measures
# ---------------------------------------------------------------------------

def _ring_signed_area(c: np.ndarray) -> float:
    x, y = c[:, 0], c[:, 1]
    return 0.5 * float(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1]))


def area(g: Geometry) -> float:
    """Planar area (squared degrees); holes subtracted; 0 for points/lines."""
    if isinstance(g, Polygon):
        a = abs(_ring_signed_area(g.shell))
        for h in g.holes:
            a -= abs(_ring_signed_area(h))
        return a
    if isinstance(g, _Multi):
        return sum(area(p) for p in g.parts)
    return 0.0


def _polyline_length(c: np.ndarray) -> float:
    d = np.diff(c, axis=0)
    return float(np.sqrt((d * d).sum(axis=1)).sum())


def length(g: Geometry) -> float:
    """Planar length: path length for lines, perimeter for polygons (JTS)."""
    if isinstance(g, LineString):
        return _polyline_length(g.coords)
    if isinstance(g, Polygon):
        return sum(_polyline_length(r) for r in g.rings)
    if isinstance(g, _Multi):
        return sum(length(p) for p in g.parts)
    return 0.0


def _haversine_m(lon1, lat1, lon2, lat2):
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, dtype=np.float64)) for v in (lon1, lat1, lon2, lat2))
    dlon, dlat = lon2 - lon1, lat2 - lat1
    h = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2.0 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(h, 0.0, 1.0)))


def distance_sphere(a: Geometry, b: Geometry) -> float:
    """Great-circle distance in meters between representative nearest points.

    Exact for point×point (``st_distanceSphere``); for extended geometries the
    planar nearest points are projected onto the sphere.
    """
    pa, pb = closest_point(a, b), closest_point(b, a)
    return float(_haversine_m(pa.x, pa.y, pb.x, pb.y))


def length_sphere(g: Geometry) -> float:
    """Great-circle path length in meters (``st_lengthSphere``)."""
    if isinstance(g, LineString):
        c = g.coords
        return float(_haversine_m(c[:-1, 0], c[:-1, 1], c[1:, 0], c[1:, 1]).sum())
    if isinstance(g, Polygon):
        return sum(
            float(_haversine_m(r[:-1, 0], r[:-1, 1], r[1:, 0], r[1:, 1]).sum())
            for r in g.rings
        )
    if isinstance(g, _Multi):
        return sum(length_sphere(p) for p in g.parts)
    return 0.0


def centroid(g: Geometry) -> Point:
    """Area/length/count-weighted centroid per highest dimension present."""
    if isinstance(g, Point):
        return g
    if isinstance(g, Polygon):
        cx = cy = asum = 0.0
        for ring, sign in [(g.shell, 1.0), *[(h, -1.0) for h in g.holes]]:
            x, y = ring[:, 0], ring[:, 1]
            cr = x[:-1] * y[1:] - x[1:] * y[:-1]
            a = 0.5 * float(cr.sum())
            if a == 0.0:
                continue
            cx += sign * abs(a) * (float(((x[:-1] + x[1:]) * cr).sum()) / (6.0 * a))
            cy += sign * abs(a) * (float(((y[:-1] + y[1:]) * cr).sum()) / (6.0 * a))
            asum += sign * abs(a)
        if asum == 0.0:
            return centroid(LineString(g.shell))
        return Point(cx / asum, cy / asum)
    if isinstance(g, LineString):
        d = np.diff(g.coords, axis=0)
        w = np.sqrt((d * d).sum(axis=1))
        if w.sum() == 0.0:
            return Point(float(g.coords[:, 0].mean()), float(g.coords[:, 1].mean()))
        mids = 0.5 * (g.coords[:-1] + g.coords[1:])
        return Point(
            float((mids[:, 0] * w).sum() / w.sum()),
            float((mids[:, 1] * w).sum() / w.sum()),
        )
    if isinstance(g, _Multi):
        dim = dimension(g)
        weights, cents = [], []
        for p in g.parts:
            if dimension(p) != dim:
                continue
            c = centroid(p)
            w = {2: area(p), 1: length(p), 0: 1.0}[dim]
            weights.append(w)
            cents.append((c.x, c.y))
        w = np.asarray(weights)
        c = np.asarray(cents)
        if w.sum() == 0.0:
            return Point(float(c[:, 0].mean()), float(c[:, 1].mean()))
        return Point(float((c[:, 0] * w).sum() / w.sum()), float((c[:, 1] * w).sum() / w.sum()))
    raise TypeError(type(g).__name__)


# ---------------------------------------------------------------------------
# constructive ops
# ---------------------------------------------------------------------------

def _all_vertices(g: Geometry) -> np.ndarray:
    if isinstance(g, Point):
        return np.array([[g.x, g.y]], dtype=np.float64)
    if isinstance(g, LineString):
        return g.coords
    if isinstance(g, Polygon):
        return np.vstack(g.rings)
    if isinstance(g, _Multi):
        return np.vstack([_all_vertices(p) for p in g.parts])
    raise TypeError(type(g).__name__)


def convex_hull(g: Geometry) -> Geometry:
    """Andrew monotone-chain convex hull (``st_convexhull``)."""
    pts = np.unique(_all_vertices(g), axis=0)
    if len(pts) == 1:
        return Point(float(pts[0, 0]), float(pts[0, 1]))
    # sort by (x, y)
    order = np.lexsort((pts[:, 1], pts[:, 0]))
    pts = pts[order]

    def half(points):
        out: list[np.ndarray] = []
        for p in points:
            while len(out) >= 2:
                u, v = out[-1] - out[-2], p - out[-2]
                if u[0] * v[1] - u[1] * v[0] <= 0:
                    out.pop()
                else:
                    break
            out.append(p)
        return out

    lower = half(pts)
    upper = half(pts[::-1])
    hull = np.array(lower[:-1] + upper[:-1])
    if len(hull) == 2:
        return LineString(hull)
    return Polygon(hull)


def envelope(g: Geometry) -> Geometry:
    xmin, ymin, xmax, ymax = g.bbox
    if xmin == xmax and ymin == ymax:
        return Point(xmin, ymin)
    if xmin == xmax or ymin == ymax:
        return LineString(np.array([[xmin, ymin], [xmax, ymax]]))
    from geomesa_tpu.geometry.types import box

    return box(xmin, ymin, xmax, ymax)


def boundary(g: Geometry) -> Geometry | None:
    """Topological boundary; ``None`` for points (empty set)."""
    if isinstance(g, Point) or isinstance(g, MultiPoint):
        return None
    if isinstance(g, LineString):
        if is_closed(g):
            return None
        c = g.coords
        return MultiPoint((Point(*c[0]), Point(*c[-1])))
    if isinstance(g, Polygon):
        rings = [LineString(r) for r in g.rings]
        return rings[0] if len(rings) == 1 else MultiLineString(tuple(rings))
    if isinstance(g, _Multi):
        parts = [boundary(p) for p in g.parts]
        flat: list[Geometry] = []
        for b in parts:
            if b is None:
                continue
            flat.extend(b.parts if isinstance(b, _Multi) else [b])
        if not flat:
            return None
        if all(isinstance(p, Point) for p in flat):
            return MultiPoint(tuple(flat))
        return MultiLineString(tuple(p for p in flat if isinstance(p, LineString)))
    raise TypeError(type(g).__name__)


def closest_point(a: Geometry, b: Geometry) -> Point:
    """The point ON ``a`` closest to ``b`` (``st_closestPoint``)."""
    vb = _all_vertices(b)
    if isinstance(a, Point):
        return a
    if intersects(a, b):
        # any intersection witness is a valid (distance-0) closest point
        cb = _classify_region(vb[:, 0], vb[:, 1], a)
        hit = np.nonzero(cb != EXTERIOR)[0]
        if len(hit):
            return Point(float(vb[hit[0], 0]), float(vb[hit[0], 1]))
        va = _all_vertices(a)
        ca = _classify_region(va[:, 0], va[:, 1], b)
        hit = np.nonzero(ca != EXTERIOR)[0]
        if len(hit):
            return Point(float(va[hit[0], 0]), float(va[hit[0], 1]))
        for la in _skeleton_lines(a):
            for lb in _skeleton_lines(b):
                _, pts, _ = _pairwise_splits(la, lb)
                if pts:
                    return Point(float(pts[0][0]), float(pts[0][1]))
    # candidate: for every vertex of b, its projection onto a's segments;
    # plus a's vertices scored against b
    best, best_d2 = None, np.inf
    for seg_src in _skeleton_lines(a):
        x1, y1 = seg_src[:-1, 0][None, :], seg_src[:-1, 1][None, :]
        x2, y2 = seg_src[1:, 0][None, :], seg_src[1:, 1][None, :]
        px, py = vb[:, 0][:, None], vb[:, 1][:, None]
        dx, dy = x2 - x1, y2 - y1
        len2 = dx * dx + dy * dy
        with np.errstate(divide="ignore", invalid="ignore"):
            t = np.where(len2 > 0, ((px - x1) * dx + (py - y1) * dy) / len2, 0.0)
        t = np.clip(t, 0.0, 1.0)
        cx, cy = x1 + t * dx, y1 + t * dy
        d2 = (px - cx) ** 2 + (py - cy) ** 2
        i = int(np.argmin(d2))
        if d2.flat[i] < best_d2:
            best_d2 = float(d2.flat[i])
            best = Point(float(cx.flat[i]), float(cy.flat[i]))
    va = _all_vertices(a)
    from geomesa_tpu.geometry.predicates import points_dist2_geom

    d2v = points_dist2_geom(va[:, 0], va[:, 1], b)
    i = int(np.argmin(d2v))
    if best is None or d2v[i] < best_d2:
        best = Point(float(va[i, 0]), float(va[i, 1]))
    return best


def translate(g: Geometry, dx: float, dy: float) -> Geometry:
    if isinstance(g, Point):
        return Point(g.x + dx, g.y + dy)
    if isinstance(g, LineString):
        return LineString(g.coords + [dx, dy])
    if isinstance(g, Polygon):
        return Polygon(g.shell + [dx, dy], tuple(h + [dx, dy] for h in g.holes))
    if isinstance(g, _Multi):
        return type(g)(tuple(translate(p, dx, dy) for p in g.parts))
    raise TypeError(type(g).__name__)


def buffer_point(p: Point, meters: float, segments: int = 32) -> Polygon:
    """Geodesic point buffer as a polygon in degrees (``st_bufferPoint``).

    Matches the reference's use for DWithin acceleration: a small circle around
    a lon/lat point, radius in meters, local-scale approximation.
    """
    dlat = math.degrees(meters / EARTH_RADIUS_M)
    coslat = max(math.cos(math.radians(p.y)), 1e-12)
    dlon = dlat / coslat
    ang = np.linspace(0.0, 2.0 * math.pi, segments, endpoint=False)
    ring = np.stack([p.x + dlon * np.cos(ang), p.y + dlat * np.sin(ang)], axis=1)
    return Polygon(ring)


def _capsule(p0, p1, r: float, segs: int) -> Polygon:
    """Stadium (flat rectangle + semicircular caps) around segment p0→p1."""
    dx, dy = p1[0] - p0[0], p1[1] - p0[1]
    length = math.hypot(dx, dy)
    if length < 1e-300:
        ang = np.linspace(0.0, 2.0 * math.pi, 2 * segs, endpoint=False)
        return Polygon(np.stack(
            [p0[0] + r * np.cos(ang), p0[1] + r * np.sin(ang)], axis=1
        ))
    ux, uy = dx / length, dy / length
    base = math.atan2(uy, ux)
    # cap at p1 sweeps from base-90° to base+90°, cap at p0 the other half
    a1 = base - math.pi / 2.0 + np.linspace(0.0, math.pi, segs + 1)
    a0 = base + math.pi / 2.0 + np.linspace(0.0, math.pi, segs + 1)
    ring = np.concatenate([
        np.stack([p1[0] + r * np.cos(a1), p1[1] + r * np.sin(a1)], axis=1),
        np.stack([p0[0] + r * np.cos(a0), p0[1] + r * np.sin(a0)], axis=1),
    ])
    return Polygon(ring)


def _ring_capsules(coords: np.ndarray, r: float, segs: int) -> list[Polygon]:
    return [
        _capsule(coords[i], coords[i + 1], r, segs)
        for i in range(len(coords) - 1)
    ]


def buffer_geometry(g: Geometry, distance: float,
                    quad_segs: int = 16) -> Geometry:
    """Generic positive buffer (the JTS ``ST_Buffer`` role, planar, radius
    in coordinate units — degrees on the lon/lat datum).

    The result is the UNION-SEMANTICS cover of ``{p : dist(p, g) <=
    distance}``: a MultiPolygon whose parts may overlap (per-segment
    stadium capsules plus, for areal inputs, the original polygon).
    Containment/intersection predicates over a MultiPolygon already test
    "any part", so consumers — DWithin-style selects, ST_Within against a
    buffered zone — see exact union semantics without polygon boolean ops;
    the reference gets the same result from JTS's buffer
    (``geomesa-spark-jts/.../DataFrameFunctions.scala`` ``st_buffer``).
    Negative distances are not supported (raise)."""
    if distance < 0:
        raise ValueError("negative buffer distances are not supported")
    if isinstance(g, Point):
        if distance == 0:
            return g
        ang = np.linspace(0.0, 2.0 * math.pi, 4 * quad_segs, endpoint=False)
        return Polygon(np.stack(
            [g.x + distance * np.cos(ang), g.y + distance * np.sin(ang)],
            axis=1,
        ))
    if distance == 0:
        return g
    segs = max(4, quad_segs)
    if isinstance(g, LineString):
        return MultiPolygon(tuple(_ring_capsules(g.coords, distance, segs)))
    if isinstance(g, Polygon):
        parts: list[Polygon] = [g]
        parts += _ring_capsules(g.shell, distance, segs)
        for h in g.holes:
            parts += _ring_capsules(h, distance, segs)
        return MultiPolygon(tuple(parts))
    if isinstance(g, _Multi):
        parts = []
        for p in g.parts:
            b = buffer_geometry(p, distance, quad_segs)
            parts.extend(b.parts if isinstance(b, MultiPolygon) else [b])
        return MultiPolygon(tuple(parts))
    raise TypeError(type(g).__name__)


def antimeridian_safe(g: Geometry) -> Geometry:
    """Split geometries whose bbox spans the antimeridian (``st_idlSafeGeom``).

    Heuristic matching the reference's ``st_antimeridianSafeGeom``: if the
    geometry's longitudinal extent exceeds 180°, shift the negative-lon part by
    +360, split at lon=180, and shift the right half back.
    """
    xmin, _, xmax, _ = g.bbox
    if xmax - xmin <= 180.0:
        return g
    if not isinstance(g, Polygon):
        return g  # only polygons are split (the reference's supported case)
    shifted = Polygon(
        np.where(g.shell[:, :1] < 0, g.shell + [360.0, 0.0], g.shell),
        tuple(np.where(h[:, :1] < 0, h + [360.0, 0.0], h) for h in g.holes),
    )
    west = _clip_halfplane(shifted, 180.0, keep_left=True)
    east = _clip_halfplane(shifted, 180.0, keep_left=False)
    parts = []
    if west is not None:
        parts.append(west)
    if east is not None:
        parts.append(translate(east, -360.0, 0.0))
    if len(parts) == 1:
        return parts[0]
    return MultiPolygon(tuple(parts))


def _clip_halfplane(poly: Polygon, xcut: float, keep_left: bool) -> Polygon | None:
    """Sutherland–Hodgman clip (shell and holes) against a vertical line."""

    def inside(pt):
        return pt[0] <= xcut if keep_left else pt[0] >= xcut

    def isect(p1, p2):
        t = (xcut - p1[0]) / (p2[0] - p1[0])
        return np.array([xcut, p1[1] + t * (p2[1] - p1[1])])

    def clip_ring(ring: np.ndarray) -> np.ndarray | None:
        out: list[np.ndarray] = []
        for i in range(len(ring) - 1):
            p1, p2 = ring[i], ring[i + 1]
            if inside(p1):
                out.append(p1)
                if not inside(p2):
                    out.append(isect(p1, p2))
            elif inside(p2):
                out.append(isect(p1, p2))
        return np.array(out) if len(out) >= 3 else None

    shell = clip_ring(poly.shell)
    if shell is None:
        return None
    holes = tuple(h for h in map(clip_ring, poly.holes) if h is not None)
    return Polygon(shell, holes)


# ---------------------------------------------------------------------------
# simple accessors / validity
# ---------------------------------------------------------------------------

def is_empty(g: Geometry | None) -> bool:
    return g is None or (isinstance(g, _Multi) and len(g.parts) == 0)


def dimension(g: Geometry) -> int:
    if isinstance(g, Point) or isinstance(g, MultiPoint):
        return 0
    if isinstance(g, (LineString, MultiLineString)):
        return 1
    if isinstance(g, (Polygon, MultiPolygon)):
        return 2
    if isinstance(g, _Multi):
        return max((dimension(p) for p in g.parts), default=0)
    raise TypeError(type(g).__name__)


def num_points(g: Geometry) -> int:
    if isinstance(g, Point):
        return 1
    if isinstance(g, LineString):
        return len(g.coords)
    if isinstance(g, Polygon):
        return sum(len(r) for r in g.rings)
    if isinstance(g, _Multi):
        return sum(num_points(p) for p in g.parts)
    raise TypeError(type(g).__name__)


def num_geometries(g: Geometry) -> int:
    return len(g.parts) if isinstance(g, _Multi) else 1


def geometry_n(g: Geometry, n: int) -> Geometry:
    """1-based part accessor (OGC convention, ``st_geometryN``)."""
    if isinstance(g, _Multi):
        return g.parts[n - 1]
    if n == 1:
        return g
    raise IndexError(n)


def point_n(g: LineString, n: int) -> Point:
    """1-based vertex accessor; negative counts from the end (``st_pointN``)."""
    c = g.coords
    idx = n - 1 if n > 0 else len(c) + n
    return Point(float(c[idx, 0]), float(c[idx, 1]))


def exterior_ring(g: Polygon) -> LineString:
    return LineString(g.shell)


def interior_ring_n(g: Polygon, n: int) -> LineString:
    return LineString(g.holes[n - 1])


def is_closed(g: Geometry) -> bool:
    if isinstance(g, LineString):
        return bool(np.array_equal(g.coords[0], g.coords[-1]))
    if isinstance(g, (MultiLineString,)):
        return all(is_closed(p) for p in g.parts)
    return True  # points/polygons are closed by definition (JTS)


def is_ring(g: Geometry) -> bool:
    return isinstance(g, LineString) and is_closed(g) and is_simple(g)


def _cross2(u, v) -> float:
    return float(u[0] * v[1] - u[1] * v[0])


def _polyline_self_intersects(c: np.ndarray, closed: bool) -> bool:
    n = len(c) - 1
    for i in range(n):
        for j in range(i + 1, n):
            adjacent = j == i + 1 or (closed and i == 0 and j == n - 1)
            a1, a2, b1, b2 = c[i], c[i + 1], c[j], c[j + 1]
            d = _cross2(a2 - a1, b2 - b1)
            if d != 0:
                t = _cross2(b1 - a1, b2 - b1) / d
                u = _cross2(b1 - a1, a2 - a1) / d
                if 0 <= t <= 1 and 0 <= u <= 1:
                    if not adjacent:
                        return True
                    # adjacent segments legitimately share one endpoint
                    pt = a1 + t * (a2 - a1)
                    shared = c[j] if j == i + 1 else c[0]
                    if not np.allclose(pt, shared):
                        return True
            else:
                # parallel: collinear overlap?
                if _cross2(b1 - a1, a2 - a1) == 0:
                    axis = 0 if a1[0] != a2[0] else 1
                    lo1, hi1 = sorted((a1[axis], a2[axis]))
                    lo2, hi2 = sorted((b1[axis], b2[axis]))
                    if min(hi1, hi2) - max(lo1, lo2) > 0:
                        return True
    return False


def is_simple(g: Geometry) -> bool:
    if isinstance(g, (Point, MultiPoint, Polygon, MultiPolygon)):
        return True
    if isinstance(g, LineString):
        return not _polyline_self_intersects(g.coords, is_closed(g))
    if isinstance(g, MultiLineString):
        return all(is_simple(p) for p in g.parts)
    raise TypeError(type(g).__name__)


def is_valid(g: Geometry) -> bool:
    """Basic OGC validity: simple rings, holes inside shell."""
    if isinstance(g, Polygon):
        for r in g.rings:
            if _polyline_self_intersects(r, closed=True):
                return False
        for h in g.holes:
            cls = classify_points_polygon(h[:-1, 0], h[:-1, 1], Polygon(g.shell))
            if (cls == EXTERIOR).any():
                return False
        return True
    if isinstance(g, _Multi):
        return all(is_valid(p) for p in g.parts)
    if isinstance(g, LineString):
        return len(g.coords) >= 2
    return True


# ---------------------------------------------------------------------------
# DE-9IM relate
# ---------------------------------------------------------------------------

_F = -1  # dim of an empty intersection


def _skeleton_lines(g: Geometry) -> list[np.ndarray]:
    if isinstance(g, LineString):
        return [g.coords]
    if isinstance(g, Polygon):
        return list(g.rings)
    if isinstance(g, _Multi):
        out = []
        for p in g.parts:
            out.extend(_skeleton_lines(p))
        return out
    return []


def _boundary_points(g: Geometry) -> np.ndarray:
    """Endpoints of line parts (mod-2 rule approximated as raw endpoints)."""
    pts = []
    if isinstance(g, LineString):
        if not is_closed(g):
            pts = [g.coords[0], g.coords[-1]]
    elif isinstance(g, MultiLineString):
        for p in g.parts:
            if not is_closed(p):
                pts.extend([p.coords[0], p.coords[-1]])
    return np.array(pts).reshape(-1, 2)


def _pairwise_splits(A: np.ndarray, B: np.ndarray):
    """Intersections of polyline A with polyline B.

    Returns ``(t_by_seg, points, overlap)`` where ``t_by_seg[i]`` is a list of
    split parameters on A's segment ``i``, ``points`` the isolated intersection
    coordinates, and ``overlap`` True if a 1D collinear overlap exists.
    """
    nA, nB = len(A) - 1, len(B) - 1
    t_by_seg: list[list[float]] = [[] for _ in range(nA)]
    points: list[np.ndarray] = []
    overlap = False
    a1 = A[:-1][:, None, :]
    a2 = A[1:][:, None, :]
    b1 = B[:-1][None, :, :]
    b2 = B[1:][None, :, :]
    da = a2 - a1
    db = b2 - b1
    denom = da[..., 0] * db[..., 1] - da[..., 1] * db[..., 0]
    diff = b1 - a1
    cross1 = diff[..., 0] * db[..., 1] - diff[..., 1] * db[..., 0]
    cross2 = diff[..., 0] * da[..., 1] - diff[..., 1] * da[..., 0]
    with np.errstate(divide="ignore", invalid="ignore"):
        t = np.where(denom != 0, cross1 / denom, np.nan)
        u = np.where(denom != 0, cross2 / denom, np.nan)
    hit = (denom != 0) & (t >= 0) & (t <= 1) & (u >= 0) & (u <= 1)
    for i, j in zip(*np.nonzero(hit)):
        tv = float(t[i, j])
        t_by_seg[i].append(tv)
        points.append(A[i] + tv * (A[i + 1] - A[i]))
    # parallel & collinear
    par = (denom == 0) & (cross1 == 0)
    for i, j in zip(*np.nonzero(par)):
        d = A[i + 1] - A[i]
        len2 = float(d @ d)
        if len2 == 0:
            continue
        t0 = float((B[j] - A[i]) @ d) / len2
        t1 = float((B[j + 1] - A[i]) @ d) / len2
        lo, hi = min(t0, t1), max(t0, t1)
        lo, hi = max(lo, 0.0), min(hi, 1.0)
        if hi < lo:
            continue
        if hi == lo:
            t_by_seg[i].append(lo)
            points.append(A[i] + lo * d)
        else:
            overlap = True
            t_by_seg[i].extend([lo, hi])
    return t_by_seg, points, overlap


def _pieces(A: np.ndarray, others: list[np.ndarray]):
    """Split polyline A at all crossings with `others`; return midpoints of the
    resulting sub-segments (for piece classification) + isolated touch points."""
    nA = len(A) - 1
    t_all: list[list[float]] = [[0.0, 1.0] for _ in range(nA)]
    pts: list[np.ndarray] = []
    overlap = False
    for B in others:
        tb, p, ov = _pairwise_splits(A, B)
        overlap = overlap or ov
        pts.extend(p)
        for i in range(nA):
            t_all[i].extend(tb[i])
    mids = []
    for i in range(nA):
        ts = np.unique(np.clip(np.array(t_all[i]), 0.0, 1.0))
        seg = A[i + 1] - A[i]
        if float(seg @ seg) == 0.0:
            continue
        for t0, t1 in zip(ts[:-1], ts[1:]):
            if t1 > t0:
                mids.append(A[i] + 0.5 * (t0 + t1) * seg)
    mids_arr = np.array(mids).reshape(-1, 2)
    pts_arr = np.array(pts).reshape(-1, 2) if pts else np.empty((0, 2))
    return mids_arr, pts_arr, overlap


def _classify_region(xs, ys, g: Geometry) -> np.ndarray:
    """0 exterior / 1 interior / 2 boundary of points vs any geometry."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    if isinstance(g, (Polygon, MultiPolygon)):
        polys = g.parts if isinstance(g, MultiPolygon) else (g,)
        cls = np.full(len(xs), EXTERIOR, dtype=np.int8)
        for p in polys:
            c = classify_points_polygon(xs, ys, p)
            cls = np.where(cls == INTERIOR, cls, np.maximum(cls, c))
            cls = np.where((cls == BOUNDARY) & (c == INTERIOR), INTERIOR, cls)
        return cls
    if isinstance(g, (LineString, MultiLineString)):
        from geomesa_tpu.geometry.predicates import points_intersect_geom

        on = points_intersect_geom(xs, ys, g)
        bp = _boundary_points(g)
        cls = np.where(on, INTERIOR, EXTERIOR).astype(np.int8)
        if len(bp):
            at_end = ((xs[:, None] == bp[None, :, 0]) & (ys[:, None] == bp[None, :, 1])).any(axis=1)
            cls = np.where(on & at_end, BOUNDARY, cls)
        return cls
    if isinstance(g, Point):
        return np.where((xs == g.x) & (ys == g.y), INTERIOR, EXTERIOR).astype(np.int8)
    if isinstance(g, MultiPoint):
        cls = np.full(len(xs), EXTERIOR, dtype=np.int8)
        for p in g.parts:
            cls = np.maximum(cls, _classify_region(xs, ys, p))
        return cls
    raise TypeError(type(g).__name__)


def representative_point(poly: Polygon) -> Point:
    """A point guaranteed strictly inside a valid polygon (point-on-surface).

    Casts a horizontal chord at a y midway between two distinct vertex
    ordinates and takes the midpoint of the first interior interval.
    """
    yv = np.unique(np.concatenate([r[:, 1] for r in poly.rings]))
    candidates = 0.5 * (yv[:-1] + yv[1:]) if len(yv) > 1 else yv
    for y in candidates:
        xs = []
        for r in poly.rings:
            y1, y2 = r[:-1, 1], r[1:, 1]
            x1, x2 = r[:-1, 0], r[1:, 0]
            straddle = (y1 > y) != (y2 > y)
            with np.errstate(divide="ignore", invalid="ignore"):
                xi = x1 + (y - y1) * (x2 - x1) / (y2 - y1)
            xs.extend(xi[straddle].tolist())
        xs = sorted(xs)
        for x0, x1 in zip(xs[0::2], xs[1::2]):
            if x1 > x0:
                cand = Point(0.5 * (x0 + x1), float(y))
                if classify_points_polygon([cand.x], [cand.y], poly)[0] == INTERIOR:
                    return cand
    return centroid(poly)  # degenerate fallback


def _im_set(M, row, col, d):
    i = {"I": 0, "B": 1, "E": 2}[row]
    j = {"I": 0, "B": 1, "E": 2}[col]
    M[i][j] = max(M[i][j], d)


def _accumulate_points(M, pts: np.ndarray, a: Geometry, b: Geometry, dim0: int = 0):
    """Classify isolated points against both geometries; bump matrix cells."""
    if len(pts) == 0:
        return
    ca = _classify_region(pts[:, 0], pts[:, 1], a)
    cb = _classify_region(pts[:, 0], pts[:, 1], b)
    names = {INTERIOR: "I", BOUNDARY: "B", EXTERIOR: "E"}
    for ra, rb in zip(ca, cb):
        _im_set(M, names[int(ra)], names[int(rb)], dim0)


def relate(a: Geometry, b: Geometry) -> str:
    """DE-9IM intersection matrix of ``a`` vs ``b`` as a 9-char string.

    From-scratch implementation: skeleton polylines of each geometry are split
    at every crossing with the other's skeleton; sub-segment midpoints and
    isolated intersection points are classified against each geometry's
    interior/boundary/exterior, and each classified piece bumps the dimension
    of its matrix cell. Areal interior-vs-interior/exterior cells are derived
    from the boundary-piece classification (a boundary arc of one polygon lying
    strictly inside the other implies 2D overlap on both sides of the arc).
    """
    M = [[_F] * 3 for _ in range(3)]
    dim_a, dim_b = dimension(a), dimension(b)
    _im_set(M, "E", "E", 2)

    # --- point components of a vs b and vice versa
    def point_parts(g):
        if isinstance(g, Point):
            return [g]
        if isinstance(g, MultiPoint):
            return list(g.parts)
        return []

    pa, pb = point_parts(a), point_parts(b)
    names = {INTERIOR: "I", BOUNDARY: "B", EXTERIOR: "E"}
    if pa:
        pts = np.array([[p.x, p.y] for p in pa])
        cb = _classify_region(pts[:, 0], pts[:, 1], b)
        for c in cb:
            _im_set(M, "I", names[int(c)], 0)
        if dim_b > 0:
            # b's interior minus a finite point set keeps its dimension
            _im_set(M, "E", "I", dim_b if dim_b == 2 else 1)
            if dim_b == 2:
                _im_set(M, "E", "B", 1)
    if pb and not pa:
        pts = np.array([[p.x, p.y] for p in pb])
        ca = _classify_region(pts[:, 0], pts[:, 1], a)
        for c in ca:
            _im_set(M, names[int(c)], "I", 0)
        if dim_a > 0:
            _im_set(M, "I", "E", dim_a if dim_a == 2 else 1)
            if dim_a == 2:
                _im_set(M, "B", "E", 1)
    if pa and dim_b == 0 and pb:
        # point-set vs point-set exteriors
        set_a = {(p.x, p.y) for p in pa}
        set_b = {(q.x, q.y) for q in pb}
        if set_a - set_b:
            _im_set(M, "I", "E", 0)
        if set_b - set_a:
            _im_set(M, "E", "I", 0)

    # boundary endpoints of line parts, classified exactly against the other
    bp_a = _boundary_points(a)
    if len(bp_a):
        cb = _classify_region(bp_a[:, 0], bp_a[:, 1], b)
        for c in cb:
            _im_set(M, "B", names[int(c)], 0)
    bp_b = _boundary_points(b)
    if len(bp_b):
        ca = _classify_region(bp_b[:, 0], bp_b[:, 1], a)
        for c in ca:
            _im_set(M, names[int(c)], "B", 0)

    lines_a, lines_b = _skeleton_lines(a), _skeleton_lines(b)
    if lines_a and (lines_b or pb):
        # pieces of a's skeleton classified against both geometries
        all_mids, all_pts = [], []
        for la in lines_a:
            m, p, _ = _pieces(la, lines_b)
            all_mids.append(m)
            all_pts.append(p)
        mids = np.vstack(all_mids) if all_mids else np.empty((0, 2))
        pts = np.vstack(all_pts) if all_pts else np.empty((0, 2))

        if len(mids):
            ca = _classify_region(mids[:, 0], mids[:, 1], a)
            cb = _classify_region(mids[:, 0], mids[:, 1], b)
            names = {INTERIOR: "I", BOUNDARY: "B", EXTERIOR: "E"}
            for ra, rb in zip(ca, cb):
                _im_set(M, names[int(ra)], names[int(rb)], 1)
                if dim_a == 2 and ra == BOUNDARY:
                    # a is areal: its boundary arc has a's interior alongside
                    if rb == INTERIOR:
                        _im_set(M, "I", "I", 2)
                    if rb == EXTERIOR:
                        _im_set(M, "I", "E", 2)
        _accumulate_points(M, pts, a, b)

    if lines_b and (lines_a or pa):
        all_mids, all_pts = [], []
        for lb in lines_b:
            m, p, _ = _pieces(lb, lines_a)
            all_mids.append(m)
            all_pts.append(p)
        mids = np.vstack(all_mids) if all_mids else np.empty((0, 2))
        pts = np.vstack(all_pts) if all_pts else np.empty((0, 2))
        if len(mids):
            ca = _classify_region(mids[:, 0], mids[:, 1], a)
            cb = _classify_region(mids[:, 0], mids[:, 1], b)
            names = {INTERIOR: "I", BOUNDARY: "B", EXTERIOR: "E"}
            for ra, rb in zip(ca, cb):
                _im_set(M, names[int(ra)], names[int(rb)], 1)
                if dim_b == 2 and rb == BOUNDARY:
                    if ra == INTERIOR:
                        _im_set(M, "I", "I", 2)
                    if ra == EXTERIOR:
                        _im_set(M, "E", "I", 2)
        _accumulate_points(M, pts, a, b)

    # areal interiors with no boundary interaction at all (equal or nested)
    if dim_a == 2 and dim_b == 2 and M[0][0] < 2:
        for poly_src, other in ((a, b), (b, a)):
            polys = poly_src.parts if isinstance(poly_src, MultiPolygon) else (poly_src,)
            rp = representative_point(polys[0])
            if _classify_region([rp.x], [rp.y], other)[0] == INTERIOR:
                _im_set(M, "I", "I", 2)
                break

    # line/areal vs anything: does any piece of its skeleton avoid the other
    # entirely? covered above via midpoints (they classify as E on the other
    # side). Nothing further needed.

    out = []
    for i in range(3):
        for j in range(3):
            out.append("F" if M[i][j] == _F else str(M[i][j]))
    return "".join(out)


def relate_bool(a: Geometry, b: Geometry, pattern: str) -> bool:
    """Match a DE-9IM pattern (``T``/``F``/``*``/``0``/``1``/``2``)."""
    if len(pattern) != 9:
        raise ValueError(f"DE-9IM pattern must be 9 chars: {pattern!r}")
    m = relate(a, b)
    for mc, pc in zip(m, pattern):
        if pc == "*":
            continue
        if pc == "T":
            if mc == "F":
                return False
        elif pc != mc:
            return False
    return True


def equals(a: Geometry, b: Geometry) -> bool:
    return relate_bool(a, b, "T*F**FFF*")


def touches(a: Geometry, b: Geometry) -> bool:
    if not intersects(a, b):
        return False
    m = relate(a, b)
    return m[0] == "F" and (m[1] != "F" or m[3] != "F" or m[4] != "F")


def crosses(a: Geometry, b: Geometry) -> bool:
    da, db = dimension(a), dimension(b)
    m = relate(a, b)
    if da < db:
        return m[0] != "F" and m[2] != "F"
    if da > db:
        return m[0] != "F" and m[6] != "F"
    if da == 1 and db == 1:
        return m[0] == "0"
    return False


def overlaps(a: Geometry, b: Geometry) -> bool:
    da, db = dimension(a), dimension(b)
    if da != db:
        return False
    m = relate(a, b)
    if da == 1:
        return m[0] == "1" and m[2] != "F" and m[6] != "F"
    return m[0] != "F" and m[2] != "F" and m[6] != "F"


def covers(a: Geometry, b: Geometry) -> bool:
    m = relate(a, b)
    some = m[0] != "F" or m[1] != "F" or m[3] != "F" or m[4] != "F"
    return some and m[6] == "F" and m[7] == "F"


def covered_by(a: Geometry, b: Geometry) -> bool:
    return covers(b, a)
