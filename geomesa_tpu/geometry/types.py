"""Planar geometry value model (the JTS role, minimal and numpy-backed).

The reference leans on JTS for geometry objects and predicates
(``geomesa-utils/.../utils/geotools/GeometryUtils.scala``, SURVEY.md §2.18).
We implement a small, exact, pure-numpy planar model instead: coordinates are
``(N, 2)`` float64 arrays, every geometry knows its bbox, and the batched
predicate kernels live in :mod:`geomesa_tpu.geometry.predicates` (scalar exact
versions here are the oracle's semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable

import numpy as np

__all__ = [
    "Geometry",
    "Point",
    "LineString",
    "Polygon",
    "MultiPoint",
    "MultiLineString",
    "MultiPolygon",
    "bbox_union",
]


def _coords(arr) -> np.ndarray:
    a = np.asarray(arr, dtype=np.float64)
    if a.ndim != 2 or a.shape[1] != 2:
        raise ValueError(f"coordinates must be (N, 2): got {a.shape}")
    return a


class Geometry:
    """Base geometry; subclasses set ``geom_type`` and implement ``bbox``."""

    geom_type: str = "Geometry"

    @property
    def bbox(self) -> tuple[float, float, float, float]:  # (xmin, ymin, xmax, ymax)
        raise NotImplementedError

    @property
    def is_point(self) -> bool:
        return isinstance(self, Point)

    def __repr__(self) -> str:
        from geomesa_tpu.geometry.wkt import to_wkt

        return to_wkt(self)

    def __eq__(self, other) -> bool:
        from geomesa_tpu.geometry.wkt import to_wkt

        return isinstance(other, Geometry) and to_wkt(self) == to_wkt(other)

    def __hash__(self) -> int:
        from geomesa_tpu.geometry.wkt import to_wkt

        return hash(to_wkt(self))


@dataclass(frozen=True, eq=False, repr=False)
class Point(Geometry):
    x: float
    y: float
    geom_type = "Point"

    @property
    def bbox(self):
        return (self.x, self.y, self.x, self.y)


@dataclass(frozen=True, eq=False, repr=False)
class LineString(Geometry):
    coords: np.ndarray  # (N, 2) f64
    geom_type = "LineString"

    def __post_init__(self):
        object.__setattr__(self, "coords", _coords(self.coords))

    @property
    def bbox(self):
        c = self.coords
        return (c[:, 0].min(), c[:, 1].min(), c[:, 0].max(), c[:, 1].max())


@dataclass(frozen=True, eq=False, repr=False)
class Polygon(Geometry):
    """Shell + holes; rings need not be explicitly closed (we close them)."""

    shell: np.ndarray  # (N, 2) f64
    holes: tuple[np.ndarray, ...] = ()
    geom_type = "Polygon"

    def __post_init__(self):
        object.__setattr__(self, "shell", _close_ring(_coords(self.shell)))
        object.__setattr__(
            self, "holes", tuple(_close_ring(_coords(h)) for h in self.holes)
        )

    @property
    def bbox(self):
        c = self.shell
        return (c[:, 0].min(), c[:, 1].min(), c[:, 0].max(), c[:, 1].max())

    @property
    def rings(self) -> tuple[np.ndarray, ...]:
        return (self.shell, *self.holes)


def _close_ring(c: np.ndarray) -> np.ndarray:
    if len(c) < 3:
        raise ValueError("ring needs at least 3 coordinates")
    if not np.array_equal(c[0], c[-1]):
        c = np.vstack([c, c[:1]])
    return c


@dataclass(frozen=True, eq=False, repr=False)
class _Multi(Geometry):
    parts: tuple[Geometry, ...]

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))

    @property
    def bbox(self):
        return bbox_union(p.bbox for p in self.parts)


class MultiPoint(_Multi):
    geom_type = "MultiPoint"


class MultiLineString(_Multi):
    geom_type = "MultiLineString"


class MultiPolygon(_Multi):
    geom_type = "MultiPolygon"


def bbox_union(boxes: Iterable[tuple[float, float, float, float]]):
    boxes = list(boxes)
    if not boxes:
        raise ValueError("empty geometry collection")
    a = np.asarray(boxes, dtype=np.float64)
    return (a[:, 0].min(), a[:, 1].min(), a[:, 2].max(), a[:, 3].max())


def box(xmin: float, ymin: float, xmax: float, ymax: float) -> Polygon:
    """Axis-aligned rectangle polygon (the CQL BBOX literal)."""
    return Polygon(
        np.array(
            [[xmin, ymin], [xmax, ymin], [xmax, ymax], [xmin, ymax], [xmin, ymin]],
            dtype=np.float64,
        )
    )
