"""WKT read/write for the geometry model (the JTS WKTReader/Writer role,
``geomesa-utils/.../geotools`` WKT utils — SURVEY.md §2.18)."""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

_NUM = r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?"


def _parse_coord_seq(body: str) -> np.ndarray:
    pts = []
    for pair in body.split(","):
        xy = pair.split()
        if len(xy) < 2:
            raise ValueError(f"bad coordinate: {pair!r}")
        pts.append((float(xy[0]), float(xy[1])))
    return np.asarray(pts, dtype=np.float64)


def _split_rings(body: str) -> list[str]:
    """Split '(...), (...)' at top level."""
    rings, depth, start = [], 0, None
    for i, ch in enumerate(body):
        if ch == "(":
            if depth == 0:
                start = i + 1
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                rings.append(body[start:i])
    if depth != 0:
        raise ValueError(f"unbalanced parens in WKT body: {body!r}")
    return rings


def from_wkt(wkt: str) -> Geometry:
    s = wkt.strip()
    m = re.match(r"^([A-Za-z]+)\s*\((.*)\)\s*$", s, re.S)
    if not m:
        raise ValueError(f"invalid WKT: {wkt!r}")
    typ = m.group(1).upper()
    body = m.group(2).strip()
    if typ == "POINT":
        c = _parse_coord_seq(body)
        return Point(float(c[0, 0]), float(c[0, 1]))
    if typ == "LINESTRING":
        return LineString(_parse_coord_seq(body))
    if typ == "POLYGON":
        rings = [_parse_coord_seq(r) for r in _split_rings(body)]
        return Polygon(rings[0], tuple(rings[1:]))
    if typ == "MULTIPOINT":
        if "(" in body:
            pts = [_parse_coord_seq(r) for r in _split_rings(body)]
            coords = np.vstack(pts)
        else:
            coords = _parse_coord_seq(body)
        return MultiPoint(tuple(Point(float(x), float(y)) for x, y in coords))
    if typ == "MULTILINESTRING":
        return MultiLineString(
            tuple(LineString(_parse_coord_seq(r)) for r in _split_rings(body))
        )
    if typ == "MULTIPOLYGON":
        polys = []
        # each polygon is ((ring), (ring)...)
        depth, start = 0, None
        for i, ch in enumerate(body):
            if ch == "(":
                if depth == 0:
                    start = i + 1
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    inner = body[start:i]
                    rings = [_parse_coord_seq(r) for r in _split_rings(inner)]
                    polys.append(Polygon(rings[0], tuple(rings[1:])))
        return MultiPolygon(tuple(polys))
    raise ValueError(f"unsupported WKT type: {typ}")


def _fmt(v: float) -> str:
    return f"{v:.10g}"


def _ring_str(c: np.ndarray) -> str:
    return "(" + ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in c) + ")"


def to_wkt(g: Geometry) -> str:
    if isinstance(g, Point):
        return f"POINT ({_fmt(g.x)} {_fmt(g.y)})"
    if isinstance(g, LineString):
        return "LINESTRING " + _ring_str(g.coords)
    if isinstance(g, Polygon):
        return "POLYGON (" + ", ".join(_ring_str(r) for r in g.rings) + ")"
    if isinstance(g, MultiPoint):
        return "MULTIPOINT (" + ", ".join(
            f"({_fmt(p.x)} {_fmt(p.y)})" for p in g.parts
        ) + ")"
    if isinstance(g, MultiLineString):
        return "MULTILINESTRING (" + ", ".join(_ring_str(l.coords) for l in g.parts) + ")"
    if isinstance(g, MultiPolygon):
        return (
            "MULTIPOLYGON ("
            + ", ".join("(" + ", ".join(_ring_str(r) for r in p.rings) + ")" for p in g.parts)
            + ")"
        )
    raise ValueError(f"cannot serialize: {g!r}")
