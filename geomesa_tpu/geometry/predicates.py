"""Planar spatial predicates: exact scalar forms + batched numpy kernels.

Role parity: JTS predicates used by the reference's secondary filters and
ST_* Spark UDFs (``geomesa-spark-jts/.../udf/SpatialRelationFunctions.scala``,
SURVEY.md §2.14) and the post-scan refinement the server-side iterators apply.
The batched forms here vectorize over candidate point sets (one polygon × N
points per call) — the same formulas are re-expressed in jax by
:mod:`geomesa_tpu.ops.geom` for on-device refine; THIS module is the semantics
oracle both must match.

Boundary semantics follow JTS: ``intersects`` includes boundaries;
``contains``/``within`` exclude boundary-only contact for points.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    _Multi,
)

EXTERIOR, INTERIOR, BOUNDARY = 0, 1, 2


# ---------------------------------------------------------------------------
# batched point kernels (one geometry × N points)
# ---------------------------------------------------------------------------

def points_in_bbox(xs, ys, bbox) -> np.ndarray:
    xmin, ymin, xmax, ymax = bbox
    xs = np.asarray(xs)
    ys = np.asarray(ys)
    return (xs >= xmin) & (xs <= xmax) & (ys >= ymin) & (ys <= ymax)


def classify_points_ring(xs, ys, ring: np.ndarray) -> np.ndarray:
    """Classify N points against one closed ring: 0 exterior / 1 interior / 2 boundary.

    Even-odd ray casting (rightward ray), with an explicit on-segment test so
    boundary contact is never misclassified by crossing parity.
    """
    xs = np.asarray(xs, dtype=np.float64)[:, None]  # (N, 1)
    ys = np.asarray(ys, dtype=np.float64)[:, None]
    x1 = ring[:-1, 0][None, :]  # (1, E)
    y1 = ring[:-1, 1][None, :]
    x2 = ring[1:, 0][None, :]
    y2 = ring[1:, 1][None, :]

    # on-segment: collinear and within the segment's bbox
    cross = (x2 - x1) * (ys - y1) - (y2 - y1) * (xs - x1)
    on_seg = (
        (cross == 0.0)
        & (xs >= np.minimum(x1, x2))
        & (xs <= np.maximum(x1, x2))
        & (ys >= np.minimum(y1, y2))
        & (ys <= np.maximum(y1, y2))
    )
    boundary = on_seg.any(axis=1)

    # crossing parity: edge straddles the horizontal line through the point,
    # and the intersection is strictly right of the point
    straddle = (y1 > ys) != (y2 > ys)
    with np.errstate(divide="ignore", invalid="ignore"):
        xint = x1 + (ys - y1) * (x2 - x1) / (y2 - y1)
    crossing = straddle & (xs < xint)
    inside = (crossing.sum(axis=1) % 2).astype(bool)

    out = np.where(inside, INTERIOR, EXTERIOR)
    return np.where(boundary, BOUNDARY, out).astype(np.int8)


def classify_points_polygon(xs, ys, poly: Polygon) -> np.ndarray:
    """0 exterior / 1 interior / 2 boundary vs a polygon with holes.

    A point on a hole's ring is on the polygon boundary; inside a hole is
    exterior.
    """
    cls = classify_points_ring(xs, ys, poly.shell)
    for hole in poly.holes:
        h = classify_points_ring(xs, ys, hole)
        cls = np.where(
            cls == INTERIOR,
            np.where(h == INTERIOR, EXTERIOR, np.where(h == BOUNDARY, BOUNDARY, cls)),
            cls,
        ).astype(np.int8)
    return cls


def points_intersect_geom(xs, ys, geom: Geometry) -> np.ndarray:
    """Batched JTS-style ``intersects(geom, POINT(x y))`` over N points."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    if isinstance(geom, Point):
        return (xs == geom.x) & (ys == geom.y)
    if isinstance(geom, Polygon):
        return classify_points_polygon(xs, ys, geom) != EXTERIOR
    if isinstance(geom, LineString):
        return _points_on_line(xs, ys, geom.coords)
    if isinstance(geom, _Multi):
        out = np.zeros(len(xs), dtype=bool)
        for p in geom.parts:
            out |= points_intersect_geom(xs, ys, p)
        return out
    raise ValueError(f"unsupported geometry: {geom.geom_type}")


def points_within_geom(xs, ys, geom: Geometry) -> np.ndarray:
    """Batched ``within(POINT, geom)``: interior only (boundary excluded)."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    if isinstance(geom, Polygon):
        return classify_points_polygon(xs, ys, geom) == INTERIOR
    if isinstance(geom, MultiPolygon):
        out = np.zeros(len(xs), dtype=bool)
        for p in geom.parts:
            out |= classify_points_polygon(xs, ys, p) == INTERIOR
        return out
    if isinstance(geom, Point):
        return (xs == geom.x) & (ys == geom.y)
    # a point is never *within* a line's interior in the JTS sense unless on
    # it and not at an endpoint; approximate as on-line
    if isinstance(geom, (LineString, MultiLineString)):
        return points_intersect_geom(xs, ys, geom)
    raise ValueError(f"unsupported geometry: {geom.geom_type}")


def _points_on_line(xs, ys, coords: np.ndarray) -> np.ndarray:
    xs = xs[:, None]
    ys = ys[:, None]
    x1, y1 = coords[:-1, 0][None, :], coords[:-1, 1][None, :]
    x2, y2 = coords[1:, 0][None, :], coords[1:, 1][None, :]
    cross = (x2 - x1) * (ys - y1) - (y2 - y1) * (xs - x1)
    on = (
        (cross == 0.0)
        & (xs >= np.minimum(x1, x2))
        & (xs <= np.maximum(x1, x2))
        & (ys >= np.minimum(y1, y2))
        & (ys <= np.maximum(y1, y2))
    )
    return on.any(axis=1)


def points_dist2_geom(xs, ys, geom: Geometry) -> np.ndarray:
    """Squared euclidean distance from N points to a geometry (0 if inside)."""
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    if isinstance(geom, Point):
        return (xs - geom.x) ** 2 + (ys - geom.y) ** 2
    if isinstance(geom, LineString):
        return _points_dist2_segments(xs, ys, geom.coords)
    if isinstance(geom, Polygon):
        d2 = _points_dist2_segments(xs, ys, geom.shell)
        for h in geom.holes:
            d2 = np.minimum(d2, _points_dist2_segments(xs, ys, h))
        inside = classify_points_polygon(xs, ys, geom) == INTERIOR
        return np.where(inside, 0.0, d2)
    if isinstance(geom, _Multi):
        return np.min([points_dist2_geom(xs, ys, p) for p in geom.parts], axis=0)
    raise ValueError(f"unsupported geometry: {geom.geom_type}")


def _points_dist2_segments(xs, ys, coords: np.ndarray) -> np.ndarray:
    px = xs[:, None]
    py = ys[:, None]
    x1, y1 = coords[:-1, 0][None, :], coords[:-1, 1][None, :]
    x2, y2 = coords[1:, 0][None, :], coords[1:, 1][None, :]
    dx, dy = x2 - x1, y2 - y1
    len2 = dx * dx + dy * dy
    with np.errstate(divide="ignore", invalid="ignore"):
        t = np.where(len2 > 0, ((px - x1) * dx + (py - y1) * dy) / len2, 0.0)
    t = np.clip(t, 0.0, 1.0)
    cx, cy = x1 + t * dx, y1 + t * dy
    return ((px - cx) ** 2 + (py - cy) ** 2).min(axis=1)


# ---------------------------------------------------------------------------
# segment intersection (for line/polygon × line/polygon)
# ---------------------------------------------------------------------------

def _segments_intersect(a: np.ndarray, b: np.ndarray) -> bool:
    """Any segment of polyline ``a`` intersects any segment of polyline ``b``."""
    ax1, ay1 = a[:-1, 0][:, None], a[:-1, 1][:, None]
    ax2, ay2 = a[1:, 0][:, None], a[1:, 1][:, None]
    bx1, by1 = b[:-1, 0][None, :], b[:-1, 1][None, :]
    bx2, by2 = b[1:, 0][None, :], b[1:, 1][None, :]

    d1 = (ax2 - ax1) * (by1 - ay1) - (ay2 - ay1) * (bx1 - ax1)
    d2 = (ax2 - ax1) * (by2 - ay1) - (ay2 - ay1) * (bx2 - ax1)
    d3 = (bx2 - bx1) * (ay1 - by1) - (by2 - by1) * (ax1 - bx1)
    d4 = (bx2 - bx1) * (ay2 - by1) - (by2 - by1) * (ax2 - bx1)

    proper = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0)) & (d1 != d2) & (d3 != d4)
    if proper.any():
        return True

    # collinear / endpoint touching
    def on(d, px, py, qx1, qy1, qx2, qy2):
        return (
            (d == 0)
            & (px >= np.minimum(qx1, qx2))
            & (px <= np.maximum(qx1, qx2))
            & (py >= np.minimum(qy1, qy2))
            & (py <= np.maximum(qy1, qy2))
        )

    touch = (
        on(d1, bx1, by1, ax1, ay1, ax2, ay2)
        | on(d2, bx2, by2, ax1, ay1, ax2, ay2)
        | on(d3, ax1, ay1, bx1, by1, bx2, by2)
        | on(d4, ax2, ay2, bx1, by1, bx2, by2)
    )
    return bool(touch.any())


# ---------------------------------------------------------------------------
# scalar geometry × geometry predicates (oracle semantics)
# ---------------------------------------------------------------------------

def _bbox_disjoint(a: Geometry, b: Geometry) -> bool:
    ax1, ay1, ax2, ay2 = a.bbox
    bx1, by1, bx2, by2 = b.bbox
    return ax2 < bx1 or bx2 < ax1 or ay2 < by1 or by2 < ay1


def _lines(geom: Geometry) -> list[np.ndarray]:
    """All polyline coordinate arrays making up a geometry's boundary/path."""
    if isinstance(geom, LineString):
        return [geom.coords]
    if isinstance(geom, Polygon):
        return list(geom.rings)
    if isinstance(geom, _Multi):
        out: list[np.ndarray] = []
        for p in geom.parts:
            out.extend(_lines(p))
        return out
    return []


def _vertices(geom: Geometry) -> np.ndarray:
    if isinstance(geom, Point):
        return np.array([[geom.x, geom.y]])
    vs = _lines(geom)
    return np.vstack(vs) if vs else np.empty((0, 2))


def intersects(a: Geometry, b: Geometry) -> bool:
    if _bbox_disjoint(a, b):
        return False
    if isinstance(a, Point):
        return bool(points_intersect_geom(np.array([a.x]), np.array([a.y]), b)[0])
    if isinstance(b, Point):
        return intersects(b, a)
    if isinstance(a, _Multi):
        return any(intersects(p, b) for p in a.parts)
    if isinstance(b, _Multi):
        return any(intersects(a, p) for p in b.parts)
    # line/polygon × line/polygon: any boundary crossing, or one inside the other
    for la in _lines(a):
        for lb in _lines(b):
            if _segments_intersect(la, lb):
                return True
    if isinstance(a, Polygon):
        v = _vertices(b)
        if bool(classify_points_polygon(v[:1, 0], v[:1, 1], a)[0] != EXTERIOR):
            return True
    if isinstance(b, Polygon):
        v = _vertices(a)
        if bool(classify_points_polygon(v[:1, 0], v[:1, 1], b)[0] != EXTERIOR):
            return True
    return False


def disjoint(a: Geometry, b: Geometry) -> bool:
    return not intersects(a, b)


def within(a: Geometry, b: Geometry) -> bool:
    """``a within b``. Exact for points; for extended ``a``: all vertices inside
    (or on boundary) of ``b`` with no boundary crossings and at least one
    interior vertex — the pragmatic planar approximation (documented in README;
    exact DE-9IM is out of scope for v1)."""
    if _bbox_disjoint(a, b):
        return False
    if isinstance(a, Point):
        return bool(points_within_geom(np.array([a.x]), np.array([a.y]), b)[0])
    if isinstance(a, _Multi):
        return all(within(p, b) for p in a.parts)
    if isinstance(b, (Polygon, MultiPolygon)):
        v = _vertices(a)
        polys = b.parts if isinstance(b, MultiPolygon) else (b,)
        cls = np.full(len(v), EXTERIOR, dtype=np.int8)
        for p in polys:
            c = classify_points_polygon(v[:, 0], v[:, 1], p)
            cls = np.maximum(cls, np.where(c == EXTERIOR, cls, c))
            if all(
                not _segments_intersect_interior(la, p) for la in _lines(a)
            ) and bool((c != EXTERIOR).all()) and bool((c == INTERIOR).any()):
                return True
        return False
    return False


def _segments_intersect_interior(line: np.ndarray, poly: Polygon) -> bool:
    """True if ``line`` properly crosses the polygon boundary (touch allowed)."""
    for ring in poly.rings:
        ax1, ay1 = line[:-1, 0][:, None], line[:-1, 1][:, None]
        ax2, ay2 = line[1:, 0][:, None], line[1:, 1][:, None]
        bx1, by1 = ring[:-1, 0][None, :], ring[:-1, 1][None, :]
        bx2, by2 = ring[1:, 0][None, :], ring[1:, 1][None, :]
        d1 = (ax2 - ax1) * (by1 - ay1) - (ay2 - ay1) * (bx1 - ax1)
        d2 = (ax2 - ax1) * (by2 - ay1) - (ay2 - ay1) * (bx2 - ax1)
        d3 = (bx2 - bx1) * (ay1 - by1) - (by2 - by1) * (ax1 - bx1)
        d4 = (bx2 - bx1) * (ay2 - by1) - (by2 - by1) * (ax2 - bx1)
        proper = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0)) & (d1 != 0) & (d2 != 0) & (d3 != 0) & (d4 != 0)
        if proper.any():
            return True
    return False


def contains(a: Geometry, b: Geometry) -> bool:
    return within(b, a)


def distance(a: Geometry, b: Geometry) -> float:
    """Min euclidean distance (degrees); 0 when intersecting."""
    if intersects(a, b):
        return 0.0
    va = _vertices(a)
    vb = _vertices(b)
    best = np.inf
    for lb in _lines(b) or [vb]:
        best = min(best, float(np.sqrt(_points_dist2_segments(va[:, 0], va[:, 1], lb)).min())) if len(lb) > 1 else best
    for la in _lines(a) or [va]:
        if len(la) > 1:
            best = min(best, float(np.sqrt(_points_dist2_segments(vb[:, 0], vb[:, 1], la)).min()))
    if not np.isfinite(best):  # point × point
        best = float(np.sqrt(((va[:, None, :] - vb[None, :, :]) ** 2).sum(-1)).min())
    return best


def dwithin(a: Geometry, b: Geometry, d: float) -> bool:
    return distance(a, b) <= d
