"""Geometry ↔ GeoJSON dict conversion (export side).

Import side (``geojson_geometry``) lives with the JSON converter
(:mod:`geomesa_tpu.convert.json_converter`); this is the inverse, used by the
GeoJSON export format and the REST endpoints (SURVEY.md §2.8/§2.19).
"""

from __future__ import annotations

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

__all__ = ["geometry_to_geojson", "table_to_feature_collection"]


def _ring(c):
    return [[float(x), float(y)] for x, y in c]


def geometry_to_geojson(g: Geometry | None) -> dict | None:
    if g is None:
        return None
    if isinstance(g, Point):
        return {"type": "Point", "coordinates": [float(g.x), float(g.y)]}
    if isinstance(g, LineString):
        return {"type": "LineString", "coordinates": _ring(g.coords)}
    if isinstance(g, Polygon):
        return {"type": "Polygon", "coordinates": [_ring(r) for r in g.rings]}
    if isinstance(g, MultiPoint):
        return {
            "type": "MultiPoint",
            "coordinates": [[float(p.x), float(p.y)] for p in g.parts],
        }
    if isinstance(g, MultiLineString):
        return {
            "type": "MultiLineString",
            "coordinates": [_ring(p.coords) for p in g.parts],
        }
    if isinstance(g, MultiPolygon):
        return {
            "type": "MultiPolygon",
            "coordinates": [[_ring(r) for r in p.rings] for p in g.parts],
        }
    raise TypeError(f"cannot convert {type(g).__name__} to GeoJSON")


def table_to_feature_collection(table) -> dict:
    """FeatureTable → GeoJSON FeatureCollection dict (dates stay epoch ms)."""
    gf = table.sft.geom_field
    feats = []
    for i in range(len(table)):
        rec = table.record(i)
        geom = rec.pop(gf, None) if gf else None
        feats.append(
            {
                "type": "Feature",
                "id": str(table.fids[i]),
                "geometry": geometry_to_geojson(geom) if gf else None,
                "properties": rec,
            }
        )
    return {"type": "FeatureCollection", "features": feats}
