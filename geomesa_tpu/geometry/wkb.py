"""Well-Known Binary codec for the planar geometry model.

Role parity: the reference serializes geometries as WKB/TWKB
(``geomesa-feature-common/.../serialization/TwkbSerialization.scala``,
SURVEY.md §2.4) and exposes ``st_geomFromWKB``/``st_asBinary`` Spark UDFs
(``geomesa-spark-jts/.../udf/GeometricConstructorFunctions.scala``,
``GeometricOutputFunctions.scala``, SURVEY.md §2.14). This is a from-scratch
little-endian ISO WKB implementation over numpy coordinate arrays.
"""

from __future__ import annotations

import struct

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

__all__ = ["to_wkb", "from_wkb"]

_POINT, _LINESTRING, _POLYGON = 1, 2, 3
_MULTIPOINT, _MULTILINESTRING, _MULTIPOLYGON = 4, 5, 6


def _ring_bytes(c: np.ndarray) -> bytes:
    return struct.pack("<I", len(c)) + np.ascontiguousarray(
        c, dtype="<f8"
    ).tobytes()


def to_wkb(g: Geometry) -> bytes:
    """Serialize as little-endian ISO WKB."""
    if isinstance(g, Point):
        return struct.pack("<BIdd", 1, _POINT, g.x, g.y)
    if isinstance(g, LineString):
        return struct.pack("<BI", 1, _LINESTRING) + _ring_bytes(g.coords)
    if isinstance(g, Polygon):
        rings = g.rings
        out = [struct.pack("<BII", 1, _POLYGON, len(rings))]
        out.extend(_ring_bytes(r) for r in rings)
        return b"".join(out)
    if isinstance(g, (MultiPoint, MultiLineString, MultiPolygon)):
        code = {
            MultiPoint: _MULTIPOINT,
            MultiLineString: _MULTILINESTRING,
            MultiPolygon: _MULTIPOLYGON,
        }[type(g)]
        out = [struct.pack("<BII", 1, code, len(g.parts))]
        out.extend(to_wkb(p) for p in g.parts)
        return b"".join(out)
    raise TypeError(f"cannot WKB-encode {type(g).__name__}")


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, fmt: str):
        vals = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += struct.calcsize(fmt)
        return vals

    def coords(self, endian: str, n: int) -> np.ndarray:
        nbytes = 16 * n
        a = np.frombuffer(
            self.data, dtype=f"{endian}f8", count=2 * n, offset=self.pos
        ).reshape(n, 2)
        self.pos += nbytes
        return a.astype(np.float64)


def _read_geom(r: _Reader) -> Geometry:
    (byte_order,) = r.read("<B")
    endian = "<" if byte_order == 1 else ">"
    (type_code,) = r.read(f"{endian}I")
    type_code &= 0xFF  # mask EWKB SRID/Z flags; only 2D supported
    if type_code == _POINT:
        x, y = r.read(f"{endian}dd")
        return Point(x, y)
    if type_code == _LINESTRING:
        (n,) = r.read(f"{endian}I")
        return LineString(r.coords(endian, n))
    if type_code == _POLYGON:
        (nrings,) = r.read(f"{endian}I")
        rings = []
        for _ in range(nrings):
            (n,) = r.read(f"{endian}I")
            rings.append(r.coords(endian, n))
        return Polygon(rings[0], tuple(rings[1:]))
    if type_code in (_MULTIPOINT, _MULTILINESTRING, _MULTIPOLYGON):
        (nparts,) = r.read(f"{endian}I")
        parts = tuple(_read_geom(r) for _ in range(nparts))
        cls = {
            _MULTIPOINT: MultiPoint,
            _MULTILINESTRING: MultiLineString,
            _MULTIPOLYGON: MultiPolygon,
        }[type_code]
        return cls(parts)
    raise ValueError(f"unsupported WKB geometry type {type_code}")


def from_wkb(data: bytes) -> Geometry:
    """Parse ISO WKB (either endianness; EWKB type flags masked)."""
    return _read_geom(_Reader(bytes(data)))
