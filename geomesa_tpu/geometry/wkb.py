"""Well-Known Binary codec for the planar geometry model.

Role parity: the reference serializes geometries as WKB/TWKB
(``geomesa-feature-common/.../serialization/TwkbSerialization.scala``,
SURVEY.md §2.4) and exposes ``st_geomFromWKB``/``st_asBinary`` Spark UDFs
(``geomesa-spark-jts/.../udf/GeometricConstructorFunctions.scala``,
``GeometricOutputFunctions.scala``, SURVEY.md §2.14). This is a from-scratch
little-endian ISO WKB implementation over numpy coordinate arrays.
"""

from __future__ import annotations

import struct

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

__all__ = ["to_wkb", "from_wkb", "to_wkb_batch", "from_wkb_batch"]

_POINT, _LINESTRING, _POLYGON = 1, 2, 3
_MULTIPOINT, _MULTILINESTRING, _MULTIPOLYGON = 4, 5, 6


def _ring_bytes(c: np.ndarray) -> bytes:
    return struct.pack("<I", len(c)) + np.ascontiguousarray(
        c, dtype="<f8"
    ).tobytes()


def to_wkb(g: Geometry) -> bytes:
    """Serialize as little-endian ISO WKB."""
    if isinstance(g, Point):
        return struct.pack("<BIdd", 1, _POINT, g.x, g.y)
    if isinstance(g, LineString):
        return struct.pack("<BI", 1, _LINESTRING) + _ring_bytes(g.coords)
    if isinstance(g, Polygon):
        rings = g.rings
        out = [struct.pack("<BII", 1, _POLYGON, len(rings))]
        out.extend(_ring_bytes(r) for r in rings)
        return b"".join(out)
    if isinstance(g, (MultiPoint, MultiLineString, MultiPolygon)):
        code = {
            MultiPoint: _MULTIPOINT,
            MultiLineString: _MULTILINESTRING,
            MultiPolygon: _MULTIPOLYGON,
        }[type(g)]
        out = [struct.pack("<BII", 1, code, len(g.parts))]
        out.extend(to_wkb(p) for p in g.parts)
        return b"".join(out)
    raise TypeError(f"cannot WKB-encode {type(g).__name__}")


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, fmt: str):
        vals = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += struct.calcsize(fmt)
        return vals

    def coords(self, endian: str, n: int, ndim: int) -> np.ndarray:
        a = np.frombuffer(
            self.data, dtype=f"{endian}f8", count=ndim * n, offset=self.pos
        ).reshape(n, ndim)
        self.pos += 8 * ndim * n
        return a[:, :2].astype(np.float64)  # extra Z/M ordinates dropped


# EWKB (PostGIS) flag bits on the type word
_EWKB_Z = 0x80000000
_EWKB_M = 0x40000000
_EWKB_SRID = 0x20000000


def _read_geom(r: _Reader) -> Geometry:
    (byte_order,) = r.read("<B")
    endian = "<" if byte_order == 1 else ">"
    (raw_type,) = r.read(f"{endian}I")
    ndim = 2
    if raw_type & (_EWKB_Z | _EWKB_M | _EWKB_SRID):  # PostGIS EWKB
        if raw_type & _EWKB_Z:
            ndim += 1
        if raw_type & _EWKB_M:
            ndim += 1
        if raw_type & _EWKB_SRID:
            r.read(f"{endian}I")  # SRID payload, not modeled
        type_code = raw_type & 0x0FFFFFFF
    else:  # ISO WKB: Z=+1000, M=+2000, ZM=+3000
        type_code = raw_type % 1000
        flavor = raw_type // 1000
        if flavor in (1, 2):
            ndim = 3
        elif flavor == 3:
            ndim = 4
        elif flavor != 0:
            raise ValueError(f"unsupported WKB geometry type {raw_type}")
    if type_code == _POINT:
        vals = r.read(f"{endian}{'d' * ndim}")
        return Point(vals[0], vals[1])
    if type_code == _LINESTRING:
        (n,) = r.read(f"{endian}I")
        return LineString(r.coords(endian, n, ndim))
    if type_code == _POLYGON:
        (nrings,) = r.read(f"{endian}I")
        rings = []
        for _ in range(nrings):
            (n,) = r.read(f"{endian}I")
            rings.append(r.coords(endian, n, ndim))
        return Polygon(rings[0], tuple(rings[1:]))
    if type_code in (_MULTIPOINT, _MULTILINESTRING, _MULTIPOLYGON):
        (nparts,) = r.read(f"{endian}I")
        parts = tuple(_read_geom(r) for _ in range(nparts))
        cls = {
            _MULTIPOINT: MultiPoint,
            _MULTILINESTRING: MultiLineString,
            _MULTIPOLYGON: MultiPolygon,
        }[type_code]
        return cls(parts)
    raise ValueError(f"unsupported WKB geometry type {raw_type}")


def from_wkb(data: bytes) -> Geometry:
    """Parse ISO WKB or PostGIS EWKB (either endianness).

    SRID payloads are skipped and Z/M ordinates dropped — the framework's
    geometry model is 2D lon/lat.
    """
    return _read_geom(_Reader(bytes(data)))


# -- batch codec --------------------------------------------------------------
#
# Column-level encode/decode with the same (buf, offsets) contract as
# twkb.to_twkb_batch, used by the lossless Arrow geometry mapping
# (io/arrow.py). WKB coordinates are raw little-endian f8 so the round trip
# is bit-exact — unlike TWKB's fixed-point quantization — matching the
# reference's full-precision double storage
# (geomesa-fs-storage/.../parquet/io/SimpleFeatureWriteSupport.scala role).
# The per-geometry coordinate payload is written with one bulk ``tobytes()``
# per part, so the Python loop is per-part, not per-vertex.

_EMPTY_POINT = struct.pack("<BIdd", 1, _POINT, float("nan"), float("nan"))


def to_wkb_batch(geoms) -> tuple[np.ndarray, np.ndarray]:
    """Encode a column of geometries → (buf uint8 array, offsets (n+1,)
    int64). ``None`` slots encode as a NaN-coordinate point (the column stays
    non-null; :func:`from_wkb_batch` restores ``None``)."""
    geoms = list(geoms)
    n = len(geoms)
    offsets = np.zeros(n + 1, dtype=np.int64)
    chunks: list[bytes] = []
    total = 0
    for i, g in enumerate(geoms):
        b = _EMPTY_POINT if g is None else to_wkb(g)
        chunks.append(b)
        total += len(b)
        offsets[i + 1] = total
    buf = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    return buf, offsets


def from_wkb_batch(blobs) -> np.ndarray:
    """Decode a column of WKB blobs → object array of geometries.

    All-NaN points — the conventional ``POINT EMPTY`` WKB encoding, and what
    :func:`to_wkb_batch` writes for ``None`` slots — decode to ``None``. A
    point with ONE NaN ordinate is kept as-is (it is malformed data, not an
    empty sentinel)."""
    blobs = list(blobs)
    out = np.empty(len(blobs), dtype=object)
    for i, b in enumerate(blobs):
        if b is None:
            out[i] = None
            continue
        g = from_wkb(b)
        if isinstance(g, Point) and np.isnan(g.x) and np.isnan(g.y):
            g = None
        out[i] = g
    return out
