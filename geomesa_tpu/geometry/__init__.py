"""Geometry value model, WKT codec, and spatial predicates (JTS role)."""

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    box,
)
from geomesa_tpu.geometry.wkt import from_wkt, to_wkt

__all__ = [
    "Geometry",
    "Point",
    "LineString",
    "Polygon",
    "MultiPoint",
    "MultiLineString",
    "MultiPolygon",
    "box",
    "from_wkt",
    "to_wkt",
]
