"""Tiny Well-Known Binary (TWKB) codec: zigzag-varint delta coordinates.

Role parity: ``geomesa-feature-common/.../serialization/TwkbSerialization.scala``
(652 LoC — SURVEY.md §2.4): the reference's compact geometry wire format for
row values. Coordinates are scaled to ``10^precision`` fixed-point ints and
delta-encoded as zigzag varints, so tracks and dense rings cost a few bytes
per vertex instead of 16. Format follows the public TWKB spec subset the
reference uses: type-and-precision byte, metadata byte (only the ``empty``
flag here), then counts + deltas.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)

__all__ = ["to_twkb", "from_twkb", "from_twkb_batch"]

_TYPES = {
    Point: 1,
    LineString: 2,
    Polygon: 3,
    MultiPoint: 4,
    MultiLineString: 5,
    MultiPolygon: 6,
}
_EMPTY_FLAG = 0x10


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _write_varint(out: bytearray, v: int) -> None:
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def varint(self) -> int:
        shift = 0
        out = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                if out >= 1 << 63:  # interpret as 64-bit two's complement
                    out -= 1 << 64
                return out
            shift += 7
            if shift > 63:
                raise ValueError("varint too long")

    def signed(self) -> int:
        return _unzigzag(self.varint())


def _emit_coords(out: bytearray, coords: np.ndarray, scale: float, last: list[int]):
    q = np.round(coords * scale).astype(np.int64)
    for x, y in q:
        _write_varint(out, _zigzag(int(x) - last[0]))
        _write_varint(out, _zigzag(int(y) - last[1]))
        last[0], last[1] = int(x), int(y)


def _read_coords(r: _Reader, n: int, scale: float, last: list[int]) -> np.ndarray:
    out = np.empty((n, 2), dtype=np.float64)
    for i in range(n):
        last[0] += r.signed()
        last[1] += r.signed()
        out[i, 0] = last[0] / scale
        out[i, 1] = last[1] / scale
    return out


def to_twkb(g: Geometry | None, precision: int = 7) -> bytes:
    """Serialize; ``precision`` = decimal digits kept (reference default 7 ≈
    centimeter resolution in degrees). ``None`` encodes as empty point."""
    if not -8 <= precision <= 7:
        # zigzag(precision) must fit the 4-bit nibble of the type byte
        raise ValueError("precision must be in [-8, 7]")
    out = bytearray()
    if g is None:
        out.append(1 | (_zigzag(precision) << 4))
        out.append(_EMPTY_FLAG)
        return bytes(out)
    t = _TYPES[type(g)]
    out.append(t | (_zigzag(precision) << 4))
    out.append(0)  # metadata: no bbox/size/ids/extended
    scale = 10.0**precision
    last = [0, 0]
    if isinstance(g, Point):
        _emit_coords(out, np.array([[g.x, g.y]]), scale, last)
    elif isinstance(g, LineString):
        _write_varint(out, len(g.coords))
        _emit_coords(out, g.coords, scale, last)
    elif isinstance(g, Polygon):
        rings = g.rings
        _write_varint(out, len(rings))
        for ring in rings:
            _write_varint(out, len(ring))
            _emit_coords(out, ring, scale, last)
    elif isinstance(g, MultiPoint):
        _write_varint(out, len(g.parts))
        for p in g.parts:
            _emit_coords(out, np.array([[p.x, p.y]]), scale, last)
    elif isinstance(g, MultiLineString):
        _write_varint(out, len(g.parts))
        for ls in g.parts:
            _write_varint(out, len(ls.coords))
            _emit_coords(out, ls.coords, scale, last)
    elif isinstance(g, MultiPolygon):
        _write_varint(out, len(g.parts))
        for poly in g.parts:
            rings = poly.rings
            _write_varint(out, len(rings))
            for ring in rings:
                _write_varint(out, len(ring))
                _emit_coords(out, ring, scale, last)
    else:
        raise TypeError(f"cannot TWKB-encode {type(g).__name__}")
    return bytes(out)


def from_twkb(data: bytes) -> Geometry | None:
    """Deserialize a TWKB buffer produced by :func:`to_twkb`."""
    r = _Reader(data)
    head = r.data[r.pos]
    r.pos += 1
    t = head & 0x0F
    precision = _unzigzag(head >> 4)
    meta = r.data[r.pos]
    r.pos += 1
    if meta & _EMPTY_FLAG:
        return None
    scale = 10.0**precision
    last = [0, 0]
    if t == 1:
        c = _read_coords(r, 1, scale, last)
        return Point(c[0, 0], c[0, 1])
    if t == 2:
        return LineString(_read_coords(r, r.varint(), scale, last))
    if t == 3:
        nrings = r.varint()
        rings = [_read_coords(r, r.varint(), scale, last) for _ in range(nrings)]
        return Polygon(rings[0], holes=tuple(rings[1:]))
    if t == 4:
        n = r.varint()
        pts = [_read_coords(r, 1, scale, last) for _ in range(n)]
        return MultiPoint([Point(c[0, 0], c[0, 1]) for c in pts])
    if t == 5:
        n = r.varint()
        return MultiLineString(
            [LineString(_read_coords(r, r.varint(), scale, last)) for _ in range(n)]
        )
    if t == 6:
        n = r.varint()
        polys = []
        for _ in range(n):
            nrings = r.varint()
            rings = [_read_coords(r, r.varint(), scale, last) for _ in range(nrings)]
            polys.append(Polygon(rings[0], holes=tuple(rings[1:])))
        return MultiPolygon(polys)
    raise ValueError(f"unknown TWKB type {t}")


def from_twkb_batch(blobs) -> np.ndarray:
    """Decode a column of TWKB blobs → object array of geometries (None for
    empty/null slots).

    Fast path: one native C++ pass over the concatenated buffer
    (``native/twkb.cpp``) producing flat count/coord arrays, reassembled here
    with numpy slicing; falls back to per-blob :func:`from_twkb`.
    """
    blobs = list(blobs)
    n = len(blobs)
    out = np.empty(n, dtype=object)
    if n == 0:
        return out
    from geomesa_tpu import native

    decoded = None
    # only pay the concat + offsets build when the fast path can run
    if all(b is not None for b in blobs) and native._twkb_lib() is not None:
        offsets = np.zeros(n + 1, dtype=np.int64)
        for i, b in enumerate(blobs):
            offsets[i + 1] = offsets[i] + len(b)
        decoded = native.twkb_decode_batch(b"".join(blobs), offsets)
    if decoded is None:
        for i, b in enumerate(blobs):
            out[i] = None if b is None else from_twkb(b)
        return out

    types, gpc, npolys, prc, psz, coords = decoded
    # prefix sums: where each geometry's parts/polys/coords start
    part_starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(gpc, out=part_starts[1:])
    poly_starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(npolys, out=poly_starts[1:])
    coord_of_part = np.zeros(len(psz) + 1, dtype=np.int64)
    np.cumsum(psz, out=coord_of_part[1:])

    for i in range(n):
        t = int(types[i])
        p0 = int(part_starts[i])
        if t == 0:
            out[i] = None
            continue
        c0 = int(coord_of_part[p0])
        # slices are COPIED: a retained geometry must not pin the whole
        # column-wide coords buffer
        if t == 1:
            out[i] = Point(coords[c0, 0], coords[c0, 1])
        elif t == 2:
            out[i] = LineString(coords[c0 : int(coord_of_part[p0 + 1])].copy())
        elif t == 3:
            nr = int(gpc[i])
            rings = [
                coords[int(coord_of_part[p0 + j]) : int(coord_of_part[p0 + j + 1])].copy()
                for j in range(nr)
            ]
            out[i] = Polygon(rings[0], holes=tuple(rings[1:]))
        elif t == 4:
            k = int(gpc[i])
            out[i] = MultiPoint(
                [
                    Point(coords[int(coord_of_part[p0 + j]), 0],
                          coords[int(coord_of_part[p0 + j]), 1])
                    for j in range(k)
                ]
            )
        elif t == 5:
            k = int(gpc[i])
            out[i] = MultiLineString(
                [
                    LineString(
                        coords[int(coord_of_part[p0 + j]) : int(coord_of_part[p0 + j + 1])].copy()
                    )
                    for j in range(k)
                ]
            )
        elif t == 6:
            polys = []
            part = p0
            for pj in range(int(npolys[i])):
                nr = int(prc[int(poly_starts[i]) + pj])
                rings = [
                    coords[int(coord_of_part[part + j]) : int(coord_of_part[part + j + 1])].copy()
                    for j in range(nr)
                ]
                part += nr
                polys.append(Polygon(rings[0], holes=tuple(rings[1:])))
            out[i] = MultiPolygon(polys)
        else:
            raise ValueError(f"unknown TWKB type {t}")
    return out


def to_twkb_batch(geoms, precision: int = 7):
    """Encode a column of geometries in one native pass →
    (buf uint8 array, offsets (n+1,) int64), or None when the native
    library is unavailable (callers fall back to per-geometry
    :func:`to_twkb`). Blob ``i`` is ``buf[offsets[i]:offsets[i+1]]``."""
    if not -8 <= precision <= 7:
        # zigzag(precision) must fit the 4-bit nibble of the type byte
        raise ValueError("precision must be in [-8, 7]")
    from geomesa_tpu import native

    if native._twkb_lib() is None:
        return None
    geoms = list(geoms)
    n = len(geoms)
    types = np.zeros(n, dtype=np.int8)
    gpc = np.zeros(n, dtype=np.int32)
    npolys = np.zeros(n, dtype=np.int32)
    prc: list[int] = []
    psz: list[int] = []
    chunks: list[np.ndarray] = []
    for i, g in enumerate(geoms):
        if g is None:
            continue
        t = _TYPES[type(g)]
        types[i] = t
        if t == 1:
            gpc[i] = 1
            psz.append(1)
            chunks.append(np.array([[g.x, g.y]]))
        elif t == 2:
            gpc[i] = 1
            psz.append(len(g.coords))
            chunks.append(g.coords)
        elif t == 3:
            rings = g.rings
            gpc[i] = len(rings)
            npolys[i] = 1
            prc.append(len(rings))
            for ring in rings:
                psz.append(len(ring))
                chunks.append(ring)
        elif t == 4:
            gpc[i] = len(g.parts)
            for p in g.parts:
                psz.append(1)
                chunks.append(np.array([[p.x, p.y]]))
        elif t == 5:
            gpc[i] = len(g.parts)
            for ls in g.parts:
                psz.append(len(ls.coords))
                chunks.append(ls.coords)
        else:  # t == 6
            npolys[i] = len(g.parts)
            parts = 0
            for poly in g.parts:
                rings = poly.rings
                prc.append(len(rings))
                parts += len(rings)
                for ring in rings:
                    psz.append(len(ring))
                    chunks.append(ring)
            gpc[i] = parts
    coords = (
        np.concatenate([np.asarray(c, dtype=np.float64) for c in chunks])
        if chunks
        else np.zeros((0, 2))
    )
    return native.twkb_encode_batch(
        types, gpc, npolys,
        np.asarray(prc, dtype=np.int32), np.asarray(psz, dtype=np.int32),
        coords, precision,
    )
