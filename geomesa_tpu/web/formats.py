"""Shared result-set serialization for the web layer.

One formatter feeds BOTH the native REST query endpoint and the WFS
GetFeature operation, so wire formats (GeoJSON/GML/Arrow/Avro/BIN/CSV/
Leaflet) stay consistent — a namespace or id-handling fix lands once.
"""

from __future__ import annotations

__all__ = ["format_table", "UnknownFormat"]


class UnknownFormat(ValueError):
    pass


def format_table(table, fmt: str):
    """FeatureTable → (payload, content_type) for wire format ``fmt``.

    ``payload`` is bytes or a JSON-able dict (the responder encodes dicts).
    Raises :class:`UnknownFormat` for unrecognized names."""
    if fmt == "geojson":
        from geomesa_tpu.geometry.geojson import table_to_feature_collection

        return table_to_feature_collection(table), "application/geo+json"
    if fmt == "arrow":
        from geomesa_tpu.io.arrow import to_ipc_bytes

        return to_ipc_bytes(table), "application/vnd.apache.arrow.stream"
    if fmt == "bin":
        from geomesa_tpu.store.reduce import bin_encode

        return bin_encode(table, {}), "application/octet-stream"
    if fmt == "avro":
        import io as _io

        from geomesa_tpu.io.avro import write_avro

        buf = _io.BytesIO()
        write_avro(table, buf)
        return buf.getvalue(), "application/avro"
    if fmt == "gml":
        from geomesa_tpu.io.gml import to_gml

        return to_gml(table), "application/gml+xml"
    if fmt == "csv":
        # the analytics CSV endpoint role (geomesa-web-data)
        import csv as _csv
        import io as _io

        buf = _io.StringIO()
        w = _csv.writer(buf)
        # header from the RESULT schema (projection-aware), not the first
        # record — zero-row pages must keep the same columns
        cols = ["__fid__"] + [
            a.name for a in table.sft.attributes if a.name in table.columns
        ]
        w.writerow(cols)
        recs = [table.record(i) for i in range(len(table))]
        for fid, rec in zip(table.fids, recs):
            w.writerow([str(fid)] + [str(rec[c]) for c in cols[1:]])
        return buf.getvalue().encode("utf-8"), "text/csv"
    if fmt == "leaflet":
        from geomesa_tpu.jupyter import map_html

        return map_html(table).encode("utf-8"), "text/html"
    raise UnknownFormat(fmt)
