"""OGC WFS 2.0 KVP protocol endpoints (the GeoServer-plugin role).

Role parity: the reference serves standard clients through GeoServer WFS
modules (``geomesa-accumulo/geomesa-accumulo-gs-plugin/`` — SURVEY.md §2.19;
VERDICT r2 missing #4). Here the protocol surface is served directly by the
framework's web layer: ``GET /wfs?service=WFS&request=...`` speaks the WFS
2.0 key-value-pair binding —

- ``GetCapabilities`` — service + operations + feature-type listing
- ``DescribeFeatureType`` — per-type XSD (attribute names/types)
- ``GetFeature`` — ``typeNames``/``bbox``/``cql_filter``/``count``/
  ``startIndex``/``sortBy``/``resultType=hits``; GML 3.1 out by default,
  ``outputFormat=application/json`` for GeoJSON

Filters ride the SAME planner/CQL machinery as the native API (``bbox=`` is
folded into the CQL as a BBOX conjunct), so index planning, visibility
auths, paging, and device execution all apply unchanged. Transactions
(WFS-T Insert/Update/Delete) are served by the REST feature mutations
(``POST/PUT/DELETE /api/schemas/{type}/features``) with the same replace
semantics.
"""

from __future__ import annotations

from xml.sax.saxutils import escape

from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.sft import AttributeType, FeatureType

__all__ = ["handle_wfs"]


def _attr(s: str) -> str:
    # attribute-context escape: saxutils.escape() alone leaves '"' intact,
    # letting a name containing a quote break out of the attribute value
    return escape(str(s), {'"': "&quot;"})

_XSD_TYPES = {
    AttributeType.STRING: "xsd:string",
    AttributeType.INT: "xsd:int",
    AttributeType.LONG: "xsd:long",
    AttributeType.FLOAT: "xsd:float",
    AttributeType.DOUBLE: "xsd:double",
    AttributeType.BOOLEAN: "xsd:boolean",
    AttributeType.DATE: "xsd:dateTime",
    AttributeType.UUID: "xsd:string",
    AttributeType.BYTES: "xsd:base64Binary",
}
_GML_GEOM = {
    AttributeType.POINT: "gml:PointPropertyType",
    AttributeType.LINESTRING: "gml:CurvePropertyType",
    AttributeType.POLYGON: "gml:SurfacePropertyType",
    AttributeType.MULTIPOINT: "gml:MultiPointPropertyType",
    AttributeType.MULTILINESTRING: "gml:MultiCurvePropertyType",
    AttributeType.MULTIPOLYGON: "gml:MultiSurfacePropertyType",
    AttributeType.GEOMETRY: "gml:GeometryPropertyType",
}


class WfsError(ValueError):
    """OGC ExceptionReport payload (maps to HTTP 400)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code

    def to_xml(self) -> str:
        return (
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            '<ows:ExceptionReport xmlns:ows="http://www.opengis.net/ows/1.1" '
            'version="2.0.0">'
            f'<ows:Exception exceptionCode="{_attr(self.code)}">'
            f"<ows:ExceptionText>{escape(str(self))}</ows:ExceptionText>"
            "</ows:Exception></ows:ExceptionReport>"
        )


def handle_wfs(store, params: dict, auths=None):
    """Dispatch one WFS KVP request → (status, body bytes/str, content type).

    ``params`` keys are matched case-insensitively (KVP requirement)."""
    p = {k.lower(): v for k, v in params.items()}
    service = p.get("service", "WFS").upper()
    if service != "WFS":
        raise WfsError("InvalidParameterValue", f"unknown service {service!r}")
    request = p.get("request", "")
    try:
        if request.lower() == "getcapabilities":
            return 200, _capabilities(store, auths), "text/xml"
        if request.lower() == "describefeaturetype":
            return 200, _describe(store, p), "text/xml"
        if request.lower() == "getfeature":
            return _get_feature(store, p, auths)
    except WfsError:
        raise
    except KeyError as e:
        raise WfsError("InvalidParameterValue", f"unknown type {e}") from e
    raise WfsError(
        "OperationNotSupported",
        f"request {request!r} (supported: GetCapabilities, "
        "DescribeFeatureType, GetFeature; transactions via the REST "
        "feature endpoints)",
    )


def _capabilities(store, auths=None) -> str:
    types = []
    for name in store.list_schemas():
        sft = store.get_schema(name)
        bounds = (-180.0, -90.0, 180.0, 90.0)
        stats_fn = getattr(store, "stats_bounds", None)
        # store-wide sketch bounds would leak hidden-feature LOCATIONS to a
        # restricted caller (the same leak class the stats endpoints guard
        # against) — visibility-labeled schemas advertise the world bbox to
        # restricted callers instead
        restricted = auths is not None and (
            (sft.user_data or {}).get("geomesa.vis.field")
        )
        if stats_fn is not None and sft.geom_field is not None and not restricted:
            try:
                lo, hi = stats_fn(name, sft.geom_field)
                # geometry min/max come back as (x, y) corner pairs
                bounds = (lo[0], lo[1], hi[0], hi[1])
            except Exception:  # noqa: BLE001 — capabilities must not 500
                pass
        types.append(
            "<FeatureType>"
            f"<Name>{escape(name)}</Name>"
            f"<Title>{escape(name)}</Title>"
            "<DefaultCRS>urn:ogc:def:crs:EPSG::4326</DefaultCRS>"
            '<ows:WGS84BoundingBox xmlns:ows="http://www.opengis.net/ows/1.1">'
            f"<ows:LowerCorner>{bounds[0]:.8g} {bounds[1]:.8g}</ows:LowerCorner>"
            f"<ows:UpperCorner>{bounds[2]:.8g} {bounds[3]:.8g}</ows:UpperCorner>"
            "</ows:WGS84BoundingBox>"
            "</FeatureType>"
        )
    ops = "".join(
        f'<ows:Operation xmlns:ows="http://www.opengis.net/ows/1.1" '
        f'name="{op}"/>'
        for op in ("GetCapabilities", "DescribeFeatureType", "GetFeature")
    )
    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<wfs:WFS_Capabilities xmlns:wfs="http://www.opengis.net/wfs/2.0" '
        'version="2.0.0">'
        f"<ows:OperationsMetadata "
        f'xmlns:ows="http://www.opengis.net/ows/1.1">{ops}'
        "</ows:OperationsMetadata>"
        f"<FeatureTypeList>{''.join(types)}</FeatureTypeList>"
        "</wfs:WFS_Capabilities>"
    )


def _describe(store, p: dict) -> str:
    names = [
        n for n in (p.get("typenames") or p.get("typename") or "").split(",")
        if n
    ] or store.list_schemas()
    parts = []
    for name in names:
        sft: FeatureType = store.get_schema(name)
        elems = []
        for a in sft.attributes:
            t = (
                _GML_GEOM.get(a.type)
                or _XSD_TYPES.get(a.type, "xsd:string")
            )
            elems.append(
                f'<xsd:element name="{_attr(a.name)}" type="{t}" '
                'minOccurs="0" nillable="true"/>'
            )
        parts.append(
            f'<xsd:complexType name="{_attr(name)}Type">'
            "<xsd:complexContent>"
            '<xsd:extension base="gml:AbstractFeatureType">'
            f"<xsd:sequence>{''.join(elems)}</xsd:sequence>"
            "</xsd:extension></xsd:complexContent></xsd:complexType>"
            f'<xsd:element name="{_attr(name)}" type="{_attr(name)}Type" '
            'substitutionGroup="gml:AbstractFeature"/>'
        )
    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<xsd:schema xmlns:xsd="http://www.w3.org/2001/XMLSchema" '
        'xmlns:gml="http://www.opengis.net/gml" '
        'elementFormDefault="qualified">'
        f"{''.join(parts)}</xsd:schema>"
    )


def _get_feature(store, p: dict, auths):
    names = p.get("typenames") or p.get("typename")
    if not names:
        raise WfsError("MissingParameterValue", "typeNames is required")
    name = names.split(",")[0]  # one type per request (common profile)
    filters = []
    if p.get("cql_filter"):
        filters.append(p["cql_filter"])
    if p.get("bbox"):
        parts = p["bbox"].split(",")
        if len(parts) not in (4, 5):  # optional trailing CRS token
            raise WfsError("InvalidParameterValue", "bbox needs 4 numbers")
        try:
            x1, y1, x2, y2 = (float(v) for v in parts[:4])
        except ValueError as e:
            raise WfsError("InvalidParameterValue", f"bad bbox: {e}") from e
        if len(parts) == 5 and parts[4].strip():
            # trailing CRS token: the bbox arrives in that CRS. The WFS 2.0
            # urn form of EPSG:4326 mandates LAT/LON axis order — swap
            # before transforming. Transform all FOUR corners: projected
            # axes (UTM meridian convergence) do not stay axis-aligned in
            # lon/lat, so a two-corner transform under-covers the box.
            from geomesa_tpu.utils.crs import transform_coords

            token = parts[4].strip()
            low = token.lower()
            if low.startswith("urn:") and low.endswith((":4326", ":epsg::4326")):
                x1, y1, x2, y2 = y1, x1, y2, x2
                token = "EPSG:4326"
            try:
                cx, cy = transform_coords(
                    [x1, x2, x1, x2], [y1, y1, y2, y2], token, "EPSG:4326"
                )
            except ValueError as e:
                raise WfsError("InvalidParameterValue", str(e)) from None
            x1, x2 = float(cx.min()), float(cx.max())
            y1, y2 = float(cy.min()), float(cy.max())
        sft = store.get_schema(name)
        if sft.geom_field is None:
            raise WfsError("InvalidParameterValue", f"{name} has no geometry")
        filters.append(f"BBOX({sft.geom_field}, {x1}, {y1}, {x2}, {y2})")
    if p.get("featureid") or p.get("resourceid"):
        fids = (p.get("featureid") or p.get("resourceid")).split(",")
        quoted = ",".join("'" + f.replace("'", "''") + "'" for f in fids)
        filters.append(f"IN ({quoted})")
    cql = " AND ".join(f"({f})" for f in filters) if filters else None
    if cql is not None:
        # validate NOW so a malformed cql_filter is a protocol error
        # (ExceptionReport), not a generic JSON 400 from the dispatcher
        from geomesa_tpu.filter.cql import parse as _parse_cql

        try:
            _parse_cql(cql)
        except ValueError as e:
            raise WfsError("InvalidParameterValue", f"bad filter: {e}") from e

    def _int_param(key):
        raw = p.get(key)
        if not raw:
            return None
        try:
            v = int(raw)
        except ValueError:
            raise WfsError(
                "InvalidParameterValue", f"{key} must be an integer: {raw!r}"
            ) from None
        if v < 0:
            raise WfsError("InvalidParameterValue", f"{key} must be >= 0")
        return v

    count = _int_param("count")
    start = _int_param("startindex") or 0
    sort_by = None
    descending = False
    if p.get("sortby"):
        # WFS KVP forms: "attr", "attr ASC|DESC", "attr+A|+D", "attr A|D"
        token = p["sortby"].split(",")[0].strip()
        upper = token.upper()
        for suffix, desc in ((" DESC", True), ("+DESC", True), (" D", True),
                             ("+D", True), (" ASC", False), ("+ASC", False),
                             (" A", False), ("+A", False)):
            if upper.endswith(suffix):
                descending = desc
                token = token[: -len(suffix)].strip("+ ")
                break
        sort_by = token

    if p.get("resulttype", "").lower() == "hits":
        # numberMatched is the TOTAL match count — paging params do not
        # apply (WFS 2.0); prefer the stats fast path over materializing.
        # The fast path is safe unless the SCHEMA labels features AND the
        # caller is restricted (the _restricted_auths gate): store-wide
        # counts would then include rows the caller cannot see.
        n = None
        sft = store.get_schema(name)
        restricted = auths is not None and (
            (sft.user_data or {}).get("geomesa.vis.field")
        )
        stats_count = getattr(store, "stats_count", None)
        if stats_count is not None and not restricted:
            try:
                n = int(stats_count(name, cql, exact=True))
            except Exception:  # noqa: BLE001 — fall back to the query path
                n = None
        if n is None:
            n = store.query(name, Query(filter=cql, auths=auths)).count
        body = (
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            '<wfs:FeatureCollection xmlns:wfs="http://www.opengis.net/wfs/2.0" '
            f'numberMatched="{n}" numberReturned="0"/>'
        )
        return 200, body, "text/xml"

    hints = {}
    if p.get("srsname"):
        # output reprojection (the Reprojection.scala role): validate the
        # code NOW so a bogus srsName is a protocol error, then ride the
        # query pipeline's crs hint (store/reduce.py applies it)
        from geomesa_tpu.utils.crs import get_crs

        try:
            get_crs(p["srsname"])
        except ValueError as e:
            raise WfsError("InvalidParameterValue", str(e)) from None
        hints["crs"] = p["srsname"]
    q = Query(
        filter=cql, limit=count, start_index=start,
        sort_by=(sort_by, descending) if sort_by else None, auths=auths,
        hints=hints,
    )
    fmt = (p.get("outputformat") or "gml").lower()
    if "json" in fmt:
        wire = "geojson"
    elif fmt in ("gml", "gml3", "gml32", "text/xml", "application/xml",
                 "application/gml+xml", "text/xml; subtype=gml/3.1.1",
                 "text/xml; subtype=gml/3.2"):
        wire = "gml"
    else:
        # a client asking for an unsupported format must get a protocol
        # error, never a silently different format
        raise WfsError(
            "InvalidParameterValue",
            f"unsupported outputFormat {p.get('outputformat')!r} "
            "(supported: GML 3, application/json)",
        )
    r = store.query(name, q)
    from geomesa_tpu.web.formats import format_table

    payload, ctype = format_table(r.table, wire)
    return 200, payload, ctype
