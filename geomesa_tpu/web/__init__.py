"""REST API over a datastore."""

from geomesa_tpu.web.app import GeoMesaApp, serve

__all__ = ["GeoMesaApp", "serve"]
