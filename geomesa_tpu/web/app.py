"""REST endpoints over a datastore — stdlib WSGI, no framework.

Role parity: ``geomesa-web`` (SURVEY.md §2.19) — the reference exposes
Scalatra servlets for stats (``GeoMesaStatsEndpoint.scala``), query audit
(``QueryAuditEndpoint``), datastore management (``DataStoreServlet``) and a
GeoJSON REST API (``geomesa-geojson-rest``). Routes:

    GET    /api/version
    GET    /api/schemas                          list type names
    POST   /api/schemas                          {"name": ..., "spec": ...}
    POST   /api/sql                              {"q": "SELECT ..."} (caller
                                                 auths scope every row read)
    GET    /api/schemas/{name}                   spec + row count
    PATCH  /api/schemas/{name}                   {"add"|"keywords"|"rename_to"}
    DELETE /api/schemas/{name}
    POST   /api/schemas/{name}/features          GeoJSON FeatureCollection in
    PUT    /api/schemas/{name}/features          replace-by-id (WFS-T Update)
    DELETE /api/schemas/{name}/features?fids=a,b (WFS-T Delete)
    GET    /api/schemas/{name}/query?cql=&limit=&startIndex=&format=geojson|arrow|bin|avro|gml|csv|leaflet
    POST   /api/schemas/{name}/count-many        batched loose counts
    POST   /api/schemas/{name}/select-many       batched row retrieval (whole
                                                 batch in two device dispatches,
                                                 per-query Arrow IPC back)
    POST   /api/schemas/{name}/density-many      batched shared-viewport heatmaps
    POST   /api/schemas/{name}/aggregate         batched grouped aggregation
    GET    /api/schemas/{name}/stats?stats=Count();MinMax(a)   sketch stats
    GET    /api/schemas/{name}/stats/count?cql=&exact=
    GET    /api/schemas/{name}/stats/bounds?attr=
    GET    /api/schemas/{name}/stats/topk?attr=&k=
    GET    /api/schemas/{name}/density?cql=&bbox=&width=&height=
    GET    /api/audit?typeName=                  query audit records
    GET    /api/obs/flight?limit=&tenant=&type=&anomalies=1
                                                 query-audit flight recorder
    GET    /api/obs/costs?limit=&member=         per-plan-shape cost profiles
                                                 (+ per-member aggregates)
    GET    /api/obs/tenants?limit=               per-tenant usage accounting
    GET    /api/obs/audit?limit=                 continuous correctness auditor
    GET    /api/obs/lens?limit=&window=&type=    retained per-plan-signature
                                                 latency history + exemplars
                                                 (+ regression sentinel state)
    GET    /api/obs/lens?trace=<id>              resolve one exemplar trace_id
                                                 to its stitched span tree
    GET    /api/obs/stream?limit=&window=&topic= standing-query scale report:
                                                 subscriptions ranked by cost
                                                 share + delivery p99, capacity
                                                 section, backlog sentinel
    GET    /api/obs/stream?trace=<id>            resolve one delivery exemplar
                                                 to its stitched span tree
    GET    /api/obs/fusion?limit=                host-roundtrip fusion report
                                                 (signatures ranked by host-
                                                 choreography share)
    GET    /api/obs/ledger?format=json           raw roundtrip-ledger rollup
                                                 in the stable reconcile-
                                                 export schema (tpusync
                                                 --reconcile input)
    GET    /api/obs/shards                       shard map + live migration
                                                 states + migration counters
    GET    /api/metrics                          metrics snapshot (+ device
                                                 HBM residency section)
    GET    /api/metrics?format=prometheus       Prometheus text exposition
    GET    /wfs?service=WFS&request=...          OGC WFS 2.0 KVP binding
    GET    /wms?service=WMS&request=...          OGC WMS 1.3.0 (GetMap tiles)
    POST   /api/lease/{acquire|renew|release}    cross-host expiring leases
                                                 (ZK DistributedLocking role)
    POST   /api/journal/{topic}/publish          cross-host stream transport
    GET    /api/journal/{topic}/{poll|tpoll|end} (Kafka-broker role; tpoll
                                                 supports ?cursor= byte tail)
    POST   /subjects/{s}/versions                Confluent schema registry
    GET    /subjects/{s}/versions, /schemas/ids/{id}   (service half)
"""

from __future__ import annotations

import json
import re
from urllib.parse import parse_qs

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.obs import trace as _obstrace
from geomesa_tpu.obs import usage as _usage
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.utils.timeouts import QueryTimeout as _QueryTimeout

__all__ = ["GeoMesaApp", "serve"]


class _HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


_STATUS = {
    200: "200 OK",
    201: "201 Created",
    204: "204 No Content",
    400: "400 Bad Request",
    403: "403 Forbidden",
    404: "404 Not Found",
    405: "405 Method Not Allowed",
    429: "429 Too Many Requests",
    500: "500 Internal Server Error",
    504: "504 Gateway Timeout",
}


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


_ADMISSION_ROUTES = frozenset({
    # query-serving work subject to admission control: everything that
    # can reach a scan / device dispatch. Ops surfaces (metrics, obs,
    # audit, version), schema CRUD, writes, and the coordination planes
    # (lease/journal/registry) are exempt — shedding a metrics scrape
    # would blind the operator to the shed itself.
    "_query", "_sql", "_count_many", "_select_many", "_density_many",
    "_aggregate", "_stats", "_stats_count", "_stats_bounds",
    "_stats_topk", "_density", "_wfs", "_wms",
    # trajectory plane (docs/trajectory.md): corridor scans, track
    # aggregation, and interlink joins are all scan-class work
    "_tube_select", "_track_stats", "_link",
})


class GeoMesaApp:
    """WSGI application over one :class:`DataStore` (or merged view).

    ``admission``: a :class:`geomesa_tpu.serving.admission.
    AdmissionController` gating the query-serving routes (None = admit
    everything, the classic behavior); shed requests answer 429 +
    ``Retry-After``. ``coalesce_ms``: the request-coalescing batch
    window (None = ``GEOMESA_TPU_COALESCE_MS``, default 2 ms; <= 0
    disables) — concurrent compatible ``/query`` requests share one
    batched device dispatch (docs/serving.md).
    """

    def __init__(self, store, auth_provider=None, journal=None,
                 schema_registry=None, admission=None,
                 coalesce_ms: float | None = None):
        # auth_provider: security.auth.AuthorizationsProvider — derives the
        # caller's visibility auths from the request (None = unrestricted,
        # the single-tenant default)
        # journal: a stream.journal.JournalBus to expose over /api/journal
        # (cross-host stream transport — the Kafka-broker role; None hides
        # the routes)
        # schema_registry: a stream.confluent.SchemaRegistry to expose on
        # the Confluent REST paths (/subjects, /schemas/ids) so remote
        # producers/consumers share schema ids; None hides the routes
        from geomesa_tpu.utils.locks import LeaseService

        self.store = store
        self.auth_provider = auth_provider
        self.journal = journal
        self.schema_registry = schema_registry
        self.leases = LeaseService()
        self.admission = admission
        from geomesa_tpu.serving.coalesce import Coalescer, env_window_s

        window_s = (env_window_s() if coalesce_ms is None
                    else max(float(coalesce_ms), 0.0) / 1000.0)
        self.coalescer = (
            Coalescer(store, window_s=window_s,
                      metrics=getattr(store, "metrics", None))
            if window_s > 0 else None
        )
        self.routes = [
            # Confluent Schema Registry wire protocol (the
            # geomesa-kafka-confluent service half)
            ("POST", r"^/subjects/([^/]+)/versions$", self._registry_register),
            ("GET", r"^/subjects/([^/]+)/versions$", self._registry_versions),
            ("GET", r"^/schemas/ids/(\d+)$", self._registry_by_id),
            # cross-host coordination: named expiring leases (the ZK
            # DistributedLocking role for hosts with no shared mount)
            ("POST", r"^/api/lease/(acquire|renew|release)$", self._lease),
            # cross-host stream transport over the journal (Kafka-broker
            # role): publish + offset-addressed poll
            ("POST", r"^/api/journal/([^/]+)/publish$", self._journal_publish),
            ("GET", r"^/api/journal/([^/]+)/poll$", self._journal_poll),
            ("GET", r"^/api/journal/([^/]+)/tpoll$", self._journal_tpoll),
            ("GET", r"^/api/journal/([^/]+)/end$", self._journal_end),
            ("GET", r"^/api/version$", self._version),
            ("GET", r"^/api/schemas$", self._list_schemas),
            ("POST", r"^/api/schemas$", self._create_schema),
            ("POST", r"^/api/sql$", self._sql),
            ("GET", r"^/api/schemas/([^/]+)$", self._get_schema),
            ("PATCH", r"^/api/schemas/([^/]+)$", self._update_schema),
            ("DELETE", r"^/api/schemas/([^/]+)$", self._delete_schema),
            ("POST", r"^/api/schemas/([^/]+)/features$", self._add_features),
            ("PUT", r"^/api/schemas/([^/]+)/features$", self._update_features),
            ("DELETE", r"^/api/schemas/([^/]+)/features$", self._delete_features),
            ("GET", r"^/api/schemas/([^/]+)/query$", self._query),
            ("POST", r"^/api/schemas/([^/]+)/count-many$", self._count_many),
            ("POST", r"^/api/schemas/([^/]+)/select-many$", self._select_many),
            ("POST", r"^/api/schemas/([^/]+)/density-many$", self._density_many),
            ("POST", r"^/api/schemas/([^/]+)/aggregate$", self._aggregate),
            # trajectory plane: corridor scans + batched track aggregation
            # + two-store interlink (docs/trajectory.md § HTTP surface)
            ("POST", r"^/api/schemas/([^/]+)/tube-select$", self._tube_select),
            ("POST", r"^/api/schemas/([^/]+)/track-stats$", self._track_stats),
            ("POST", r"^/api/link$", self._link),
            ("GET", r"^/api/schemas/([^/]+)/stats$", self._stats),
            ("GET", r"^/api/schemas/([^/]+)/stats/count$", self._stats_count),
            ("GET", r"^/api/schemas/([^/]+)/stats/bounds$", self._stats_bounds),
            ("GET", r"^/api/schemas/([^/]+)/stats/topk$", self._stats_topk),
            ("GET", r"^/api/schemas/([^/]+)/density$", self._density),
            ("GET", r"^/api/audit$", self._audit),
            ("GET", r"^/api/obs/flight$", self._obs_flight),
            ("GET", r"^/api/obs/costs$", self._obs_costs),
            ("GET", r"^/api/obs/tenants$", self._obs_tenants),
            ("GET", r"^/api/obs/audit$", self._obs_audit),
            # profiling plane: retained latency history + trace exemplars,
            # the host-roundtrip fusion report (docs/observability.md
            # § Query lens & host-roundtrip ledger)
            ("GET", r"^/api/obs/lens$", self._obs_lens),
            # stream lens: per-subscription delivery histograms + the
            # standing-query scale report (docs/streaming.md § Stream lens)
            ("GET", r"^/api/obs/stream$", self._obs_stream),
            ("GET", r"^/api/obs/fusion$", self._obs_fusion),
            ("GET", r"^/api/obs/ledger$", self._obs_ledger),
            # elasticity plane: shard map + live migration states +
            # process-wide migration counters (docs/operations.md)
            ("GET", r"^/api/obs/shards$", self._obs_shards),
            ("GET", r"^/api/metrics$", self._metrics),
            # OGC WFS 2.0 KVP binding (GeoServer-plugin role, web/wfs.py)
            ("GET", r"^/wfs/?$", self._wfs),
            # OGC WMS 1.3.0 KVP binding: GetCapabilities + GetMap tiles
            ("GET", r"^/wms/?$", self._wms),
        ]

    # -- WSGI ----------------------------------------------------------------
    def __call__(self, environ, start_response):
        method = environ["REQUEST_METHOD"]
        path = environ.get("PATH_INFO", "/")
        params = {
            k: v[0] for k, v in parse_qs(environ.get("QUERY_STRING", "")).items()
        }
        # reserved keys: only the server may set them — never the client
        params.pop("__auths__", None)
        params.pop("__deadline__", None)
        params.pop("__tenant__", None)
        if self.auth_provider is not None:
            params["__auths__"] = self.auth_provider.auths(environ)
        # tenant attribution (obs.usage / docs/observability.md § Usage
        # metering): the caller's X-Geomesa-Tenant assertion (same proxy-
        # trust posture as X-Geomesa-Auths — the fronting proxy must own
        # this header), bound to the request context below so every store
        # audit record and outbound federated RPC attributes to it;
        # absent header = the default (anonymous) tenant
        tenant = (environ.get("HTTP_X_GEOMESA_TENANT") or "").strip()
        if tenant:
            params["__tenant__"] = tenant
        # deadline propagation (X-Geomesa-Deadline-Ms): the caller's
        # REMAINING budget in ms, re-anchored on this host's monotonic
        # clock — see geomesa_tpu.resilience.http / docs/resilience.md
        hdr = environ.get("HTTP_X_GEOMESA_DEADLINE_MS")
        if hdr is not None:
            from geomesa_tpu.utils.timeouts import Deadline

            try:
                params["__deadline__"] = Deadline.after_ms(float(hdr))
            except ValueError:
                return self._respond(
                    start_response, 400,
                    {"error": f"bad X-Geomesa-Deadline-Ms header: {hdr!r}"},
                    "application/json",
                )
        # trace propagation (X-Geomesa-Trace): join the remote caller's
        # trace; a sampled context force-records this request's tree and
        # returns it serialized so the caller grafts it under its RPC span
        # (the stitched federated tree — docs/observability.md)
        ctx = _obstrace.extract(environ.get("HTTP_X_GEOMESA_TRACE"))
        # per-request metrics (the servlet AggregatedMetricsFilter role):
        # counter per route pattern + total, into the store's registry so
        # /api/metrics reports request rates alongside store counters
        metrics = getattr(self.store, "metrics", None)
        if metrics is not None:
            metrics.counter("web.requests").inc()
        try:
            body = None
            if method in ("POST", "PUT", "PATCH", "DELETE"):
                length = int(environ.get("CONTENT_LENGTH") or 0)
                raw = environ["wsgi.input"].read(length) if length else b""
                body = json.loads(raw) if raw else None
            matched_path = False
            for m, pattern, handler in self.routes:
                match = re.match(pattern, path)
                if match:
                    matched_path = True
                    if m == method:
                        if (
                            self.admission is not None
                            and handler.__name__ in _ADMISSION_ROUTES
                        ):
                            # the serving plane's front gate: per-tenant
                            # token bucket + priority class; a shed
                            # answers 429 + Retry-After BEFORE any scan
                            # or device work (docs/serving.md)
                            import math

                            decision = self.admission.admit(
                                tenant or None,
                                environ.get("HTTP_X_GEOMESA_PRIORITY")
                                or "normal",
                            )
                            if not decision.admitted:
                                if metrics is not None:
                                    metrics.counter("web.shed").inc()
                                return self._respond(
                                    start_response, 429,
                                    {
                                        "error": "admission shed: tenant "
                                                 "over rate/budget",
                                        "retry_after_s": round(
                                            decision.retry_after_s, 3),
                                    },
                                    "application/json",
                                    extra_headers=[(
                                        "Retry-After",
                                        str(max(1, math.ceil(
                                            decision.retry_after_s))),
                                    )],
                                )
                        # one trace root per request: each server thread's
                        # ContextVar starts empty, so concurrent requests
                        # build disjoint span trees; the handler's store
                        # queries/serialization nest underneath
                        route = handler.__name__.lstrip("_")
                        if ctx is not None and ctx.sampled:
                            span_cm = _obstrace.propagated(
                                "http", ctx, method=method, path=path,
                                route=route)
                        else:
                            span_cm = obs.span(
                                "http", method=method, path=path, route=route)
                        from contextlib import nullcontext

                        # an unsampled incoming context must stay unsampled
                        # on OUR outbound hops too (fan-out to members):
                        # honoring the flag end to end, not just locally
                        join_cm = (
                            _obstrace.unsampled_join()
                            if ctx is not None and not ctx.sampled
                            else nullcontext()
                        )
                        with span_cm as sp, join_cm, \
                                _usage.tenant_context(tenant):
                            if (
                                ctx is not None and not ctx.sampled
                                and isinstance(sp, _obstrace.Span)
                            ):
                                # unsampled context + local tracing on: the
                                # ids still join the caller's trace (honoring
                                # the flag means not FORCING a record)
                                sp.trace_id = ctx.trace_id
                                sp.parent_id = ctx.parent_span_id
                            if metrics is not None:
                                metrics.counter(
                                    f"web.requests.{route}"
                                ).inc()
                                with metrics.timer("web.request_ms").time():
                                    status, payload, ctype = self._run_handler(
                                        handler, match.groups(), params, body
                                    )
                            else:
                                status, payload, ctype = self._run_handler(
                                    handler, match.groups(), params, body
                                )
                        extra = None
                        if ctx is not None and ctx.sampled:
                            extra = [(
                                _obstrace.TRACE_RETURN_HEADER,
                                _obstrace.serialize_subtree(sp),
                            )]
                        out = self._respond(
                            start_response, status, payload, ctype,
                            extra_headers=extra)
                        if out and out[0]:
                            # response-payload bytes attribute to the
                            # tenant (the store can't see serialization);
                            # headerless traffic accrues under the
                            # default (anonymous) tenant — egress
                            # attribution must not undercount the bulk
                            # of an unlabeled deployment's load
                            _usage.get().note_bytes_out(
                                tenant or None, len(out[0]))
                        return out
            raise _HttpError(405 if matched_path else 404,
                             "method not allowed" if matched_path else "not found")
        except _HttpError as e:
            return self._respond(
                start_response, e.status, {"error": e.message}, "application/json"
            )
        except _QueryTimeout as e:
            # a spent/blown deadline — shed before work or expired during
            # it — answers 504 so the caller's client maps it back to its
            # own QueryTimeout (the end-to-end timeout contract)
            return self._respond(
                start_response, 504, {"error": str(e)}, "application/json"
            )
        except KeyError as e:
            return self._respond(
                start_response, 404, {"error": str(e)}, "application/json"
            )
        except PermissionError as e:
            return self._respond(
                start_response, 403, {"error": str(e)}, "application/json"
            )
        except (ValueError, TypeError) as e:
            return self._respond(
                start_response, 400, {"error": str(e)}, "application/json"
            )

    def _run_handler(self, handler, groups, params, body):
        """Dispatch one matched route under the request's deadline.

        No deadline: a plain call (zero overhead). With one: work whose
        budget is already spent is shed with 504 BEFORE the handler runs
        (no scan, no device work); otherwise the handler runs under
        :func:`run_with_timeout` registered with the store's Watchdog, so
        a blown budget abandons the worker thread, counts it, and still
        answers 504 — the ThreadManagement posture applied per hop."""
        deadline = params.get("__deadline__")
        if deadline is None:
            return handler(*groups, params=params, body=body)
        from geomesa_tpu.utils.timeouts import run_with_timeout

        metrics = getattr(self.store, "metrics", None)
        rem_s = deadline.remaining_s()
        if rem_s <= 0:
            if metrics is not None:
                metrics.counter("web.deadline.shed").inc()
            raise _QueryTimeout("deadline spent before processing began")
        wd = getattr(self.store, "watchdog", None)
        token = None
        if wd is not None:
            token = wd.register(
                f"http {handler.__name__.lstrip('_')} "
                f"(deadline {rem_s * 1000:.0f}ms)")
        abandoned = False
        try:
            return run_with_timeout(
                handler, rem_s, *groups, params=params, body=body)
        except _QueryTimeout as e:
            # only count THIS request abandoned when OUR worker is the
            # one still running — a store scan that already shed/expired
            # (and counted itself) re-raises with the marker cleared
            abandoned = getattr(e, "worker_abandoned", True)
            if metrics is not None:
                metrics.counter("web.deadline.expired").inc()
            raise
        finally:
            # finally: a handler error (404/400/403) must release the
            # registration too, not leak it in the active set forever
            if token is not None:
                wd.complete(token, timed_out=abandoned)

    def _respond(self, start_response, status, payload, ctype,
                 extra_headers=None):
        if isinstance(payload, (dict, list)):
            data = json.dumps(_jsonable(payload)).encode()
        elif payload is None:
            data = b""
        else:
            data = payload
        headers = [("Content-Type", ctype), ("Content-Length", str(len(data)))]
        if extra_headers:
            headers.extend(extra_headers)
        start_response(_STATUS[status], headers)
        return [data]

    # -- handlers ------------------------------------------------------------
    def _version(self, params, body):
        import geomesa_tpu

        return 200, {"name": "geomesa-tpu", "version": geomesa_tpu.__version__}, "application/json"

    # -- cross-host coordination (no-shared-mount deployments) ---------------
    def _lease(self, op, params, body):
        """Named expiring leases (``utils.locks.LeaseService``): the
        coordinator half of ``http_lease_lock``. Always 200 — contention
        is a normal outcome (``ok: false``), not an HTTP error."""
        b = body or {}
        name = b.get("name")
        if not name or not isinstance(name, str):
            raise _HttpError(400, "body must include a lease 'name'")
        ttl = float(b.get("ttl_s", 60.0))
        if op == "acquire":
            out = self.leases.acquire(name, str(b.get("holder", "?")), ttl)
        elif op == "renew":
            out = self.leases.renew(name, str(b.get("token", "")), ttl)
        else:
            out = self.leases.release(name, str(b.get("token", "")))
        return 200, out, "application/json"

    def _require_journal(self):
        if self.journal is None:
            raise _HttpError(404, "no journal attached to this server")
        return self.journal

    # NB: WSGI servers deliver PATH_INFO already percent-decoded (PEP
    # 3333), so the matched topic/subject group is the literal name — do
    # NOT unquote again. Names containing '/' are not addressable over
    # these path routes (journal topics are `geomesa-<type>`, so this
    # never arises in practice).
    def _journal_publish(self, topic, params, body):
        import base64

        bus = self._require_journal()
        b = body or {}
        if "data_b64" not in b:
            raise _HttpError(400, "body must include 'data_b64'")
        bus.publish(
            topic, str(b.get("key", "")),
            base64.b64decode(b["data_b64"]),
            barrier=bool(b.get("barrier", False)),
        )
        return 200, {"ok": True}, "application/json"

    def _journal_poll(self, topic, params, body):
        import base64

        bus = self._require_journal()
        partition = self._int_param(params, "partition") or 0
        offset = self._int_param(params, "offset") or 0
        max_n = self._int_param(params, "max_n") or 256
        msgs = bus.poll(topic, partition, offset, max_n)
        return 200, {
            "payloads": [base64.b64encode(p).decode() for p in msgs],
            "end": bus.end_offset(topic, partition),
        }, "application/json"

    def _journal_tpoll(self, topic, params, body):
        import base64

        bus = self._require_journal()
        if "cursor" in params:
            # byte-cursor tail: O(new data) per call — the long-lived
            # remote-subscriber path
            msgs, nxt = bus.total_poll_bytes(
                topic, self._int_param(params, "cursor") or 0)
            return 200, {
                "payloads": [base64.b64encode(p).decode() for p in msgs],
                "cursor": nxt,
            }, "application/json"
        offset = self._int_param(params, "offset") or 0
        max_n = self._int_param(params, "max_n") or 256
        msgs = bus.total_poll(topic, offset, max_n)
        return 200, {
            "payloads": [base64.b64encode(p).decode() for p in msgs],
            "size": bus.topic_size(topic),
        }, "application/json"

    def _journal_end(self, topic, params, body):
        bus = self._require_journal()
        partition = self._int_param(params, "partition") or 0
        return 200, {
            "end": bus.end_offset(topic, partition),
            "partitions": bus.partitions,
            "size": bus.topic_size(topic),
        }, "application/json"

    # -- Confluent Schema Registry protocol ----------------------------------
    def _require_registry(self):
        if self.schema_registry is None:
            raise _HttpError(404, "no schema registry attached to this server")
        return self.schema_registry

    def _registry_register(self, subject, params, body):
        reg = self._require_registry()
        b = body or {}
        if "schema" not in b:
            raise _HttpError(400, 'body must be {"schema": "<avro json>"}')
        # Confluent wire format carries the schema as a STRING of JSON
        schema = (json.loads(b["schema"]) if isinstance(b["schema"], str)
                  else b["schema"])
        sid = reg.register(subject, schema)
        return 200, {"id": sid}, "application/json"

    def _registry_versions(self, subject, params, body):
        reg = self._require_registry()
        return 200, reg.versions(subject), "application/json"

    def _registry_by_id(self, sid, params, body):
        reg = self._require_registry()
        try:
            schema = reg.schema_by_id(int(sid))
        except KeyError:
            raise _HttpError(404, f"schema id {sid} not found")
        return 200, {"schema": json.dumps(schema)}, "application/json"

    def _list_schemas(self, params, body):
        return 200, {"schemas": self.store.list_schemas()}, "application/json"

    def _sql(self, params, body):
        if not isinstance(body, dict) or not body.get("q"):
            raise _HttpError(400, "body must be {\"q\": \"SELECT ...\"}")
        from geomesa_tpu.geometry.types import Geometry
        from geomesa_tpu.geometry.wkt import to_wkt
        from geomesa_tpu.sql.engine import SqlError, sql as _run_sql

        try:
            # caller auths thread into EVERY internal store query; paths
            # that cannot apply row visibility (mesh aggregation, device
            # join gather) decline automatically inside sql()
            res = _run_sql(self.store, str(body["q"]),
                           auths=params.get("__auths__"))
        except SqlError as e:
            raise _HttpError(400, f"sql error: {e}")

        def _cell(v):
            # geometry-typed projections serialize as WKT (the engine's own
            # convention for geometry-valued UDF results)
            return to_wkt(v) if isinstance(v, Geometry) else _jsonable(v)

        return 200, {
            "columns": list(res.columns),
            "rows": [[_cell(v) for v in row] for row in res.rows()],
        }, "application/json"

    def _create_schema(self, params, body):
        if not body or "name" not in body or "spec" not in body:
            raise _HttpError(400, "body must be {\"name\": ..., \"spec\": ...}")
        self.store.create_schema(body["name"], body["spec"])
        return 201, {"created": body["name"]}, "application/json"

    def _get_schema(self, name, params, body):
        sft = self.store.get_schema(name)
        if self._restricted_auths(name, params) is not None:
            # same leak class as the stats endpoints: the store-wide count
            # reveals restricted rows — report only the caller-visible count
            count = self._visible_stat(name, params, "Count()").count
        else:
            count = self.store.stats_count(name)
        return 200, {
            "name": sft.name,
            "spec": sft.to_spec(),
            "attributes": [
                {"name": a.name, "type": a.type.value} for a in sft.attributes
            ],
            "count": count,
        }, "application/json"

    def _update_schema(self, name, params, body):
        """Schema evolution (updateSchema role): body keys ``add`` (spec
        string or list of specs), ``keywords`` (list of strings),
        ``rename_to`` (string)."""
        if not isinstance(body, dict) or not ({"add", "keywords", "rename_to"} & set(body)):
            raise _HttpError(400, "expected {add|keywords|rename_to} body")
        add = body.get("add")
        if add is not None and not (
            isinstance(add, str)
            or (isinstance(add, list) and all(isinstance(s, str) for s in add))
        ):
            raise _HttpError(400, "'add' must be a spec string or list of them")
        keywords = body.get("keywords")
        if keywords is not None and not (
            isinstance(keywords, list)
            and all(isinstance(k, str) for k in keywords)
        ):
            raise _HttpError(400, "'keywords' must be a list of strings")
        rename_to = body.get("rename_to")
        if rename_to is not None and not isinstance(rename_to, str):
            raise _HttpError(400, "'rename_to' must be a string")
        # store ValueErrors map to JSON 400 in __call__
        sft = self.store.update_schema(
            name, add=add, keywords=keywords, rename_to=rename_to
        )
        return 200, {"name": sft.name, "spec": sft.to_spec()}, "application/json"

    def _delete_schema(self, name, params, body):
        self.store.delete_schema(name)
        return 204, None, "application/json"

    def _geojson_records(self, name, body, require_id: bool):
        """GeoJSON FeatureCollection (or bare Feature) body → (records,
        fids). ``require_id``: modify semantics address features by id."""
        if not isinstance(body, dict):
            raise _HttpError(400, "expected a GeoJSON FeatureCollection body")
        feats = body.get("features", [body] if body.get("type") == "Feature" else None)
        if feats is None:
            raise _HttpError(400, "expected a GeoJSON FeatureCollection body")
        from geomesa_tpu.convert.json_converter import geojson_geometry

        sft = self.store.get_schema(name)
        recs, fids = [], []
        for i, f in enumerate(feats):
            if require_id and "id" not in f:
                raise _HttpError(400, f"feature {i}: updates require an id")
            props = dict(f.get("properties") or {})
            if sft.geom_field:
                g = geojson_geometry(f.get("geometry"))
                if g is None:
                    raise _HttpError(400, f"feature {i}: missing/invalid geometry")
                props[sft.geom_field] = g
            recs.append({a.name: props.get(a.name) for a in sft.attributes})
            fids.append(str(f["id"]) if "id" in f else None)
        return recs, fids

    def _add_features(self, name, params, body):
        recs, fids = self._geojson_records(name, body, require_id=False)
        if any(f is None for f in fids):
            fids = None  # auto-generated z3-uuid fids
        elif fids and self._restricted_auths(name, params) is not None:
            # explicit ids from a restricted caller could shadow hidden rows,
            # and any existence check would itself be an oracle — restricted
            # writers get auto-generated ids only
            raise _HttpError(
                403, "explicit feature ids require unrestricted access"
            )
        n = self.store.write(name, recs, fids=fids)
        return 201, {"written": n}, "application/json"

    def _assert_fids_mutable(self, name, params, fids):
        """Visibility guard for mutations: a restricted caller may only
        address ids it can SEE. Any id NOT in the caller-visible set — hidden
        or nonexistent alike, so the response can't be used as an existence
        oracle — is a uniform 403. Returns the caller's auths (for the
        store-level enforcement that re-checks under the mutation lock,
        closing the check-then-act race), or None when unrestricted."""
        auths = self._restricted_auths(name, params)
        if auths is None:
            return None
        from geomesa_tpu.filter import ast as _ast

        visible = set(
            self.store.query(
                name, Query(filter=_ast.FidIn(tuple(fids)), auths=auths)
            ).table.fids.tolist()
        )
        if set(fids) - visible:
            raise _HttpError(403, "forbidden: target features not visible")
        return auths

    def _update_features(self, name, params, body):
        """WFS-T Update analog: replace features by id (modify writer);
        store-side ValueError maps to 400 via the dispatch handler."""
        recs, fids = self._geojson_records(name, body, require_id=True)
        auths = self._assert_fids_mutable(name, params, fids)
        n = self.store.update_features(name, recs, fids, visible_to=auths)
        return 200, {"updated": n}, "application/json"

    def _delete_features(self, name, params, body):
        """WFS-T Delete analog: ``?fids=a,b,c`` (or body {"fids": [...]})."""
        fids = [f for f in params.get("fids", "").split(",") if f]
        if not fids and isinstance(body, dict):
            fids = body.get("fids")
        if not (
            isinstance(fids, list)
            and fids
            and all(isinstance(f, str) for f in fids)
        ):
            raise _HttpError(400, 'expected ?fids=a,b,c or {"fids": [...]}')
        auths = self._assert_fids_mutable(name, params, fids)
        n = self.store.delete_features(name, fids, visible_to=auths)
        return 200, {"deleted": n}, "application/json"

    def _int_param(self, params, key):
        if key not in params:
            return None
        try:
            v = int(params[key])
        except ValueError:
            raise _HttpError(400, f"{key} must be an integer: {params[key]!r}")
        if v < 0:
            raise _HttpError(400, f"{key} must be >= 0: {v}")
        return v

    def _parse_query(self, params) -> Query:
        hints = {}
        if params.get("__tenant__"):
            # tenant rides the query object too (the audit record's
            # primary source; the context var covers paths that build
            # their own Query instances)
            hints["tenant"] = params["__tenant__"]
        if params.get("__deadline__") is not None:
            # the store's own scan honors the remaining budget too: it
            # sheds before device work when the budget is gone and caps
            # its watchdog timeout at the remaining time
            hints["deadline"] = params["__deadline__"]
        limit = self._int_param(params, "limit")
        props = params["properties"].split(",") if params.get("properties") else None
        sort_by = None
        if params.get("sortBy"):
            fld = params["sortBy"]
            desc = fld.startswith("-")
            sort_by = (fld.lstrip("-"), desc)
        return Query(
            filter=params.get("cql") or None,
            limit=limit,
            # OGC startIndex paging (use with sortBy for stable pages)
            start_index=self._int_param(params, "startIndex"),
            properties=props,
            sort_by=sort_by,
            hints=hints,
            auths=params.get("__auths__"),
        )

    def _query(self, name, params, body):
        q = self._parse_query(params)
        fmt = params.get("format", "geojson")
        if self.coalescer is not None:
            # request coalescing (docs/serving.md): concurrent /query
            # requests for the same type share ONE select_many device
            # dispatch; per-query auths/hints/deadlines are preserved,
            # and a deadline too tight for the window bypasses it. A
            # store without select_many executes singly (no window).
            r = self.coalescer.submit(name, "select", q)
        else:
            r = self.store.query(name, q)
        from geomesa_tpu.web.formats import UnknownFormat, format_table

        try:
            # the pipeline's last stage: payload encoding, timed apart from
            # the store scan it follows
            with obs.span("serialize", format=fmt, rows=r.count):
                payload, ctype = format_table(r.table, fmt)
        except UnknownFormat:
            raise _HttpError(400, f"unknown format {fmt!r}") from None
        return 200, payload, ctype

    def _tube_select(self, name, params, body):
        """POST {"track": [[x, y, epoch_ms], ...], "buffer_deg": f,
        "time_buffer_ms": n, "filter"?: cql, "format"?: fmt} → matching
        features through the batched device corridor engine."""
        if not body or "track" not in body:
            raise _HttpError(400, 'body must include "track"')
        try:
            track = [(float(x), float(y), int(t)) for x, y, t in body["track"]]
            buf = float(body.get("buffer_deg", 0.0))
            tb = int(body.get("time_buffer_ms", 0))
        except (TypeError, ValueError) as e:
            raise _HttpError(400, f"bad tube-select body: {e}") from None
        from geomesa_tpu.trajectory.corridor import tube_select_device
        from geomesa_tpu.web.formats import UnknownFormat, format_table

        try:
            table = tube_select_device(
                self.store, name, track, buf, tb,
                filter=body.get("filter"), auths=params.get("__auths__"))
        except ValueError as e:
            raise _HttpError(400, str(e)) from None
        except KeyError as e:
            raise _HttpError(404, str(e)) from None
        fmt = body.get("format", params.get("format", "geojson"))
        try:
            with obs.span("serialize", format=fmt, rows=len(table)):
                payload, ctype = format_table(table, fmt)
        except UnknownFormat:
            raise _HttpError(400, f"unknown format {fmt!r}") from None
        return 200, payload, ctype

    def _track_stats(self, name, params, body):
        """POST {"track_field": str, "filter"?: cql, "dwell_eps_deg"?: f}
        → per-entity track aggregates (one fused device pass)."""
        if not body or "track_field" not in body:
            raise _HttpError(400, 'body must include "track_field"')
        from geomesa_tpu.trajectory.state import (
            DEFAULT_DWELL_EPS_DEG, track_stats)

        try:
            stats = track_stats(
                self.store, name, str(body["track_field"]),
                filter=body.get("filter"),
                dwell_eps_deg=float(
                    body.get("dwell_eps_deg", DEFAULT_DWELL_EPS_DEG)),
                auths=params.get("__auths__"))
        except (TypeError, ValueError) as e:
            raise _HttpError(400, f"bad track-stats request: {e}") from None
        except KeyError as e:
            raise _HttpError(404, str(e)) from None
        n = len(stats["track"])
        return 200, {
            "entities": n,
            "columns": {k: [_jsonable(x) if isinstance(x, (np.generic,))
                            else (x if isinstance(x, (int, float, str))
                                  else str(x))
                            for x in v.tolist()]
                        for k, v in stats.items()},
        }, "application/json"

    def _link(self, params, body):
        """POST {"left": type, "right": type, "pred"?: "intersects"|
        "dwithin", "distance"?: f, "time_buffer_ms"?: n, "left_filter"?,
        "right_filter"?} → exact interlink pair set (2D / XZ3 legs)."""
        if not body or "left" not in body or "right" not in body:
            raise _HttpError(400, 'body must include "left" and "right"')
        from geomesa_tpu.trajectory.interlink import interlink

        tb = body.get("time_buffer_ms")
        try:
            pairs = interlink(
                self.store, str(body["left"]), self.store,
                str(body["right"]), pred=body.get("pred", "intersects"),
                distance=float(body.get("distance", 0.0)),
                time_buffer_ms=(None if tb is None else int(tb)),
                lfilter=body.get("left_filter"),
                rfilter=body.get("right_filter"),
                auths=params.get("__auths__"))
        except ValueError as e:
            raise _HttpError(400, str(e)) from None
        except KeyError as e:
            raise _HttpError(404, str(e)) from None
        return 200, {
            "count": len(pairs),
            "pairs": [[lf, rf] for lf, rf in pairs],
        }, "application/json"

    def _restricted_auths(self, name, params):
        """The caller's auths when visibility enforcement applies, else None.

        Stats/count endpoints normally read pre-computed store-wide sketches;
        when an auth provider is configured AND the schema labels features
        (``geomesa.vis.field``), those sketches would leak restricted rows —
        such requests must recompute over the caller-visible subset."""
        auths = params.get("__auths__")
        if auths is None:
            return None
        try:
            sft = self.store.get_schema(name)
        except KeyError:
            return None  # handler will 404 on its own store call
        if not (sft.user_data or {}).get("geomesa.vis.field"):
            return None
        return auths

    def _count_many(self, name, params, body):
        """POST {"queries": [cql, ...], "loose": bool} → batched counts in
        one device pass (DataStore.count_many)."""
        if not body or "queries" not in body:
            raise _HttpError(400, 'body must be {"queries": [...]}')
        if not hasattr(self.store, "count_many"):
            raise _HttpError(400, "store does not support batched counts")
        auths = self._restricted_auths(name, params)
        queries = body["queries"]
        if auths is not None:
            # visibility-filtered counts can't use the loose batched path
            queries = [Query(filter=c, auths=auths) for c in queries]
        counts = self.store.count_many(
            name, queries, loose=bool(body.get("loose", True))
        )
        return 200, {"counts": counts}, "application/json"

    def _select_many(self, name, params, body):
        """POST {"queries": [cql|null, ...]} → {"results": [{"count": n,
        "arrow_b64": ...}, ...]}: batched row retrieval — the whole
        batch's device work in two dispatches (DataStore.select_many),
        per-query Arrow IPC back. The federation surface of the batched
        read path; caller visibility applies per query through the shared
        reduce pipeline."""
        import base64

        from geomesa_tpu.io.arrow import to_ipc_bytes

        if not body or "queries" not in body:
            raise _HttpError(400, 'body must be {"queries": [...]}')
        sm = getattr(self.store, "select_many", None)
        if sm is None:
            raise _HttpError(400, "store does not support batched selects")
        auths = self._restricted_auths(name, params)
        queries = [Query(filter=c, auths=auths) for c in body["queries"]]
        out = [
            {
                "count": int(r.count),
                "arrow_b64": base64.b64encode(
                    to_ipc_bytes(r.table)).decode(),
            }
            for r in sm(name, queries)
        ]
        return 200, {"results": out}, "application/json"

    def _aggregate(self, name, params, body):
        """POST {"queries": [cql, ...], "group_by": [cols], "value_cols":
        [cols]} → per query: null (that query cannot ride the mesh — the
        caller runs its own fold) or {"groups": [[key, ...], ...], "count":
        [...], "cols": {col: {"count"/"sum"/"min"/"max": [...]}}} with NaN
        extrema as null. The fused grouped segment-reduce over HTTP — the
        federation analog of count-many/density-many."""
        if not body or "queries" not in body:
            raise _HttpError(400, 'body must be {"queries": [...]}')
        agg = getattr(self.store, "aggregate_many", None)
        if agg is None:
            raise _HttpError(400, "store does not support batched aggregation")
        auths = self._restricted_auths(name, params)
        queries = body["queries"]
        if auths is not None:
            # visibility-filtered rows can't ride the batched device fold
            queries = [Query(filter=c, auths=auths) for c in queries]
        now_ms = body.get("now_ms")
        out = agg(
            name, queries,
            group_by=body.get("group_by"),
            value_cols=body.get("value_cols", []),
            now_ms=None if now_ms is None else int(now_ms),
        )

        def _key(v):
            return v.item() if isinstance(v, np.generic) else v

        def _f(v: float):
            return None if np.isnan(v) else float(v)

        results = []
        for r in out:
            if r is None:
                results.append(None)
                continue
            results.append({
                "groups": [[_key(k) for k in key] for key in r["groups"]],
                "count": [int(c) for c in r["count"]],
                "cols": {
                    c: {
                        "count": [int(v) for v in d["count"]],
                        "sum": [float(v) for v in d["sum"]],
                        "min": [_f(v) for v in d["min"]],
                        "max": [_f(v) for v in d["max"]],
                    }
                    for c, d in r["cols"].items()
                },
            })
        return 200, {"results": results}, "application/json"

    def _density_many(self, name, params, body):
        """POST {"queries": [cql, ...], "bbox": [x1,y1,x2,y2], "width", "height",
        "loose"} → one shared-viewport heatmap per query in one device pass
        (DataStore.density_many)."""
        if not body or "queries" not in body or "bbox" not in body:
            raise _HttpError(400, 'body must be {"queries": [...], "bbox": [...]}')
        if not hasattr(self.store, "density_many"):
            raise _HttpError(400, "store does not support batched density")
        bbox = body["bbox"]
        if not (isinstance(bbox, list) and len(bbox) == 4):
            raise _HttpError(400, "bbox must be [xmin, ymin, xmax, ymax]")
        width = int(body.get("width", 256))
        height = int(body.get("height", 256))
        # clamp client-controlled grid dims: a huge grid is a huge
        # allocation AND a forever-cached compiled kernel per distinct shape
        if not (1 <= width <= 4096 and 1 <= height <= 4096):
            raise _HttpError(400, "width/height must be in [1, 4096]")
        auths = self._restricted_auths(name, params)
        queries = body["queries"]
        if auths is not None:
            # visibility-filtered grids can't use the loose batched path
            queries = [Query(filter=c, auths=auths) for c in queries]
        grids = self.store.density_many(
            name, queries, tuple(float(v) for v in bbox),
            width=width, height=height,
            loose=bool(body.get("loose", True)),
        )
        return 200, {
            "width": width,
            "height": height,
            "grids": [g.tolist() for g in grids],
        }, "application/json"

    def _stats(self, name, params, body):
        spec = params.get("stats")
        if not spec:
            raise _HttpError(400, "missing ?stats= spec")
        r = self.store.query(
            name,
            Query(filter=params.get("cql"), hints={"stats": spec},
                  auths=params.get("__auths__")),
        )

        def sketch_dict(s):
            from geomesa_tpu.stats.sketches import Stat

            d = {}
            for k, v in vars(s).items():
                if k.startswith("_") or callable(v):
                    continue
                if isinstance(v, Stat):
                    v = sketch_dict(v)
                elif isinstance(v, dict):
                    v = {
                        str(gk): sketch_dict(gv) if isinstance(gv, Stat) else gv
                        for gk, gv in v.items()
                    }
                d[k] = v
            return _jsonable(d)

        out = {label: sketch_dict(s) for label, s in (r.stats or {}).items()}
        return 200, out, "application/json"

    def _visible_stat(self, name, params, spec: str):
        """One sketch computed over the caller-visible rows only."""
        r = self.store.query(
            name,
            Query(filter=params.get("cql"), hints={"stats": spec},
                  auths=params.get("__auths__")),
        )
        return r.stats[spec]

    def _stats_count(self, name, params, body):
        if self._restricted_auths(name, params) is not None:
            c = self._visible_stat(name, params, "Count()").count
        else:
            exact = params.get("exact", "false").lower() in ("1", "true", "yes")
            c = self.store.stats_count(name, params.get("cql"), exact=exact)
        return 200, {"count": c}, "application/json"

    def _stats_bounds(self, name, params, body):
        attr = params.get("attr")
        if not attr:
            raise _HttpError(400, "missing ?attr=")
        if self._restricted_auths(name, params) is not None:
            mm = self._visible_stat(name, params, f"MinMax({attr})")
            lo, hi = mm.min, mm.max
        else:
            lo, hi = self.store.stats_bounds(name, attr)
        return 200, {"attr": attr, "min": lo, "max": hi}, "application/json"

    def _stats_topk(self, name, params, body):
        attr = params.get("attr")
        if not attr:
            raise _HttpError(400, "missing ?attr=")
        k = int(params.get("k", 10))
        if self._restricted_auths(name, params) is not None:
            top = self._visible_stat(name, params, f"TopK({attr}, {k})").top(k)
        else:
            top = self.store.stats_top_k(name, attr, k)
        return 200, {"attr": attr, "topk": [[v, int(c)] for v, c in top]}, "application/json"

    def _density(self, name, params, body):
        opts = {
            "width": int(params.get("width", 256)),
            "height": int(params.get("height", 256)),
        }
        if params.get("bbox"):
            opts["bbox"] = tuple(float(v) for v in params["bbox"].split(","))
        r = self.store.query(
            name,
            Query(filter=params.get("cql"), hints={"density": opts},
                  auths=params.get("__auths__")),
        )
        return 200, {"width": opts["width"], "height": opts["height"],
                     "grid": r.density}, "application/json"

    def _audit(self, params, body):
        w = getattr(self.store, "audit_writer", None)
        events = []
        if w is not None and hasattr(w, "query_events"):
            events = [json.loads(e.to_json()) for e in w.query_events(params.get("typeName"))]
        return 200, {"events": events}, "application/json"

    def _obs_flight(self, params, body):
        """The query-audit flight recorder (``geomesa-tpu obs flight``
        pulls this): newest records, dump state, recorder config.
        Server-side filters: ``?tenant=``, ``?type=``, ``?anomalies=1``
        (applied before the limit)."""
        from geomesa_tpu.obs import flight

        limit = self._int_param(params, "limit")
        anomalies = params.get("anomalies", "").lower() in ("1", "true",
                                                            "yes")
        return 200, flight.get().snapshot(
            limit=limit or 64,
            tenant=params.get("tenant") or None,
            type_name=params.get("type") or None,
            anomalies_only=anomalies,
        ), "application/json"

    def _obs_tenants(self, params, body):
        """Per-tenant usage accounting (``geomesa-tpu obs tenants`` pulls
        this): rolling-window + lifetime counters per tenant, the
        (tenant, type, plan-signature) heavy-hitter table, and per-tenant
        SLO burn — docs/observability.md § Usage metering & workload
        replay."""
        limit = self._int_param(params, "limit")
        return 200, _usage.get().snapshot(limit=limit), "application/json"

    def _obs_costs(self, params, body):
        """The per-(type, plan-signature) observed-cost table
        (``geomesa-tpu obs costs`` pulls this): p50/p95 device-ms and
        wall-ms, rows, bytes scanned — the adaptive planner's training
        signal — plus the cost model's ``calibration`` report
        (predicted-vs-actual drift per plan shape: mean absolute relative
        error, signed bias, sample counts), so a model that has gone
        stale is visible before it costs latency (docs/planning.md)."""
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.planning import costmodel

        limit = self._int_param(params, "limit")
        out = devmon.costs().snapshot(limit=limit or 256)
        out["calibration"] = costmodel.model().calibration_report()
        # per-member observed-cost aggregates (merged/sharded views):
        # ?member=N filters to one member's rows
        member_costs = getattr(self.store, "member_costs_snapshot", None)
        if member_costs is not None:
            out["members"] = member_costs(
                member=self._int_param(params, "member"))
        return 200, out, "application/json"

    def _obs_audit(self, params, body):
        """The continuous correctness auditor (``geomesa-tpu obs audit``
        pulls this): per-kind checked/passed/diverged/abstained
        counters, queue health, recent divergence reports (with repro-
        bundle paths), and the latest invariant-sweep results —
        docs/observability.md § Continuous correctness auditing."""
        from geomesa_tpu.obs import audit as _obsaudit

        limit = self._int_param(params, "limit")
        return 200, _obsaudit.get().snapshot(limit=limit or 32), \
            "application/json"

    def _obs_lens(self, params, body):
        """The retained profiling plane (``geomesa-tpu obs lens`` pulls
        this): per-(type, plan-signature) time-bucketed latency history,
        live-window quantiles, trace exemplars (each resolvable to a
        stitched span tree), plus the regression sentinel's alarm state —
        docs/observability.md § Query lens & host-roundtrip ledger."""
        from geomesa_tpu.obs import lens as _lensmod
        from geomesa_tpu.obs import trace as _obstrace

        trace_id = params.get("trace")
        if trace_id:
            # exemplar resolution: bucket → trace_id → stitched span tree,
            # straight off the completed-roots ring (404 once it ages out)
            root = _obstrace.find_trace(trace_id)
            if root is None:
                return 404, {"error": f"trace not found: {trace_id!r}"}, \
                    "application/json"
            return 200, _obstrace.span_doc(root), "application/json"

        limit = self._int_param(params, "limit")
        try:
            window_s = float(params.get("window") or 300.0)
        except ValueError:
            return 400, {"error": f"bad window: {params['window']!r}"}, \
                "application/json"
        out = _lensmod.get().snapshot(
            limit=limit or 50, window_s=window_s,
            type_name=params.get("type") or None)
        out["sentinel"] = _lensmod.sentinel().snapshot()
        return 200, out, "application/json"

    def _obs_stream(self, params, body):
        """The standing-query scale report (``geomesa-tpu obs
        stream-report`` pulls this): per topic, subscriptions ranked by
        scan-cost share with delivery-latency quantiles / stage
        decomposition / on-time-late accounting / chunk-trace exemplars,
        the capacity section (occupancy, churn, predicted next
        bucket-crossing recompile, HBM-per-subscription ×1M), and the
        backlog sentinel's alarm state — docs/streaming.md § Stream lens
        & delivery SLOs. ``?trace=`` resolves a delivery exemplar exactly
        like ``/api/obs/lens?trace=``."""
        from geomesa_tpu.obs import streamlens as _slmod
        from geomesa_tpu.obs import trace as _obstrace

        trace_id = params.get("trace")
        if trace_id:
            root = _obstrace.find_trace(trace_id)
            if root is None:
                return 404, {"error": f"trace not found: {trace_id!r}"}, \
                    "application/json"
            return 200, _obstrace.span_doc(root), "application/json"

        limit = self._int_param(params, "limit")
        try:
            window_s = float(params.get("window") or 300.0)
        except ValueError:
            return 400, {"error": f"bad window: {params['window']!r}"}, \
                "application/json"
        out = _slmod.get().report(
            window_s=window_s, limit=limit or 50,
            topic=params.get("topic") or None)
        out["sentinel"] = _slmod.sentinel().snapshot()
        return 200, out, "application/json"

    def _obs_fusion(self, params, body):
        """The host-roundtrip fusion-opportunity report (``geomesa-tpu
        obs fusion-report`` pulls this): plan signatures ranked by
        host-choreography share — dispatches/syncs per query, inter-stage
        host gaps, transfer bytes. The work list for whole-plan device
        compilation (ROADMAP item 1)."""
        from geomesa_tpu.obs import ledger as _rtledger

        limit = self._int_param(params, "limit")
        return 200, {
            "entries": _rtledger.table().fusion_report(limit=limit or 50),
        }, "application/json"

    def _obs_ledger(self, params, body):
        """The raw roundtrip-ledger rollup in the stable reconcile-export
        schema (``kind`` + ``schema_version`` + per-(type, signature)
        counter entries) — what ``geomesa-tpu obs ledger-export`` writes
        and ``python -m geomesa_tpu.analysis --sync --reconcile`` reads.
        ``?format=json`` is accepted (and is the only format) so callers
        can pin the content negotiation they mean."""
        from geomesa_tpu.obs import ledger as _rtledger

        fmt = params.get("format")
        if fmt not in (None, "json"):
            return 400, {"error": f"unsupported format: {fmt!r}"}, \
                "application/json"
        return 200, _rtledger.table().export(), "application/json"

    def _obs_shards(self, params, body):
        """The sharded federation's routing state (``geomesa-tpu obs
        shards`` pulls this): current generation, members, per-shard
        ownership, LIVE migration records (state / rows shipped+replayed
        / dual-ledger size), coverage violations, and the process-wide
        migration state counters. Stores without a shard router answer
        with just the counters — the caller learns this serves a single
        member, not an error."""
        from geomesa_tpu.serving import elastic as _elastic

        out = {"migration_counters": _elastic.migration_metrics()}
        snap = getattr(self.store, "shards_snapshot", None)
        if snap is not None:
            out.update(snap())
        else:
            out["sharded"] = False
        return 200, out, "application/json"

    def _metrics(self, params, body):
        m = getattr(self.store, "metrics", None)
        # the store's SLO engine (DataStore and MergedDataStoreView both
        # carry one): burn rates / budgets ride the same scrape
        slo_engine = getattr(self.store, "slo", None)
        if params.get("format") == "prometheus":
            # text exposition for a Prometheus scrape: the store registry
            # plus the process-wide jax telemetry registry (compile times,
            # per-step dispatch, recompile counts) when it exists
            from geomesa_tpu.obs import devmon, jaxmon
            from geomesa_tpu.obs.export import (
                PROMETHEUS_CONTENT_TYPE,
                prometheus_text,
            )

            text = prometheus_text(m, jaxmon.GLOBAL)
            if slo_engine is not None:
                text += slo_engine.prometheus_text()
            # device telemetry: labeled HBM residency/budget/spill gauges
            text += devmon.prometheus_text()
            # buffer pool + GeoBlocks query cache: geomesa_cache_{hits,
            # misses,evictions}, geomesa_pool_* and pyramid-bytes gauges
            cache_lines = getattr(self.store, "cache_prometheus_lines", None)
            if cache_lines is not None:
                text += "\n".join(cache_lines()) + "\n"
            # streaming tier: per-topic lag / poll-rate / scanner pipeline
            # gauges (geomesa_stream_lag{topic} is the backpressure signal)
            from geomesa_tpu.stream import telemetry as stream_telemetry

            text += stream_telemetry.prometheus_text()
            # tenant usage: geomesa_tenant_* counters with BOUNDED label
            # cardinality (top-K tenants + an "other" rollup) plus the
            # per-tenant SLO burn gauges
            text += _usage.get().prometheus_text()
            # admission control: geomesa_admission_* admitted/shed
            # series (per-priority + bounded per-tenant shed counters)
            if self.admission is not None:
                text += self.admission.prometheus_text()
            # correctness auditor: geomesa_audit_* per-kind checked/
            # passed/diverged/abstained counters
            from geomesa_tpu.obs import audit as _obsaudit

            text += _obsaudit.get().prometheus_text()
            # durability plane: geomesa_wal_* append/flush/trim counters +
            # geomesa_recovery_* replay counters (store/wal.py)
            from geomesa_tpu.store import wal as _walmod

            text += _walmod.prometheus_text()
            # query lens: TRUE histogram families (geomesa_lens_latency_ms
            # _bucket/_sum/_count with le labels) per (type, signature),
            # plus the regression sentinel's gauge + counter
            from geomesa_tpu.obs import lens as _lensmod

            text += _lensmod.get().prometheus_text()
            text += _lensmod.sentinel().prometheus_text()
            # stream lens: geomesa_stream_delivery_* histogram families
            # per (topic, subscription) — top-K-by-cost + `other` rollup —
            # plus the stream.delivery SLO gauges and the backlog sentinel
            from geomesa_tpu.obs import streamlens as _slmod

            text += _slmod.get().prometheus_text()
            text += _slmod.sentinel().prometheus_text()
            # elastic plane: geomesa_shard_migrations_total{state},
            # geomesa_tier_bytes{tier,type}, geomesa_autoscaler_* totals
            from geomesa_tpu.serving import elastic as _elastic

            text += _elastic.prometheus_text()
            return 200, text.encode(), PROMETHEUS_CONTENT_TYPE
        out = m.snapshot() if m is not None else {}
        # device section: per-(type, index, group) resident bytes, budget
        # headroom, spill report, process transfer totals (obs.devmon)
        from geomesa_tpu.obs import devmon

        out["device"] = devmon.device_report()
        # buffer-pool / query-cache / pyramid gauge block
        cache_report = getattr(self.store, "cache_report", None)
        if cache_report is not None:
            out["cache"] = cache_report()
        if slo_engine is not None:
            slo_snap = slo_engine.snapshot()
            if slo_snap:
                out["slo"] = slo_snap
        # federated stores surface their per-member health scoreboard
        # (rolling success rate, p95, breaker state) alongside the metrics
        health = getattr(self.store, "member_health", None)
        if health is not None:
            out["federation_members"] = health()
        # streaming tier: per-topic lag/poll/scan gauges (empty dict when
        # no stream threads have reported)
        from geomesa_tpu.stream import telemetry as stream_telemetry

        stream_report = stream_telemetry.report()
        if stream_report:
            out["stream"] = stream_report
        # tenant usage accounting (full detail at GET /api/obs/tenants)
        meter = _usage.get()
        if meter.observe_count:
            out["tenants"] = meter.snapshot(limit=16)
        # correctness auditor (full detail at GET /api/obs/audit)
        from geomesa_tpu.obs import audit as _obsaudit

        aud = _obsaudit.get()
        if aud.checked or _obsaudit.ENABLED:
            out["audit"] = aud.snapshot(limit=8)
        # durability plane: WAL append/ack/trim + recovery replay counters
        # (only once a WAL has written — plain stores skip the section)
        from geomesa_tpu.store import wal as _walmod

        wal_m = _walmod.wal_metrics()
        if any(wal_m.values()):
            out["wal"] = wal_m
        # query lens summary (full detail at GET /api/obs/lens): only once
        # something has been observed — plain scrapes skip the section
        from geomesa_tpu.obs import lens as _lensmod

        lens_obj = _lensmod.get()
        if lens_obj.observe_count:
            out["lens"] = lens_obj.snapshot(limit=8)
            out["lens"]["sentinel"] = _lensmod.sentinel().snapshot()
        # stream lens summary (full detail at GET /api/obs/stream)
        from geomesa_tpu.obs import streamlens as _slmod

        stream_lens = _slmod.get()
        if stream_lens.observe_count:
            out["stream_lens"] = stream_lens.report(limit=8)
            out["stream_lens"]["sentinel"] = _slmod.sentinel().snapshot()
        # serving plane: admission decisions + coalesce effectiveness
        if self.admission is not None:
            out["admission"] = self.admission.snapshot(limit=16)
        if self.coalescer is not None and self.coalescer.dispatch_count:
            c = self.coalescer
            out["coalesce"] = {
                "window_ms": c.window_s * 1000.0,
                "dispatches": c.dispatch_count,
                "queries": c.query_count,
                "max_width": c.max_width,
            }
        return 200, out, "application/json"

    def _ogc(self, handler, error_cls, params):
        """Shared OGC KVP dispatch: route to the protocol handler, render
        its error class as the protocol's XML exception report, and apply
        visibility auths exactly as on the native query endpoints."""
        try:
            status, body_out, ctype = handler(
                self.store, params, auths=params.get("__auths__")
            )
        except error_cls as e:
            return 400, e.to_xml().encode(), "text/xml"
        if isinstance(body_out, str):
            body_out = body_out.encode()
        return status, body_out, ctype

    def _wfs(self, params, body):
        """OGC WFS 2.0 KVP binding (GetCapabilities / DescribeFeatureType /
        GetFeature)."""
        from geomesa_tpu.web.wfs import WfsError, handle_wfs

        return self._ogc(handle_wfs, WfsError, params)

    def _wms(self, params, body):
        """OGC WMS 1.3.0 KVP binding (GetCapabilities / GetMap): density
        heatmap or point tiles over the fused device density path."""
        from geomesa_tpu.web.wms import WmsError, handle_wms

        return self._ogc(handle_wms, WmsError, params)


def serve(store, host: str = "127.0.0.1", port: int = 8080, threads: bool = True,
          auth_provider=None, journal=None, schema_registry=None,
          admission=None, coalesce_ms: float | None = None):
    """Run the API on wsgiref's simple server (dev/ops tool, not a prod WSGI
    container — same posture as the reference's embedded servlets).

    ``threads=True`` (default) handles requests concurrently — the store's
    per-type snapshot/mutator locking makes parallel queries + background
    compactions safe; pass False for single-threaded debugging.
    ``auth_provider``: see :class:`geomesa_tpu.security.auth.AuthorizationsProvider`.
    ``journal``/``schema_registry``: attach the cross-host stream transport
    (``/api/journal``) and Confluent-protocol registry (``/subjects``) —
    see GeoMesaApp; the lease endpoint (``/api/lease``) is always on.
    """
    import socketserver
    from wsgiref.simple_server import WSGIServer, make_server

    cls = WSGIServer
    if threads:

        class _ThreadingWSGIServer(socketserver.ThreadingMixIn, WSGIServer):
            daemon_threads = True

        cls = _ThreadingWSGIServer
    httpd = make_server(
        host, port,
        GeoMesaApp(store, auth_provider=auth_provider, journal=journal,
                   schema_registry=schema_registry, admission=admission,
                   coalesce_ms=coalesce_ms),
        server_class=cls,
    )
    print(f"geomesa-tpu REST on http://{host}:{port}/api")
    httpd.serve_forever()
