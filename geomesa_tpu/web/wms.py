"""OGC WMS 1.3.0 KVP endpoints: GetCapabilities + GetMap + GetFeatureInfo
(the map-tile rendering + identify surface).

Role parity: the reference serves heatmaps and styled features to map
clients through GeoServer WMS (``geomesa-accumulo-gs-plugin/``; the density
push-down is ``geomesa-index-api/.../iterators/DensityScan.scala:28`` and
``geomesa-process-vector/.../DensityProcess.scala`` — VERDICT r3 missing
#2). Here GetMap rides the SAME fused device density path every other
surface uses (``DataStore.density_many`` → psum-merged mesh grids), so a
map tile is one batched device pass, not a feature scan:

- ``STYLES=heat`` (default) — density heatmap: transparent→blue→yellow→red
  ramp over the fused device grid; total grid mass equals the tile's exact
  row count (the DensityScan contract).
- ``STYLES=points`` — simple point rendering of the tile's features
  (bounded by a row cap; denser tiles should use ``heat``).

CRS: EPSG:4326 (WMS 1.3.0 lat/lon axis order honored) and EPSG:3857
(meters; rows are resampled from the geographic grid so tiles line up with
web-mercator basemaps). TIME accepts an ISO instant or ``start/end``
interval mapped onto the schema's default date attribute. Errors return
WMS ServiceExceptionReports.
"""

from __future__ import annotations

import io

import numpy as np

from geomesa_tpu.planning.planner import Query
from geomesa_tpu.web.wfs import _attr, escape

__all__ = ["handle_wms", "WmsError"]

MAX_DIM = 4096  # a huge grid is a huge allocation + cached kernel per shape
MAX_POINT_ROWS = 50_000


class WmsError(ValueError):
    """OGC WMS ServiceExceptionReport payload (HTTP 400)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code

    def to_xml(self) -> str:
        return (
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            '<ServiceExceptionReport version="1.3.0" '
            'xmlns="http://www.opengis.net/ogc">'
            f'<ServiceException code="{_attr(self.code)}">'
            f"{escape(str(self))}"
            "</ServiceException></ServiceExceptionReport>"
        )


def handle_wms(store, params: dict, auths=None):
    """Dispatch one WMS KVP request → (status, body bytes/str, content
    type). ``params`` keys match case-insensitively (KVP requirement);
    ``auths`` applies row visibility exactly as on the query endpoints."""
    p = {k.lower(): v for k, v in params.items()}
    if p.get("service", "WMS").upper() != "WMS":
        raise WmsError("InvalidParameterValue",
                       f"unknown service {p.get('service')!r}")
    request = p.get("request", "").lower()
    if request == "getcapabilities":
        return 200, _capabilities(store), "text/xml"
    if request == "getmap":
        return 200, _get_map(store, p, auths), "image/png"
    if request == "getfeatureinfo":
        return _get_feature_info(store, p, auths)
    if request == "getlegendgraphic":
        return 200, _legend_graphic(store, p), "image/png"
    raise WmsError("OperationNotSupported",
                   f"unsupported request {p.get('request')!r}")


def _capabilities(store) -> str:
    layers = []
    for name in store.list_schemas():
        layers.append(
            "<Layer queryable=\"1\">"
            f"<Name>{escape(name)}</Name><Title>{escape(name)}</Title>"
            "<CRS>EPSG:4326</CRS><CRS>EPSG:3857</CRS>"
            '<EX_GeographicBoundingBox>'
            "<westBoundLongitude>-180</westBoundLongitude>"
            "<eastBoundLongitude>180</eastBoundLongitude>"
            "<southBoundLatitude>-90</southBoundLatitude>"
            "<northBoundLatitude>90</northBoundLatitude>"
            "</EX_GeographicBoundingBox>"
            "</Layer>"
        )
    return (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<WMS_Capabilities version="1.3.0" '
        'xmlns="http://www.opengis.net/wms">'
        "<Service><Name>WMS</Name><Title>geomesa_tpu WMS</Title>"
        "</Service><Capability>"
        "<Request><GetCapabilities><Format>text/xml</Format>"
        "</GetCapabilities>"
        "<GetMap><Format>image/png</Format></GetMap>"
        "<GetFeatureInfo><Format>application/json</Format>"
        "<Format>text/plain</Format></GetFeatureInfo>"
        "<GetLegendGraphic><Format>image/png</Format>"
        "</GetLegendGraphic></Request>"
        f"<Layer><Title>geomesa_tpu</Title>{''.join(layers)}</Layer>"
        "</Capability></WMS_Capabilities>"
    )


def _parse_bbox(p: dict) -> tuple[tuple[float, float, float, float], str]:
    """BBOX + CRS → (lon/lat 4326 bbox, crs). Axis order: WMS 1.3.0
    EPSG:4326 is (lat, lon); WMS 1.1.x (the ``SRS`` key) and CRS:84 are
    (lon, lat); 3857 is (x, y) meters either way."""
    crs = (p.get("crs") or p.get("srs") or "EPSG:4326").upper()
    if "srs" in p and "crs" not in p:
        latlon_order = False  # the SRS key is the 1.1.x binding: lon/lat
    else:
        latlon_order = p.get("version", "1.3.0").startswith("1.3")
    raw = p.get("bbox")
    if not raw:
        raise WmsError("MissingParameterValue", "BBOX is required")
    try:
        a, b, c, d = (float(v) for v in raw.split(","))
    except ValueError:
        raise WmsError("InvalidParameterValue", f"bad BBOX {raw!r}") from None
    if crs in ("EPSG:4326", "CRS:84"):
        # CRS:84 is lon/lat by DEFINITION; EPSG:4326 is lat/lon only under
        # the 1.3.x binding (the 1.1.x SRS key kept lon/lat)
        if crs == "EPSG:4326" and latlon_order:
            xmin, ymin, xmax, ymax = b, a, d, c  # lat,lon → lon,lat
        else:
            xmin, ymin, xmax, ymax = a, b, c, d
    elif crs == "EPSG:3857":
        from geomesa_tpu.utils.crs import transform_coords

        (xmin, xmax), (ymin, ymax) = transform_coords(
            np.array([a, c]), np.array([b, d]), "EPSG:3857", "EPSG:4326"
        )
    else:
        raise WmsError("InvalidCRS", f"unsupported CRS {crs!r}")
    if not (xmin < xmax and ymin < ymax):
        raise WmsError("InvalidParameterValue", "degenerate BBOX")
    return (float(xmin), float(ymin), float(xmax), float(ymax)), crs


def _parse_dims(p: dict, dw: str = "256", dh: str = "256") -> tuple[int, int]:
    try:
        width = int(p.get("width", dw))
        height = int(p.get("height", dh))
    except ValueError:
        raise WmsError("InvalidParameterValue", "bad WIDTH/HEIGHT") from None
    if not (1 <= width <= MAX_DIM and 1 <= height <= MAX_DIM):
        raise WmsError("InvalidParameterValue",
                       f"WIDTH/HEIGHT must be in [1, {MAX_DIM}]")
    return width, height


def _merc_y(lat):
    """Latitude (deg) → unscaled web-mercator y."""
    return np.log(np.tan(np.pi / 4 + np.radians(lat) / 2))


def _merc_bounds(bbox) -> tuple[float, float]:
    """Tile bbox → (lo, hi) mercator-y row bounds, web-mercator clamped."""
    _, ymin, _, ymax = bbox
    return _merc_y(max(ymin, -85.06)), _merc_y(min(ymax, 85.06))


def _pixel_lonlat(i: float, j: float, width: int, height: int, bbox,
                  crs: str) -> tuple[float, float]:
    """Map image coordinates (i right, j DOWN from the top-left corner,
    pixel centers at +0.5) to lon/lat, inverting GetMap's rendering: row 0
    of the PNG is the NORTH edge, and 3857 tiles have rows linear in
    web-mercator y (``_mercator_resample``)."""
    xmin, ymin, xmax, ymax = bbox
    lon = xmin + (i + 0.5) / width * (xmax - xmin)
    if crs == "EPSG:3857":
        lo, hi = _merc_bounds(bbox)
        merc = lo + (height - j - 0.5) / height * (hi - lo)
        lat = float(np.degrees(2 * np.arctan(np.exp(merc)) - np.pi / 2))
    else:
        lat = ymax - (j + 0.5) / height * (ymax - ymin)
    return lon, lat


def _resolve_layer(store, p: dict, key: str):
    """LAYERS/QUERY_LAYERS → (name, schema); exactly one layer required."""
    layers = [s for s in (p.get(key) or p.get("layers") or "").split(",") if s]
    if len(layers) != 1:
        raise WmsError("LayerNotDefined",
                       f"exactly one {key.upper()} entry required")
    name = layers[0]
    try:
        return name, store.get_schema(name)
    except KeyError:
        raise WmsError("LayerNotDefined", f"no such layer {name!r}") from None


def _time_filter(sft, raw: str | None):
    if not raw:
        return None
    if sft.dtg_field is None:
        raise WmsError("InvalidParameterValue", "layer has no time attribute")
    parts = raw.split("/")
    if len(parts) == 1:
        # single instant: DURING has exclusive endpoints (t/t matches
        # nothing), so an instant maps to temporal equality
        return f"{sft.dtg_field} TEQUALS {parts[0]}"
    return f"{sft.dtg_field} DURING {parts[0]}/{parts[1]}"


def _cql_for(sft, p: dict):
    clauses = []
    if p.get("cql_filter"):
        clauses.append(f"({p['cql_filter']})")
    t = _time_filter(sft, p.get("time"))
    if t:
        clauses.append(t)
    cql = " AND ".join(clauses) if clauses else None
    if cql is not None:
        # validate NOW so malformed CQL_FILTER/TIME values come back as WMS
        # ServiceExceptionReports, not a generic JSON 400 from deep inside
        # the query path
        from geomesa_tpu.filter.cql import parse as parse_cql

        try:
            parse_cql(cql)
        except ValueError as e:
            raise WmsError("InvalidParameterValue", str(e)) from None
    return cql


# heat ramp control points (value 0..1 → RGB)
_RAMP = np.array(
    [
        (0.00, 0x2c, 0x7b, 0xb6),
        (0.33, 0x00, 0xcc, 0xcc),
        (0.66, 0xff, 0xff, 0x00),
        (1.00, 0xd7, 0x19, 0x1c),
    ],
    dtype=np.float64,
)


def _colorize(grid: np.ndarray, transparent: bool) -> np.ndarray:
    """(H, W) counts → (H, W, 4) uint8 RGBA via the heat ramp; zero cells
    are fully transparent (or white when TRANSPARENT=FALSE)."""
    h, w = grid.shape
    out = np.zeros((h, w, 4), dtype=np.uint8)
    if not transparent:
        out[:] = (255, 255, 255, 255)
    mx = float(grid.max())
    if mx <= 0:
        return out
    # log scaling keeps sparse tiles visible next to hot spots
    v = np.log1p(grid) / np.log1p(mx)
    stops = _RAMP[:, 0]
    hot = grid > 0
    idx = np.clip(np.searchsorted(stops, v, side="right") - 1, 0,
                  len(stops) - 2)
    t = (v - stops[idx]) / (stops[idx + 1] - stops[idx])
    for c in range(3):
        lo = _RAMP[idx, c + 1]
        hi = _RAMP[idx + 1, c + 1]
        chan = (lo + (hi - lo) * t).astype(np.uint8)
        out[..., c] = np.where(hot, chan, out[..., c])
    out[..., 3] = np.where(hot, 255, out[..., 3])
    return out


def _mercator_resample(grid: np.ndarray, bbox) -> np.ndarray:
    """Resample geographic grid rows onto rows linear in web-mercator y, so
    EPSG:3857 tiles align with basemaps. Nearest-row at tile resolution."""
    h = grid.shape[0]
    _, ymin, _, ymax = bbox
    lo, hi = _merc_bounds(bbox)
    # output row centers (linear in mercator y) → source latitude → row
    centers = lo + (np.arange(h) + 0.5) / h * (hi - lo)
    lats = np.degrees(2 * np.arctan(np.exp(centers)) - np.pi / 2)
    src = np.clip(((lats - ymin) / (ymax - ymin) * h).astype(int), 0, h - 1)
    return grid[src]


def _render_points(store, name, sft, cql, bbox, width, height,
                   transparent: bool, auths=None) -> np.ndarray:
    from geomesa_tpu.filter.cql import parse as parse_cql

    xmin, ymin, xmax, ymax = bbox
    bbox_cql = f"BBOX({sft.geom_field}, {xmin}, {ymin}, {xmax}, {ymax})"
    full = f"{bbox_cql} AND ({cql})" if cql else bbox_cql
    r = store.query(name, Query(filter=parse_cql(full),
                                limit=MAX_POINT_ROWS, auths=auths))
    col = r.table.geom_column()
    grid = np.zeros((height, width), dtype=np.float64)
    if col.x is not None and len(r.table):
        cx = np.clip(((col.x - xmin) / (xmax - xmin) * width).astype(int),
                     0, width - 1)
        cy = np.clip(((col.y - ymin) / (ymax - ymin) * height).astype(int),
                     0, height - 1)
        np.add.at(grid, (cy, cx), 1.0)
    rgba = np.zeros((height, width, 4), dtype=np.uint8)
    if not transparent:
        rgba[:] = (255, 255, 255, 255)
    hit = grid > 0
    # dilate one pixel so single points are visible at tile scale; shift by
    # pad-and-slice (np.roll would wrap a tile-edge point to the far edge)
    padded = np.zeros((height + 2, width + 2), dtype=bool)
    padded[1:-1, 1:-1] = hit
    dil = (
        padded[1:-1, 1:-1] | padded[:-2, 1:-1] | padded[2:, 1:-1]
        | padded[1:-1, :-2] | padded[1:-1, 2:]
    )
    rgba[dil] = (0x1f, 0x78, 0xb4, 255)
    return rgba


def _get_map(store, p: dict, auths=None) -> bytes:
    name, sft = _resolve_layer(store, p, "layers")
    fmt = (p.get("format") or "image/png").lower()
    if fmt != "image/png":
        raise WmsError("InvalidFormat", f"unsupported FORMAT {fmt!r}")
    width, height = _parse_dims(p)
    bbox, crs = _parse_bbox(p)
    transparent = (p.get("transparent", "true").lower() != "false")
    style = (p.get("styles") or "heat").strip().lower() or "heat"
    cql = _cql_for(sft, p)

    if style in ("heat", "density", ""):
        queries = [cql] if auths is None else [Query(filter=cql, auths=auths)]
        grids = store.density_many(
            name, queries, bbox, width=width, height=height, loose=False,
        )
        grid = np.asarray(grids[0])
        if crs == "EPSG:3857":
            grid = _mercator_resample(grid, bbox)
        rgba = _colorize(grid, transparent)
    elif style == "points":
        rgba = _render_points(
            store, name, sft, cql, bbox, width, height, transparent, auths
        )
        if crs == "EPSG:3857":
            rgba = np.stack(
                [_mercator_resample(rgba[..., c].astype(np.float64), bbox)
                 for c in range(4)], axis=-1,
            ).astype(np.uint8)
    else:
        raise WmsError("StyleNotDefined", f"unknown STYLES {style!r}")

    # density grids have row 0 at the SOUTH edge; PNG row 0 is the top
    rgba = rgba[::-1]
    return _encode_png(rgba)


def _get_feature_info(store, p: dict, auths=None):
    """WMS 1.3.0 GetFeatureInfo: the features under a clicked map pixel
    (the GeoServer identify surface the reference serves through its WMS
    layer). Takes the GetMap tile geometry plus I/J pixel coordinates
    (X/Y under the 1.1.x binding), a BUFFER pixel tolerance, and
    FEATURE_COUNT; returns GeoJSON (``INFO_FORMAT=application/json``) or a
    plain-text listing (default, matching the WMS spec default)."""
    from geomesa_tpu.filter.cql import parse as parse_cql

    name, sft = _resolve_layer(store, p, "query_layers")
    width, height = _parse_dims(p)
    bbox, crs = _parse_bbox(p)
    raw_i = p.get("i", p.get("x"))
    raw_j = p.get("j", p.get("y"))
    if raw_i is None or raw_j is None:
        raise WmsError("MissingParameterValue",
                       "I/J pixel coordinates are required")
    try:
        i, j = int(raw_i), int(raw_j)
    except ValueError:
        raise WmsError("InvalidPoint", f"bad I/J {raw_i!r}/{raw_j!r}") from None
    if not (0 <= i < width and 0 <= j < height):
        raise WmsError("InvalidPoint",
                       f"I/J ({i}, {j}) outside the {width}x{height} map")
    try:
        count = max(1, int(p.get("feature_count", "1")))
        buf_px = max(0, int(p.get("buffer", "3")))
    except ValueError:
        raise WmsError("InvalidParameterValue",
                       "bad FEATURE_COUNT/BUFFER") from None

    # the search window is the clicked pixel dilated by BUFFER pixels,
    # mapped through the same pixel->geography transform GetMap renders
    # with (so a click on a drawn point finds that point, 4326 or 3857)
    x1, ylo = _pixel_lonlat(i - buf_px - 0.5, j + buf_px + 0.5,
                            width, height, bbox, crs)
    x2, yhi = _pixel_lonlat(i + buf_px + 0.5, j - buf_px - 0.5,
                            width, height, bbox, crs)
    cql = _cql_for(sft, p)
    window = (f"BBOX({sft.geom_field}, {min(x1, x2)}, {min(ylo, yhi)}, "
              f"{max(x1, x2)}, {max(ylo, yhi)})")
    full = f"{window} AND ({cql})" if cql else window
    r = store.query(name, Query(filter=parse_cql(full), limit=count,
                                auths=auths))

    fmt = (p.get("info_format") or "text/plain").lower()
    if "json" in fmt:
        import json

        from geomesa_tpu.web.formats import format_table

        payload, _ = format_table(r.table, "geojson")
        # canonical JSON MIME, never the raw request parameter (echoing an
        # unvalidated value into a response header invites header injection)
        return 200, json.dumps(payload), "application/json"
    if fmt not in ("text/plain", "text"):
        raise WmsError("InvalidFormat",
                       f"unsupported INFO_FORMAT {p.get('info_format')!r} "
                       "(supported: application/json, text/plain)")
    lines = [f"GetFeatureInfo {name} ({len(r.table)} feature(s))"]
    attrs = [a.name for a in sft.attributes]
    for k in range(len(r.table)):
        rec = r.table.record(k)
        lines.append(f"fid = {r.table.fids[k]}")
        for a in attrs:
            lines.append(f"  {a} = {rec.get(a)}")
    return 200, "\n".join(lines) + "\n", "text/plain"


def _legend_graphic(store, p: dict) -> bytes:
    """WMS GetLegendGraphic (SLD-WMS extension): a PNG legend for the two
    styles — the heat ramp as a vertical low→high gradient, or the point
    swatch. Map clients fetch this next to GetMap to label layers."""
    _resolve_layer(store, p, "layer")  # unknown layers error as elsewhere
    style = (p.get("style") or p.get("styles") or "heat").strip().lower()
    width, height = _parse_dims(p, dw="20", dh="128")
    fmt = (p.get("format") or "image/png").lower()
    if fmt != "image/png":
        raise WmsError("InvalidFormat", f"unsupported FORMAT {fmt!r}")
    if style in ("heat", "density", ""):
        # one column of the ramp replicated across width, rendered through
        # _colorize so the legend can never drift from the tile colors;
        # values span (0, 1] — exact zero means "no data" and renders
        # transparent, which would blank the legend's low end
        ramp = _colorize(
            np.linspace(0.0, 1.0, height + 1,
                        dtype=np.float64)[1:][:, None], True
        )
        rgba = np.repeat(ramp, width, axis=1)
        rgba = rgba[::-1]  # high values at the TOP of the legend
    elif style == "points":
        rgba = np.zeros((height, width, 4), dtype=np.uint8)
        rgba[:] = (0x1f, 0x78, 0xb4, 255)  # the GetMap point color
    else:
        raise WmsError("StyleNotDefined", f"unknown STYLE {style!r}")
    return _encode_png(rgba)


def _encode_png(rgba: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(rgba, mode="RGBA").save(buf, format="PNG")
    return buf.getvalue()
