"""geomesa_tpu subpackage.

Re-exports :func:`plan_signature` — the canonical (type, plan-shape) key.
One string keys four per-plan surfaces: the adaptive cost table
(:mod:`geomesa_tpu.planning.costmodel`), the query lens's retained
latency rings (:mod:`geomesa_tpu.obs.lens`), the host-roundtrip ledger's
fusion-opportunity rollups (:mod:`geomesa_tpu.obs.ledger`), and flight
audit records. Planning consumers import it from here; the definition
lives in :mod:`geomesa_tpu.obs.devmon` (kept jax-free) so telemetry-only
processes never pull the planner's index machinery.
"""

from geomesa_tpu.obs.devmon import plan_signature  # noqa: F401

__all__ = ["plan_signature"]
