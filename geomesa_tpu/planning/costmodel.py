"""Adaptive cost model: live-stats seeds corrected by observed costs.

ROADMAP item 3. The reference's ``StrategyDecider`` is cost-based but
STATIC — stats sketches estimate rows, a fixed multiplier penalizes
attribute joins, and the estimate is never compared with what executions
actually cost. *Adaptive Geospatial Joins for Modern Hardware* (PAPERS.md)
shows the winning strategy flips with selectivity AND hardware; GeoBlocks
shows cached/pre-aggregated answers beat rescans only in the right regime.
This module closes the loop:

- **Seeds** come from the stats sketches
  (:meth:`geomesa_tpu.stats.store_stats.StoreStats.selectivity`): a row
  estimate converted to a synthetic cost, used only for RELATIVE ranking
  until real observations exist.
- **Observations** come from the devmon :class:`~geomesa_tpu.obs.devmon.
  CostTable` (``/api/obs/costs``), fed by every fully-planned query audit
  and by the per-route ``sel:*`` / ``gagg:*`` / ``join:*`` signatures the
  dispatch layers record. Once every candidate of a decision has enough
  observations, measured p50 wall-ms replaces the seed ranking outright.
- **Bounded exploration** (the generalized ``choose_agg_path`` tick/probe
  mechanism): every ``PROBE_EVERY``-th consult of a decision routes to the
  LOSING candidate so no profile freezes — the winner can never starve the
  loser of observations, and the verdict can flip when data or hardware
  shifts. Probes are bounded: a candidate whose seed estimate is more than
  ``PROBE_MAX_RATIO`` worse than the best is never probed (re-measuring a
  full scan against an id lookup would be pure regression).
- **SLO-aware tie-breaking**: when the caller reports error-budget burn,
  near-tied candidates (within ``TIE_BAND``) resolve to the LOWER-VARIANCE
  plan (smallest p95/p50 spread) — under burn, predictability beats a thin
  median win.
- **Calibration**: every (predicted, actual) pair lands in an online
  per-(type, signature) calibration table — mean absolute relative error,
  signed bias — served with ``/api/obs/costs`` and rendered by
  ``explain(analyze=True)``, so model drift is observable before it costs
  latency.

Locking: one leaf lock for the calibration table (same tier as the devmon
locks, docs/concurrency.md). No jax at module level
(``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from geomesa_tpu.analysis.contracts import cache_surface, feedback_sink

__all__ = [
    "Candidate", "CostModel", "MIN_OBSERVATIONS", "PROBE_EVERY",
    "PROBE_MAX_RATIO", "TIE_BAND", "install", "model",
]

# observations a signature needs before its measured p50 outranks seeds
MIN_OBSERVATIONS = 8
# consults between probes of the losing candidate (choose_agg_path legacy
# name AGG_PROBE_EVERY re-exports from planner)
PROBE_EVERY = 16
# never probe a candidate whose seed estimate is worse than the best by
# more than this ratio (bounded exploration: a 10M-row full scan must not
# be re-measured against a 100-row id lookup)
PROBE_MAX_RATIO = 32.0
# candidates within this relative band of the best are "near ties" —
# under SLO burn the lower-variance one wins
TIE_BAND = 0.25

# synthetic ms per estimated row: converts a stats row estimate into a
# seed cost. Only RELATIVE ordering among seeds matters (seeds never
# compare against measured ms — learned mode requires every candidate
# observed), so the constant is arbitrary but keeps explain output legible
SEED_MS_PER_ROW = 0.001


@dataclass
class Candidate:
    """One strategy/route option inside a decision."""

    name: str
    signature: str  # cost-table signature ("sel:planned", "z3:" prefix...)
    est_rows: float | None = None  # stats seed (rows)
    seed_ms: float | None = None  # synthetic seed cost (relative only)
    prefix: bool = False  # signature is a prefix over audit signatures
    observed: dict | None = field(default=None, repr=False)
    predicted_ms: float | None = None  # measured p50 when trained

    def seed(self) -> float:
        if self.seed_ms is not None:
            return float(self.seed_ms)
        if self.est_rows is not None:
            return float(self.est_rows) * SEED_MS_PER_ROW
        return float("inf")


class _Calibration:
    __slots__ = ("count", "abs_rel_err_sum", "signed_rel_err_sum",
                 "last_predicted", "last_actual")

    def __init__(self):
        self.count = 0
        self.abs_rel_err_sum = 0.0
        self.signed_rel_err_sum = 0.0
        self.last_predicted = 0.0
        self.last_actual = 0.0


def calibration_error(predicted_ms: float, actual_ms: float) -> float:
    """Relative prediction error vs the ACTUAL cost: |pred - actual| /
    max(actual, epsilon). 0.0 = perfect; 1.0 = off by the full actual."""
    return abs(predicted_ms - actual_ms) / max(actual_ms, 1e-6)


@cache_surface(name="planner-calibration-table", keyed_by="type_name",
               purge=("forget",))
class CostModel:
    """The decision engine: rank candidates by learned cost when every
    candidate is trained, by stats seeds otherwise; probe the loser on a
    bounded schedule; track prediction calibration."""

    def __init__(self, table=None, min_observations: int = MIN_OBSERVATIONS,
                 probe_every: int = PROBE_EVERY, max_entries: int = 256):
        self._table = table
        self.min_observations = min_observations
        self.probe_every = probe_every
        self._cal_lock = threading.Lock()  # leaf: calibration entries
        from collections import OrderedDict

        self._cal: "OrderedDict[tuple, _Calibration]" = OrderedDict()
        self._cal_max = max_entries

    def table(self):
        """The live observed-cost table (the devmon singleton unless one
        was injected for tests) — resolved per call so test installs via
        ``devmon.install`` are honored."""
        if self._table is not None:
            return self._table
        from geomesa_tpu.obs import devmon

        return devmon.costs()

    # -- prediction ----------------------------------------------------------
    def predict(self, type_name: str, signature: str,
                prefix: bool = False) -> dict | None:
        """Current cost profile for one signature — exact, or aggregated
        over every audit signature starting with ``signature`` (strategy
        decisions key by index name, audits append interval-bucket/agg)."""
        t = self.table()
        if not prefix:
            return t.predict(type_name, signature)
        agg = getattr(t, "predict_prefix", None)
        return agg(type_name, signature) if agg is not None else None

    def _fill(self, type_name: str, c: Candidate,
              min_obs: int | None = None) -> None:
        need = self.min_observations if min_obs is None else min_obs
        obs = self.predict(type_name, c.signature, prefix=c.prefix)
        c.observed = obs
        if obs is not None and obs.get("observations", 0) >= need:
            c.predicted_ms = obs["wall_ms_p50"]

    # -- the decision --------------------------------------------------------
    def choose(self, type_name: str, decision: str,
               candidates: list[Candidate], *, under_burn: bool = False,
               probe: bool = True,
               min_observations: int | None = None,
               ) -> tuple[Candidate, list[Candidate], str]:
        """Pick one candidate. Returns (winner, ranked, source) where
        ``ranked`` is best-first and ``source`` is one of ``cost-model``
        (every candidate trained — measured p50 ranking), ``stats``
        (seed ranking), or ``probe`` (scheduled re-measure of the loser).

        The probe schedule rides the cost table's per-(type, decision)
        consult counter — never observation counts, which the winner
        freezes by starving the loser (see ``choose_agg_path``)."""
        if not candidates:
            raise ValueError(f"decision {decision!r}: no candidates")
        for c in candidates:
            self._fill(type_name, c, min_observations)
        trained = all(c.predicted_ms is not None for c in candidates)
        if trained:
            ranked = sorted(candidates, key=lambda c: c.predicted_ms)
            source = "cost-model"
            if under_burn and len(ranked) > 1:
                best = ranked[0].predicted_ms
                near = [c for c in ranked
                        if c.predicted_ms <= best * (1.0 + TIE_BAND)]
                if len(near) > 1:
                    near.sort(key=lambda c: _spread(c.observed))
                    if near[0] is not ranked[0]:
                        ranked.remove(near[0])
                        ranked.insert(0, near[0])
                        source = "cost-model/slo"
        else:
            ranked = sorted(candidates, key=lambda c: c.seed())
            source = "stats"
        if probe and len(ranked) > 1:
            tick = self.table().tick(type_name, f"route:{decision}")
            if tick % self.probe_every == 0:
                # bounded exploration: re-measure the best LOSER whose seed
                # isn't catastrophically worse than the winner's. A zero
                # seed (a 0-row stats estimate) gives the bound nothing to
                # anchor on — skip the probe rather than waive the bound
                # (probing a full scan against a 0-row estimate is exactly
                # what PROBE_MAX_RATIO exists to prevent); fixed route
                # seeds (selects/agg/join) are always positive, so those
                # decisions keep their probe cadence.
                floor = ranked[0].seed()
                if floor > 0:
                    for loser in ranked[1:]:
                        if loser.seed() <= floor * PROBE_MAX_RATIO:
                            ranked = [loser] + [
                                c for c in ranked if c is not loser]
                            return ranked[0], ranked, "probe"
        return ranked[0], ranked, source

    # -- canned decisions (the dispatch layers' entry points) ----------------
    def choose_select_route(self, type_name: str) -> str:
        """Row-retrieval dispatch route for ONE planned select:
        ``"twopass"`` (per-query candidate-slot count+gather,
        ``TpuBackend._mesh_select_positions``) or ``"planned"`` (the
        batched block-pair steps run with a singleton batch — the same
        compiled executables ``select_many`` uses, so both modes share one
        jit cache). Observed costs land under ``sel:twopass`` /
        ``sel:planned`` in the dispatch layer — ONE pooled profile per
        type across plan widths (not per interval bucket: a width-aware
        split would multiply each type's training time). Until both
        routes are trained the twopass seed wins (it gathers only
        candidate slots where the planned route reads whole blocks) and
        the probe schedule measures the planned route anyway."""
        win, _, _ = self.choose(type_name, "select", [
            Candidate("twopass", "sel:twopass", seed_ms=1.0),
            Candidate("planned", "sel:planned", seed_ms=2.0),
        ])
        return win.name

    def choose_agg_path(self, type_name: str,
                        min_observations: int | None = None) -> str:
        """Grouped-aggregation route: GeoBlocks ``"pyramid"`` or fused
        device ``"scan"`` (the decision ``ops/geoblocks.py`` consults).
        Pyramid is the seeded default — repeated polygon/bbox aggregation
        is exactly its regime and boundary refinement is O(perimeter)
        where the scan is O(n)."""
        win, _, _ = self.choose(
            type_name, "gagg",
            [
                Candidate("pyramid", "gagg:pyramid", seed_ms=1.0),
                Candidate("scan", "gagg:scan", seed_ms=2.0),
            ],
            min_observations=min_observations,
        )
        return win.name

    def choose_join_path(self, type_name: str, pair_density: float) -> str:
        """Spatial-join kernel choice: ``"block"`` (index-pruned
        block-sparse join — wins when polygon bboxes touch few blocks) or
        ``"dense"`` (full ``points_in_polygons`` pass — wins when measured
        pair density is high enough that block planning + gather overhead
        buys nothing). ``pair_density`` = planned candidate pairs /
        (points x polygons), measured from the block plan."""
        dense_seed = 1.0 if pair_density >= 0.25 else 2.0
        win, _, _ = self.choose(type_name, "join", [
            Candidate("block", "join:block", seed_ms=3.0 - dense_seed),
            Candidate("dense", "join:dense", seed_ms=dense_seed),
        ])
        return win.name

    # -- calibration ---------------------------------------------------------
    @feedback_sink
    def record_calibration(self, type_name: str, signature: str,
                           predicted_ms: float, actual_ms: float) -> None:
        err = calibration_error(predicted_ms, actual_ms)
        signed = (predicted_ms - actual_ms) / max(actual_ms, 1e-6)
        key = (type_name, signature)
        with self._cal_lock:
            e = self._cal.get(key)
            if e is None:
                e = self._cal[key] = _Calibration()
                while len(self._cal) > self._cal_max:
                    self._cal.popitem(last=False)
            else:
                self._cal.move_to_end(key)
            e.count += 1
            e.abs_rel_err_sum += err
            e.signed_rel_err_sum += signed
            e.last_predicted = float(predicted_ms)
            e.last_actual = float(actual_ms)

    def forget(self, type_name: str) -> None:
        """Drop one type's calibration rows (schema delete/rename — the
        cost-table ``forget`` analog)."""
        with self._cal_lock:
            for k in [k for k in self._cal if k[0] == type_name]:
                del self._cal[k]

    # -- persistence (the workload-dir cost sidecar, obs.devmon) -------------
    def calibration_state(self) -> dict:
        """JSON-able calibration state — saved with the cost-table
        snapshot so predicted-vs-actual drift accounting survives
        restarts alongside the p50 rankings it judges."""
        with self._cal_lock:
            entries = [
                {"type": t, "signature": sig, "count": e.count,
                 "abs_rel_err_sum": round(e.abs_rel_err_sum, 6),
                 "signed_rel_err_sum": round(e.signed_rel_err_sum, 6),
                 "last_predicted": round(e.last_predicted, 4),
                 "last_actual": round(e.last_actual, 4)}
                for (t, sig), e in self._cal.items()
            ]
        return {"entries": entries}

    def load_calibration_state(self, state: dict) -> None:
        """Restore :meth:`calibration_state` (same merge-by-richness
        semantics as ``CostTable.load_state``: a snapshot row never
        regresses a live entry that has already learned past it)."""
        for row in state.get("entries", []):
            key = (row["type"], row["signature"])
            e = _Calibration()
            e.count = int(row.get("count", 0))
            e.abs_rel_err_sum = float(row.get("abs_rel_err_sum", 0.0))
            e.signed_rel_err_sum = float(row.get("signed_rel_err_sum", 0.0))
            e.last_predicted = float(row.get("last_predicted", 0.0))
            e.last_actual = float(row.get("last_actual", 0.0))
            with self._cal_lock:
                live = self._cal.get(key)
                if live is not None and live.count >= e.count:
                    continue
                self._cal[key] = e
                self._cal.move_to_end(key)
                while len(self._cal) > self._cal_max:
                    self._cal.popitem(last=False)

    def calibration_report(self) -> dict:
        """The drift surface served with ``GET /api/obs/costs``: per-(type,
        signature) mean absolute relative error (MAPE vs actual), signed
        bias (positive = over-prediction), sample count, and the last
        predicted/actual pair; plus an overall observation-weighted MAPE."""
        with self._cal_lock:
            items = [(k, e.count, e.abs_rel_err_sum, e.signed_rel_err_sum,
                      e.last_predicted, e.last_actual)
                     for k, e in self._cal.items()]
        rows = []
        tot_n = 0
        tot_err = 0.0
        for (t, sig), n, abs_sum, signed_sum, lp, la in items:
            rows.append({
                "type": t,
                "signature": sig,
                "count": n,
                "mean_abs_rel_err": round(abs_sum / n, 4),
                "mean_signed_rel_err": round(signed_sum / n, 4),
                "last_predicted_ms": round(lp, 3),
                "last_actual_ms": round(la, 3),
            })
            tot_n += n
            tot_err += abs_sum
        rows.sort(key=lambda r: (r["type"], r["signature"]))
        return {
            "entries": rows,
            "entry_count": len(rows),
            "overall_mean_abs_rel_err": (
                round(tot_err / tot_n, 4) if tot_n else None
            ),
            "samples": tot_n,
        }


def _spread(observed: dict | None) -> float:
    """p95/p50 dispersion — the variance proxy SLO tie-breaking minimizes
    (a plan with a fat tail loses a near tie under burn)."""
    if not observed:
        return float("inf")
    p50 = observed.get("wall_ms_p50") or 0.0
    p95 = observed.get("wall_ms_p95")
    if p95 is None or p50 <= 0:
        return float("inf")
    return p95 / p50


# -- process-wide singleton ---------------------------------------------------

_model = CostModel()


def model() -> CostModel:
    return _model


def install(new_model: "CostModel | None" = None) -> CostModel:
    """Swap the process singleton (test isolation); returns the previous
    model. Pass None to reset to a fresh default model."""
    global _model
    prev = _model
    _model = new_model if new_model is not None else CostModel()
    return prev
