"""Query planner: configure → decide strategy → plan ranges → execute → reduce.

The ``QueryPlanner`` / ``StrategyDecider`` / ``FilterSplitter`` roles
(``geomesa-index-api/.../planning/QueryPlanner.scala:43,63``,
``StrategyDecider.scala:41-67``; call stack SURVEY.md §3.3). Planning is
host-side Python; execution is a backend call (brute-force oracle or TPU
kernels). The residual ("secondary") filter is always the full original filter
— cheap to re-apply vectorized, and it makes every scan plan trivially sound.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.curve.binned_time import BinnedTime
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.bounds import Extraction, extract
from geomesa_tpu.filter.cql import parse as parse_cql
from geomesa_tpu.index.api import DEFAULT_MAX_RANGES, FeatureIndex, IndexPlan
from geomesa_tpu.index.attribute import AttributeIndex
from geomesa_tpu.index.z2 import IdIndex, XZ2Index, Z2Index
from geomesa_tpu.index.z3 import XZ3Index, Z3Index
from geomesa_tpu.schema.sft import FeatureType

ALL_INDEX_TYPES = [Z3Index, XZ3Index, Z2Index, XZ2Index, IdIndex]
INDEX_BY_NAME = {c.name: c for c in ALL_INDEX_TYPES}


@dataclass
class Query:
    """A query against one feature type (OGC ``Query`` role).

    ``filter``: CQL string or AST node. ``properties``: projection (None = all).
    ``hints``: QueryHints analog (``index/conf/QueryHints.scala``) — e.g.
    ``{"index": "z2"}`` to force an index, ``{"loose_bbox": True}``,
    aggregation hints (``density``, ``stats``, ``bin``...).
    """

    filter: Any = None
    properties: list[str] | None = None
    sort_by: tuple[str, bool] | None = None  # (field, descending)
    limit: int | None = None
    # OGC Query.startIndex paging offset: rows skipped after sort, before limit
    start_index: int | None = None
    hints: dict = field(default_factory=dict)
    # authorizations for record-level visibility filtering (geomesa-security
    # role); None = unrestricted, [] = only unlabeled records visible
    auths: list[str] | None = None

    def resolved_filter(self) -> ast.Filter:
        if self.filter is None:
            return ast.Include()
        if isinstance(self.filter, str):
            return parse_cql(self.filter)
        return self.filter


@dataclass
class QueryPlanInfo:
    """Explain output (``Explainer`` role, ``index/utils/Explainer.scala:16``)."""

    type_name: str
    filter_str: str
    index_name: str
    extraction: Extraction
    n_intervals: int
    n_candidates: int
    plan_ms: float
    notes: list[str] = field(default_factory=list)
    # multi-plan union (FilterSplitter role): [(index_name, IndexPlan,
    # Extraction)] — when set, the scan is the union of these sub-scans and
    # ``index_name`` reads "union(...)"
    sub_plans: list = None
    # adaptive-planner decision record (planning/costmodel.py): how the
    # strategy was chosen ("cost-model" | "cost-model/slo" | "probe" |
    # "stats" | "heuristic" | "forced" | "fid"), the model's predicted
    # wall-ms for the winner (None before training), the stats row
    # estimate, and every REJECTED alternative as {name, est_rows,
    # observed_ms_p50, observations} — what explain() renders
    strategy_source: str = ""
    predicted_ms: float | None = None
    est_rows: float | None = None
    alternatives: list = None
    # high-selectivity fast path: decomposition ran with the reduced range
    # budget and the union search was skipped (CHEAP_SELECT_ROWS)
    cheap: bool = False

    def signature(self, q=None) -> str:
        """The shared plan-shape key (``devmon.plan_signature``): what the
        adaptive cost table, the query lens, and the roundtrip ledger all
        key their per-plan profiles by. Exposed here so explain output and
        lens/fusion-report entries cross-reference without re-deriving."""
        from geomesa_tpu.obs import devmon as _devmon
        return _devmon.plan_signature(self, q)

    def explain(self) -> str:
        lines = [
            f"Planning '{self.type_name}' {self.filter_str}",
            f"  Index: {self.index_name}",
            f"  Signature: {self.signature()}",
            f"  Spatial bounds: {self.extraction.boxes}",
            f"  Temporal bounds: {self.extraction.intervals}",
            f"  Scan intervals: {self.n_intervals} covering {self.n_candidates} rows",
            f"  Planning time: {self.plan_ms:.2f} ms",
        ]
        if self.strategy_source:
            head = f"  Strategy: {self.strategy_source}"
            if self.est_rows is not None:
                head += f", estimated {self.est_rows:.0f} rows"
            if self.predicted_ms is not None:
                head += f", predicted {self.predicted_ms} ms p50"
            if self.cheap:
                head += " [cheap fast path]"
            lines.append(head)
        for alt in self.alternatives or []:
            obs_txt = (
                f"observed {alt['observed_ms_p50']} ms p50"
                f" (n={alt['observations']})"
                if alt.get("observed_ms_p50") is not None
                else "no observations"
            )
            lines.append(
                f"  Rejected: {alt['name']} ≈ {alt['est_rows']:.0f} rows, "
                + obs_txt
            )
        lines += [f"  Note: {n}" for n in self.notes]
        return "\n".join(lines)


def _extract_fids(f: ast.Filter):
    """Top-level fid filter (possibly AND'd): the ID-index fast path."""
    if isinstance(f, ast.FidIn):
        return f.fids
    if isinstance(f, ast.And):
        for c in f.children:
            if isinstance(c, ast.FidIn):
                return c.fids
    return None


class StrategyDecider:
    """Pick the best index for an extraction.

    Reference: ``StrategyDecider.scala:41-140`` — cost-based via stats
    estimates when available (``CostBasedStrategyDecider``), falling back to a
    specificity heuristic (id > attr-equality > z3 > z2 > full scan) without
    stats. Attribute-index costs get a residual-work multiplier (the
    reference's join-cost penalty for reduced attribute indexes).

    The static estimate is only the SEED: pass ``type_name`` and a
    ``cost_model`` (:mod:`geomesa_tpu.planning.costmodel`) and the decision
    upgrades to learned per-(type, index) observed costs once every
    competing strategy is trained, with bounded probes of losing strategies
    and SLO-aware tie-breaking (see docs/planning.md).
    """

    ATTR_COST_MULTIPLIER = 2.0

    @staticmethod
    def choose(
        indices: dict[str, FeatureIndex],
        e: Extraction,
        f: ast.Filter,
        hints: dict,
        stats=None,
        trace: list | None = None,
        type_name: str | None = None,
        cost_model=None,
        under_burn: bool = False,
        decision: dict | None = None,
    ) -> tuple[str, Any]:
        notes = trace if trace is not None else []
        dec = decision if decision is not None else {}
        dec.setdefault("alternatives", [])
        forced = hints.get("index")
        if forced:
            if forced not in indices:
                raise ValueError(f"forced index {forced!r} not available")
            notes.append(f"index forced by hint: {forced}")
            dec["source"] = "forced"
            return forced, None
        fids = _extract_fids(f)
        if fids is not None and "id" in indices:
            dec["source"] = "fid"
            return "id", fids
        if stats is not None and stats.count > 0:
            est = StrategyDecider.estimate_rows(indices, e, stats)
            if est:
                if cost_model is not None and type_name and len(est) > 1:
                    name = StrategyDecider._model_based(
                        est, type_name, cost_model, under_burn, notes, dec
                    )
                else:
                    name = min(est.items(), key=lambda kv: kv[1])[0]
                    ranked = ", ".join(
                        f"{n}≈{c:.0f}"
                        for n, c in sorted(est.items(), key=lambda kv: kv[1])
                    )
                    notes.append(
                        f"cost-based (estimated rows): {ranked} → {name}")
                    dec["source"] = "stats"
                    dec["alternatives"] = [
                        {"name": n, "est_rows": c, "observed_ms_p50": None,
                         "observations": 0}
                        for n, c in sorted(
                            est.items(), key=lambda kv: kv[1])
                        if n != name
                    ]
                dec["est_rows"] = est[name]
                return name, None
        name = StrategyDecider._heuristic(indices, e)
        notes.append(f"heuristic choice (no usable stats): {name}")
        dec["source"] = "heuristic"
        return name, None

    @staticmethod
    def _model_based(est: dict, type_name: str, cost_model, under_burn: bool,
                     notes: list, dec: dict) -> str:
        """Rank strategies through the cost model: stats row estimates
        seed the candidates (signature prefix = the index name, matching
        every audit signature the strategy produced), learned p50 wall-ms
        takes over once all candidates are trained, and the probe schedule
        re-measures losers so no profile freezes."""
        from geomesa_tpu.planning.costmodel import Candidate

        cands = [
            Candidate(n, f"{n}:", est_rows=r, prefix=True)
            for n, r in est.items()
        ]
        win, ranked, source = cost_model.choose(
            type_name, "strategy", cands, under_burn=under_burn
        )
        rank_txt = ", ".join(
            f"{c.name}≈{c.predicted_ms}ms" if c.predicted_ms is not None
            else f"{c.name}≈{c.est_rows:.0f}rows"
            for c in ranked
        )
        notes.append(f"adaptive ({source}): {rank_txt} → {win.name}")
        dec["source"] = source
        dec["predicted_ms"] = win.predicted_ms
        dec["alternatives"] = [
            {
                "name": c.name,
                "est_rows": c.est_rows,
                "observed_ms_p50": (
                    c.observed.get("wall_ms_p50") if c.observed else None
                ),
                "observations": (
                    c.observed.get("observations", 0) if c.observed else 0
                ),
            }
            for c in ranked[1:]
        ]
        return win.name

    @staticmethod
    def estimate_rows(indices, e: Extraction, stats) -> dict[str, float]:
        """Per-strategy stats row estimates (the ``CostBasedStrategyDecider``
        table): every servable index → estimated matching rows, attribute
        indexes penalized by :data:`ATTR_COST_MULTIPLIER`. Empty when no
        index can be costed (caller falls back to the heuristic)."""
        costs: dict[str, float] = {}
        for name, index in indices.items():
            if name == "id":
                continue  # only via fid fast path
            if name in ("z3", "xz3"):
                # z3 competes only when the filter has temporal bounds — a
                # spatial-only query would pay a per-time-bin range
                # decomposition for the same selectivity z2 gets in one pass
                # (the reference offers z3 strategies only for dtg-bounded
                # filters, Z3IndexKeySpace.getIndexValues)
                if e.spatially_bounded and not e.temporally_bounded and (
                    "z2" in indices or "xz2" in indices
                ):
                    continue
                if not (e.spatially_bounded or e.temporally_bounded):
                    costs[name] = float(stats.count)
                else:
                    # estimation always uses the point z3 curve against the
                    # Z3Histogram (built only for point schemas; otherwise
                    # falls back to total count inside the estimator)
                    costs[name] = stats.estimate_spatiotemporal(
                        e, _z3_est_sfc(index), index.binned
                    )
            elif name in ("z2", "xz2"):
                if not e.spatially_bounded:
                    costs[name] = float(stats.count)
                else:
                    # spatial-only estimate: all bins, coarse cover
                    e_sp = Extraction(e.boxes, None, {})
                    costs[name] = stats.estimate_spatiotemporal(
                        e_sp, _z3_est_sfc(index), BinnedTime(index.sft.z3_interval)
                    )
            elif name.startswith("attr:"):
                attr = name.split(":", 1)[1]
                bounds = e.attributes.get(attr)
                if bounds is None:
                    continue  # can't serve
                est = stats.estimate_attr(attr, bounds)
                costs[name] = est * StrategyDecider.ATTR_COST_MULTIPLIER
        return costs

    @staticmethod
    def _heuristic(indices, e: Extraction) -> str:
        for name in indices:
            if name.startswith("attr:") and e.attr_bounded(name.split(":", 1)[1]):
                bounds = e.attributes[name.split(":", 1)[1]]
                if all(lo is not None and lo == hi for lo, hi, _, _ in bounds):
                    return name  # equality on an indexed attribute
        temporal = e.temporally_bounded
        spatial = e.spatially_bounded
        if temporal and ("z3" in indices or "xz3" in indices):
            return "z3" if "z3" in indices else "xz3"
        if spatial and ("z2" in indices or "xz2" in indices):
            return "z2" if "z2" in indices else "xz2"
        for name in ("z3", "xz3", "z2", "xz2", "id"):
            if name in indices:
                return name
        return next(iter(indices))  # whatever is configured (full scan)


def _z3_est_sfc(index):
    """The point z3 curve used for selectivity estimation (shared by the z3
    and z2 costing branches)."""
    from geomesa_tpu.curve.sfc import z3_sfc

    return z3_sfc(index.sft.z3_interval)


# high-selectivity fast path (the bench-6 regression fix): when stats
# estimate at most this many matching rows, decomposition runs with the
# reduced range budget below and the union search is skipped outright —
# planning cost scales with range count, and a query returning a few
# thousand rows must not pay a 2000-range decomposition to save device
# work it doesn't have (results are identical either way: coarser ranges
# only widen the int-domain candidate superset the exact residual culls)
CHEAP_SELECT_ROWS = 4096
CHEAP_MAX_RANGES = 64


class QueryPlanner:
    """Plans one query over one feature type's built indexes.

    ``cost_model``: the adaptive cost model consulted for strategy choice
    (default: the process singleton, :func:`geomesa_tpu.planning.costmodel.
    model`); pass ``False`` to force the static stats-only decider (the
    union-arm sub-planner does — per-arm probes would make union plans
    nondeterministic)."""

    def __init__(
        self, sft: FeatureType, indices: dict[str, FeatureIndex], stats=None,
        cost_model=None,
    ):
        self.sft = sft
        self.indices = indices
        self.stats = stats
        if cost_model is None:
            from geomesa_tpu.planning import costmodel

            cost_model = costmodel.model()
        self.cost_model = cost_model or None  # False → None (static)
        self.indexed_attrs = tuple(
            name.split(":", 1)[1] for name in indices if name.startswith("attr:")
        )

    def plan(
        self, q: Query, max_ranges: int = DEFAULT_MAX_RANGES,
        under_burn: bool = False,
    ) -> tuple[IndexPlan, ast.Filter, QueryPlanInfo]:
        t0 = time.perf_counter()
        f = q.resolved_filter()
        from geomesa_tpu.filter.bounds import coerce_attr_bounds

        e = extract(
            f, self.sft.geom_field, self.sft.dtg_field, attrs=self.indexed_attrs
        )
        e = coerce_attr_bounds(self.sft, e)
        notes: list[str] = []
        dec: dict = {}
        name, fids = StrategyDecider.choose(
            self.indices, e, f, q.hints, self.stats, trace=notes,
            type_name=self.sft.name, cost_model=self.cost_model,
            under_burn=under_burn, decision=dec,
        )
        index = self.indices[name]
        for attr, bounds in e.attributes.items():
            if bounds is not None:
                notes.append(f"attribute bounds: {attr} in {bounds}")
        est_rows = dec.get("est_rows")
        # cheap means SELECTIVE, not small-absolute: a tiny store's full
        # scan estimates under the row threshold but deserves the whole
        # machinery; and a top-level OR keeps the union search — that IS
        # the machinery built for it (a cross-attribute OR's single-index
        # plan can be a full scan the union beats by orders of magnitude)
        cheap = (
            fids is None
            and not isinstance(f, ast.Or)
            and est_rows is not None
            and est_rows <= CHEAP_SELECT_ROWS
            and self.stats is not None
            and est_rows <= 0.25 * max(self.stats.count, 1)
        )
        if cheap:
            max_ranges = min(max_ranges, CHEAP_MAX_RANGES)
            notes.append(
                f"cheap fast path: ≈{est_rows:.0f} rows ≤ "
                f"{CHEAP_SELECT_ROWS} — range budget {max_ranges}, "
                "union search skipped"
            )
        with obs.span("decompose", index=name):
            if fids is not None and isinstance(index, IdIndex):
                plan = index.plan_fids(fids)
                notes.append(f"id lookup on {len(fids)} fids")
            else:
                plan = index.plan(e, max_ranges)

        # FilterSplitter role (FilterSplitter.scala:25): a top-level OR whose
        # arms each bind a DIFFERENT index (e.g. cross-attribute ORs) can run
        # as a union of tight scans instead of one loose/full scan — taken
        # when the combined sub-scan candidates undercut the single plan
        if "index" not in q.hints and not cheap:
            union = self._union_plans(f, max_ranges, notes)
            if union is not None:
                union_cand = sum(p.n_candidates for _, p, _ in union)
                if union_cand < plan.n_candidates:
                    notes.append(
                        "union plan: "
                        + " + ".join(
                            f"{n}({p.n_candidates})" for n, p, _ in union
                        )
                        + f" = {union_cand} candidates vs {name}"
                        f"({plan.n_candidates}) single-index"
                    )
                    info = QueryPlanInfo(
                        type_name=self.sft.name,
                        filter_str=str(q.filter) if q.filter is not None else "INCLUDE",
                        index_name="union(" + ",".join(n for n, _, _ in union) + ")",
                        extraction=e,
                        n_intervals=sum(len(p.intervals) for _, p, _ in union),
                        n_candidates=union_cand,
                        plan_ms=(time.perf_counter() - t0) * 1e3,
                        notes=notes,
                        sub_plans=union,
                        strategy_source="union",
                        est_rows=dec.get("est_rows"),
                        alternatives=dec.get("alternatives"),
                    )
                    return plan, f, info

        info = QueryPlanInfo(
            type_name=self.sft.name,
            filter_str=str(q.filter) if q.filter is not None else "INCLUDE",
            index_name=name,
            extraction=e,
            n_intervals=len(plan.intervals),
            n_candidates=plan.n_candidates,
            plan_ms=(time.perf_counter() - t0) * 1e3,
            notes=notes,
            strategy_source=dec.get("source", ""),
            predicted_ms=dec.get("predicted_ms"),
            est_rows=dec.get("est_rows"),
            alternatives=dec.get("alternatives"),
            cheap=cheap,
        )
        return plan, f, info

    def _union_plans(self, f: ast.Filter, max_ranges: int, notes: list):
        """CNF alternative: top-level OR → per-arm index plans, or None.

        Every arm must be bounded under SOME index (spatial, temporal,
        indexed-attribute, or fid bounds) — one unbounded arm makes the union
        a full scan and the single-plan path is strictly better.
        """
        if not isinstance(f, ast.Or) or not (2 <= len(f.children) <= 8):
            return None
        from geomesa_tpu.filter.bounds import coerce_attr_bounds

        budget = max(1, max_ranges // len(f.children))
        subs = []
        for child in f.children:
            e_c = extract(
                child, self.sft.geom_field, self.sft.dtg_field,
                attrs=self.indexed_attrs,
            )
            e_c = coerce_attr_bounds(self.sft, e_c)
            fids = _extract_fids(child) or (
                child.fids if isinstance(child, ast.FidIn) else None
            )
            bounded = (
                e_c.spatially_bounded
                or e_c.temporally_bounded
                or any(b is not None for b in e_c.attributes.values())
                or fids is not None
            )
            if not bounded:
                return None
            name, _ = StrategyDecider.choose(
                self.indices, e_c, child, {}, self.stats
            )
            index = self.indices[name]
            if fids is not None and isinstance(index, IdIndex):
                plan = index.plan_fids(list(fids))
            else:
                plan = index.plan(e_c, budget)
            subs.append((name, plan, e_c))
        return subs


def _standing_dimension(f, geom: str | None, dtg: str | None) -> str:
    """Dimension tag (``space``/``time``/``both``/``all``/``none``) for a
    filter the subscription matrix evaluates EXACTLY in the int domain.

    The matrix runs NO residual filter after the device scan — unlike the
    store's query path, where extraction only has to be a sound superset
    because the full predicate re-applies afterwards. Any clause that
    extraction would widen or drop (attribute predicates, NOT, fid
    filters, non-BBOX spatial ops whose envelope over-covers, ORs that
    mix dimensions — the matrix evaluates ``(any box) AND (any window)``)
    therefore raises instead of silently over-delivering."""
    if isinstance(f, ast.Include):
        return "all"
    if isinstance(f, ast.Exclude):
        return "none"
    if isinstance(f, ast.BBox) and f.prop == geom:
        return "space"
    if isinstance(f, (ast.During, ast.TempOp)) and f.prop == dtg:
        return "time"
    if isinstance(f, ast.Between) and f.prop == dtg:
        return "time"
    if (isinstance(f, ast.Compare) and f.prop == dtg
            and f.op in ("=", "<", "<=", ">", ">=")):
        return "time"
    if isinstance(f, ast.And):
        tags = [_standing_dimension(c, geom, dtg) for c in f.children]
        if "none" in tags:
            return "none"
        tags = [t for t in tags if t != "all"]
        if not tags:
            return "all"
        if all(t == "space" for t in tags):
            return "space"
        if all(t == "time" for t in tags):
            return "time"
        return "both"
    if isinstance(f, ast.Or):
        tags = [_standing_dimension(c, geom, dtg) for c in f.children]
        if "all" in tags:
            return "all"
        tags = [t for t in tags if t != "none"]
        if not tags:
            return "none"
        if all(t == "space" for t in tags):
            return "space"
        if all(t == "time" for t in tags):
            return "time"
        raise ValueError(
            "standing queries cannot OR spatial with temporal clauses — "
            "the matrix evaluates (any box) AND (any window), a strict "
            f"superset of such a predicate: {f!r}"
        )
    raise ValueError(
        "standing queries evaluate bbox + time-window predicates only; no "
        f"residual filter runs after the device scan: unsupported clause {f!r}"
    )


def standing_query_payload(sft: FeatureType, predicate,
                           box_slots: int = 2, time_slots: int = 2):
    """Decompose a STANDING query (bbox + time-window predicate) into one
    subscription-matrix row: packed int-domain box and time-range payloads,
    the exact encoding the batched count kernels consume
    (``pack_boxes``/``pack_times`` over the planner's bounds extraction —
    the per-query analog of ``TpuBackend._payload``).

    ``predicate`` is a CQL string, a filter AST node, or a
    :class:`Query`. Returns ``(boxes (box_slots, 4) int32, times
    (time_slots, 4) int32)``. Like every int-domain payload this is a
    SUPERSET test at quantization boundaries — standing-query deliveries
    are int-domain matches (docs/streaming.md § Semantics). A provably
    disjoint predicate packs to the unsatisfiable sentinel (matches
    nothing) instead of a full scan. Raises ``ValueError`` for any clause
    the matrix cannot evaluate exactly (attribute predicates, ``NOT``,
    fid filters, non-BBOX spatial ops, ORs mixing space with time): no
    residual filter runs after the device scan, so accepting one would
    deliver rows the predicate rejects.
    """
    # lazy: backends imports planner — the payload helpers live there
    from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
    from geomesa_tpu.ops.refine import pack_boxes, pack_times, unsat_rows
    from geomesa_tpu.store.backends import REFINE_PRECISION, time_quads

    q = predicate if isinstance(predicate, Query) else Query(filter=predicate)
    f = q.resolved_filter()
    # reject predicates the matrix cannot evaluate exactly (raises) —
    # deliveries would otherwise be an UNBOUNDED superset, not the
    # documented quantization-boundary one
    _standing_dimension(f, sft.geom_field, sft.dtg_field)
    e = extract(f, sft.geom_field, sft.dtg_field)
    if e.disjoint:
        return unsat_rows(box_slots, time_slots)
    boxes_i32 = None
    if e.boxes is not None:
        nlon = norm_lon(REFINE_PRECISION)
        nlat = norm_lat(REFINE_PRECISION)
        boxes_i32 = np.array(
            [
                [int(nlon.normalize(x1)), int(nlon.normalize(x2)),
                 int(nlat.normalize(y1)), int(nlat.normalize(y2))]
                for x1, y1, x2, y2 in e.boxes
            ],
            dtype=np.int32,
        )
    return (
        pack_boxes(boxes_i32, slots=box_slots),
        pack_times(time_quads(sft, e.intervals), slots=time_slots),
    )


# routing consults between probes of the loser — now THE shared probe
# cadence of every cost-model decision (planning/costmodel.PROBE_EVERY);
# re-exported here because the agg path defined it first
from geomesa_tpu.planning.costmodel import PROBE_EVERY as AGG_PROBE_EVERY  # noqa: E402


def choose_agg_path(cost_table, type_name: str,
                    min_observations: int = 8) -> str:
    """Route one eligible grouped aggregation: the GeoBlocks pyramid
    (``"pyramid"``) or the fused device scan (``"scan"``).

    Delegates to the generalized cost-model decision engine
    (:meth:`geomesa_tpu.planning.costmodel.CostModel.choose_agg_path`)
    over the given observed-cost table — the original tick/probe
    mechanism, now shared by the strategy decider, the select dispatch
    route, and the join kernel choice: once BOTH routes have enough
    observations under this type the lower p50 wins (until then the
    pyramid is the seeded default — repeated polygon/bbox aggregation is
    exactly its regime), and every ``AGG_PROBE_EVERY``-th consult routes
    to the LOSING path so neither profile freezes. The probe schedule
    rides the cost table's per-type consult counter
    (:meth:`CostTable.tick`) — never observation counts, which the
    winner freezes by starving the loser of observations."""
    from geomesa_tpu.planning.costmodel import CostModel

    return CostModel(table=cost_table).choose_agg_path(
        type_name, min_observations)


def build_indices(sft: FeatureType) -> dict[str, FeatureIndex]:
    """Instantiate the index set for a schema (``IndexManager`` role).

    Respects ``geomesa.indices`` user-data override; defaults to every index
    whose ``supports`` matches (reference default: z3+z2[+attr]+id for points,
    xz3+xz2+id for extended geometries).
    """
    configured = sft.configured_indices
    out: dict[str, FeatureIndex] = {}
    for cls in ALL_INDEX_TYPES:
        if configured is not None and cls.name not in configured:
            continue
        if cls.supports(sft):
            out[cls.name] = cls(sft)
    for attr in AttributeIndex.indexed_attributes(sft):
        if configured is None or "attr" in configured or f"attr:{attr}" in configured:
            idx = AttributeIndex(sft, attr)
            out[idx.name] = idx
    if not out:
        out["id"] = IdIndex(sft)
    return out
