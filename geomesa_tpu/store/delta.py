"""Streaming hot tier: append buffer + background-style compaction.

The lambda-architecture role (``geomesa-lambda`` — SURVEY.md §2.11) and the
Kafka live-cache role (§2.10): recent writes land in a small, unsorted
*delta tier* that is scanned brute-force (it is the "transient tier"), while
the bulk of the data lives in the sorted, device-resident *main tier*.
Compaction merges the delta into the main tier (one global re-sort + device
reload) when it grows past a threshold — the LSM-ish pattern SURVEY.md §7
flags for sorted ingest under appends.

Queries = main-tier index scan ∪ delta-tier vectorized filter; both sides
already produce row-id sets, so the merge is a concatenation (the
``LambdaQueryRunner`` merged-read role).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu.schema.columnar import FeatureTable

DEFAULT_COMPACT_FRACTION = 0.25  # compact when delta > 25% of main
DEFAULT_COMPACT_MIN_ROWS = 100_000  # ... or when delta alone exceeds this


@dataclass
class DeltaTier:
    """Unsorted append buffer for one feature type."""

    tables: list[FeatureTable] = field(default_factory=list)
    rows: int = 0

    def append(self, table: FeatureTable) -> None:
        self.tables.append(table)
        self.rows += len(table)

    def merged(self) -> FeatureTable | None:
        if not self.tables:
            return None
        if len(self.tables) > 1:
            self.tables = [FeatureTable.concat(self.tables)]
        return self.tables[0]

    def clear(self) -> None:
        self.tables = []
        self.rows = 0

    def should_compact(self, main_rows: int) -> bool:
        if self.rows == 0:
            return False
        if self.rows >= DEFAULT_COMPACT_MIN_ROWS:
            return True
        return self.rows > max(1024, int(main_rows * DEFAULT_COMPACT_FRACTION))
