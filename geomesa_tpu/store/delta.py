"""Streaming hot tier: append buffer + background-style compaction.

The lambda-architecture role (``geomesa-lambda`` — SURVEY.md §2.11) and the
Kafka live-cache role (§2.10): recent writes land in a small, unsorted
*delta tier* that is scanned brute-force (it is the "transient tier"), while
the bulk of the data lives in the sorted, device-resident *main tier*.
Compaction merges the delta into the main tier (one global re-sort + device
reload) when it grows past a threshold — the LSM-ish pattern SURVEY.md §7
flags for sorted ingest under appends.

Queries = main-tier index scan ∪ delta-tier vectorized filter; both sides
already produce row-id sets, so the merge is a concatenation (the
``LambdaQueryRunner`` merged-read role).
"""

from __future__ import annotations

from dataclasses import dataclass, field


from geomesa_tpu.schema.columnar import FeatureTable

DEFAULT_COMPACT_FRACTION = 0.25  # compact when delta > 25% of main
DEFAULT_COMPACT_MIN_ROWS = 100_000  # ... or when delta alone exceeds this


@dataclass
class DeltaTier:
    """Unsorted append buffer for one feature type."""

    tables: list[FeatureTable] = field(default_factory=list)
    rows: int = 0
    # monotonic mutation counter: bumps on every append/clear/drop so
    # epoch-validated caches (the GeoBlocks query cache, lambda-tier warm
    # paths) can prove a cached answer predates no hot-tier change. Never
    # decreases — a stale epoch stamp can only cause a cache MISS.
    version: int = 0

    def append(self, table: FeatureTable) -> None:
        self.tables.append(table)
        self.rows += len(table)
        self.version += 1

    def merged(self) -> FeatureTable | None:
        """One table view of the tier, or None. PURE — does not consolidate
        in place, so concurrent readers can never invalidate the count-based
        consumption contract of :meth:`drop_first`."""
        tables = list(self.tables)  # appends during iteration stay unseen
        if not tables:
            return None
        return tables[0] if len(tables) == 1 else FeatureTable.concat(tables)

    def clear(self) -> None:
        self.tables = []
        self.rows = 0
        self.version += 1

    def drop_first(self, n: int) -> None:
        """Remove the first ``n`` tables (the set a compaction consumed).

        Appends always land at the END, so writes that arrived after the
        consuming snapshot survive — a background persister must not lose
        concurrent writes.
        """
        if n <= 0:
            return
        dropped = self.tables[:n]
        self.tables = self.tables[n:]
        self.rows -= sum(len(t) for t in dropped)
        self.version += 1

    def should_compact(self, main_rows: int) -> bool:
        if self.rows == 0:
            return False
        if self.rows >= DEFAULT_COMPACT_MIN_ROWS:
            return True
        return self.rows > max(1024, int(main_rows * DEFAULT_COMPACT_FRACTION))
