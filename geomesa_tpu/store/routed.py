"""Routed querying across multiple stores: each query goes to exactly ONE
delegate, selected by the attributes its filter references.

Role parity: ``geomesa-index-api/.../index/view/RoutedDataStoreView.scala:31``
+ ``RouteSelectorByAttribute.scala:20`` (SURVEY.md §2.3): unlike the
fan-out-and-merge :class:`~geomesa_tpu.store.merged.MergedDataStoreView`,
a routed view sends the whole query to the single store whose declared
route matches — e.g. id lookups to a key-value-shaped store, bbox+time
scans to the Z3-indexed store. A query matching no route returns an empty
result (the reference's ``EmptySimpleFeatureReader``).

Route declarations per store (mirroring ``geomesa.route.attributes``):

- ``"id"`` — the store serving feature-id lookups
- ``[attr, ...]`` — a route matching filters that reference AT LEAST this
  attribute set (``routes.forall(names.contains)`` in the reference)
- ``[]`` — the include/catch-all store (filters referencing no attributes,
  or no other route matching)

Schema semantics are the merged view's (the reference subclasses
``MergedDataStoreSchemas``): a type must exist on every member with the
same attribute layout.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType
from geomesa_tpu.store.datastore import QueryResult
from geomesa_tpu.store.merged import intersection_schema, intersection_schemas

__all__ = ["RoutedDataStoreView", "filter_properties"]


def filter_properties(f: "ast.Filter | None") -> tuple[set[str], bool]:
    """(attribute names referenced, has-id-filter) for a filter AST — the
    ``FilterHelper.propertyNames`` / ``hasIdFilter`` role."""
    names: set[str] = set()
    has_fid = False

    def walk(n):
        nonlocal has_fid
        if n is None:
            return
        if isinstance(n, ast.FidIn):
            has_fid = True
            return
        p = getattr(n, "prop", None)
        if isinstance(p, str):
            names.add(p)
        for c in getattr(n, "children", ()) or ():
            walk(c)
        c = getattr(n, "child", None)
        if isinstance(c, ast.Filter):
            walk(c)

    walk(f)
    return names, has_fid


class RoutedDataStoreView:
    """Route-per-query view over ``[(store, routes), ...]``.

    ``routes``: an iterable whose elements are ``"id"``, a list of
    attribute names (one route), or ``[]`` (the include/catch-all) —
    several elements declare several routes for the same store.

    ``on_member_error`` (docs/resilience.md): ``"fail"`` (default)
    propagates the routed store's errors; ``"fallback"`` retries a
    MEMBER failure (transport error, open breaker — the
    :data:`geomesa_tpu.resilience.MEMBER_FAILURE_TYPES` set) against the
    include/catch-all store when one is declared and it is a different
    store — the degraded-but-answering posture for a routed federation
    whose catch-all holds a full replica.

    ``shard_router`` (docs/serving.md): a
    :class:`geomesa_tpu.serving.shards.ShardRouter` whose member ids are
    positions into ``stores``. When set, a spatially-constrained filter
    whose plan ranges intersect EXACTLY ONE member's shards routes to
    that member (the data lives there — writes partition by the same
    map); multi-shard spatial filters fall through to the attribute
    routes / include store, because a routed view sends each query to
    one delegate (fan-out + merge is
    :class:`~geomesa_tpu.serving.shards.ShardedDataStoreView`'s job).
    Fid and attribute-only filters extract no spatial bounds and keep
    their classic DETERMINISTIC routes — id store first, then the
    most-specific attribute route, then include — regardless of the
    router (pinned in tests/test_serving.py).
    """

    def __init__(self, stores, on_member_error: str = "fail", metrics=None,
                 shard_router=None):
        if not stores:
            raise ValueError("routed view needs at least one store")
        if on_member_error not in ("fail", "fallback"):
            raise ValueError(
                f"on_member_error must be 'fail' or 'fallback', "
                f"got {on_member_error!r}")
        self.on_member_error = on_member_error
        self.shard_router = shard_router
        if metrics is None:
            from geomesa_tpu.utils.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        self.stores = [s for s, _ in stores]
        self._mappings: list[tuple[frozenset, object]] = []
        self._id_store = None
        self._include = None
        seen: set[frozenset] = set()
        for store, routes in stores:
            if isinstance(routes, str):
                # a bare string would iterate character-by-character into
                # bogus single-letter routes — the docstring's contract is
                # a LIST of route declarations
                raise ValueError(
                    f"routes must be a list of declarations, got {routes!r} "
                    "(did you mean [\"id\"]?)")
            for r in routes:
                if isinstance(r, str):
                    if r.lower() == "id":
                        if self._id_store is not None:
                            raise ValueError(
                                "'id' route is defined more than once")
                        self._id_store = store
                        continue
                    key = frozenset((r,))
                elif len(r) == 0:
                    if self._include is not None:
                        raise ValueError(
                            "include route is defined more than once")
                    self._include = store
                    continue
                else:
                    key = frozenset(r)
                if key in seen:
                    raise ValueError(
                        f"route {sorted(key)} is defined more than once")
                seen.add(key)
                self._mappings.append((key, store))
        # most-specific route wins regardless of declaration order: a
        # {geom} route must not shadow a {geom, dtg} route for a
        # spatio-temporal query (stable for equal sizes)
        self._mappings.sort(key=lambda kv: -len(kv[0]))

    # -- schemas: the merged view's semantics (shared helpers) ---------------
    def get_schema(self, name: str) -> FeatureType:
        return intersection_schema(self.stores, name)

    def list_schemas(self) -> list[str]:
        return intersection_schemas(self.stores)

    # -- routing -------------------------------------------------------------
    def route(self, f: "ast.Filter | None", type_name: str | None = None):
        """The store serving this filter, or None (no matching route).

        Precedence (each step deterministic): fid filters → the id
        store; single-shard-owner spatial filters → that member (when a
        ``shard_router`` is configured and ``type_name`` is known);
        attribute routes (most-specific first, declaration order on
        ties); the include store."""
        names, has_fid = filter_properties(f)

        def by_attributes():
            if not names:
                return None
            for key, store in self._mappings:
                if key <= names:
                    return store
            return None

        if has_fid and self._id_store is not None:
            return self._id_store
        if (
            self.shard_router is not None
            and type_name is not None
            and not has_fid
        ):
            owner = self._shard_owner(f, type_name)
            if owner is not None:
                return owner
        return by_attributes() or self._include

    def _shard_owner(self, f, type_name: str):
        """The single member owning every shard this filter's plan
        ranges intersect, or None (unconstrained / multi-owner /
        unknown type — the classic routes decide)."""
        try:
            sft = self.get_schema(type_name)
        except Exception:  # noqa: BLE001 — delegate surfaces missing types
            return None
        members = self.shard_router.members_for_filter(f, sft)
        if members is not None and len(members) == 1:
            return self.stores[members[0]]
        return None

    def _with_fallback(self, store, fn):
        """Run one routed call; in ``fallback`` mode a member failure
        retries against the include store (when distinct)."""
        from geomesa_tpu import obs
        from geomesa_tpu.resilience import MEMBER_FAILURE_TYPES

        try:
            return fn(store)
        except MEMBER_FAILURE_TYPES as e:
            if (
                self.on_member_error != "fallback"
                or self._include is None
                or self._include is store
            ):
                raise
            self.metrics.counter("federation.route_fallbacks").inc()
            obs.event("route_fallback", error=type(e).__name__)
            return fn(self._include)

    def query(self, type_name: str, q=None, **kwargs) -> QueryResult:
        if isinstance(q, (str, ast.Filter)) or q is None:
            q = Query(filter=q, **kwargs)
        store = self.route(q.resolved_filter(), type_name)
        if store is None:
            # only the empty-result branch needs the (cross-validated)
            # view schema; the delegate validates its own on the happy path
            empty = FeatureTable.from_records(self.get_schema(type_name), [])
            return QueryResult(empty, np.empty(0, dtype=np.int64))
        return self._with_fallback(store, lambda s: s.query(type_name, q))

    def stats_count(self, type_name: str, cql=None, exact: bool = False):
        from geomesa_tpu.filter.cql import parse

        f = parse(cql) if isinstance(cql, str) else cql
        store = self.route(f, type_name)
        if store is None:
            return 0
        return self._with_fallback(
            store, lambda s: s.stats_count(type_name, cql, exact=exact))

    def explain(self, type_name: str, q=None) -> str:
        if isinstance(q, (str, ast.Filter)) or q is None:
            q = Query(filter=q)
        store = self.route(q.resolved_filter(), type_name)
        if store is None:
            return "Route: none (empty result)"
        idx = self.stores.index(store)
        return f"Route: store[{idx}]\n" + store.explain(type_name, q)
