"""Federated read-only view over multiple stores.

Role parity: ``geomesa-index-api/.../index/view/MergedDataStoreView.scala:31``
+ ``MergedQueryRunner.scala`` (SURVEY.md §2.3): N underlying stores (each
optionally scoped by a per-store filter) presented as one read-only store;
queries fan out, per-store results merge, sort/limit/aggregations apply at the
view level. Mergeable aggregates merge exactly (density grids sum, stat
sketches are monoids — the reference's reducer pattern, P6/P10 in §2.20).
"""

from __future__ import annotations

from dataclasses import replace

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType
from geomesa_tpu.store.datastore import QueryResult

__all__ = ["MergedDataStoreView"]


class MergedDataStoreView:
    """Read-only fan-out over ``[(store, scope_filter_or_None), ...]``."""

    def __init__(self, stores):
        if not stores:
            raise ValueError("merged view needs at least one store")
        self.stores = [s if isinstance(s, tuple) else (s, None) for s in stores]

    def get_schema(self, name: str) -> FeatureType:
        sft = self.stores[0][0].get_schema(name)
        for s, _ in self.stores[1:]:
            other = s.get_schema(name)
            if [a.name for a in other.attributes] != [a.name for a in sft.attributes]:
                raise ValueError(f"schema mismatch across stores for {name!r}")
        return sft

    def list_schemas(self) -> list[str]:
        names = set(self.stores[0][0].list_schemas())
        for s, _ in self.stores[1:]:
            names &= set(s.list_schemas())
        return sorted(names)

    def query(self, type_name: str, q: Query | str | None = None, **kwargs) -> QueryResult:
        sft = self.get_schema(type_name)
        if isinstance(q, str) or q is None:
            q = Query(filter=q, **kwargs)

        # sub-queries: scope filter ANDed in; view-level reduce steps stripped
        # (sort/limit re-applied on the merged stream, reference
        # MergedQueryRunner behavior)
        tables: list[FeatureTable] = []
        density = None
        stats = None
        bin_parts: list[bytes] = []
        for store, scope in self.stores:
            f = q.resolved_filter()
            if scope is not None:
                scope_f = scope if isinstance(scope, ast.Filter) else None
                if scope_f is None:
                    from geomesa_tpu.filter.cql import parse

                    scope_f = parse(scope)
                f = ast.And((f, scope_f))
            sub = replace(q, filter=f, sort_by=None, limit=None)
            res = store.query(type_name, sub)
            if res.density is not None:
                density = res.density if density is None else density + res.density
            if res.stats is not None:
                if stats is None:
                    stats = dict(res.stats)
                else:
                    stats = {k: stats[k].merge(v) for k, v in res.stats.items()}
            if res.bin_data is not None:
                bin_parts.append(res.bin_data)
            if res.density is None and res.stats is None and res.bin_data is None:
                tables.append(res.table)

        if density is not None or stats is not None or bin_parts:
            empty = FeatureTable.from_records(sft, [])
            return QueryResult(
                empty,
                np.empty(0, dtype=np.int64),
                density=density,
                stats=stats,
                bin_data=b"".join(bin_parts) if bin_parts else None,
            )

        table = FeatureTable.concat(tables) if len(tables) > 1 else tables[0]
        rows = np.arange(len(table), dtype=np.int64)
        if q.sort_by is not None:
            fld, desc = q.sort_by
            keys = table.fids if fld == "id" else table.columns[fld].values
            order = np.argsort(keys, kind="stable")
            if desc:
                order = order[::-1]
            table = table.take(order)
            rows = rows[order]
        if q.limit is not None:
            table = table.take(np.arange(min(q.limit, len(table))))
            rows = rows[: q.limit]
        return QueryResult(table, rows)

    def stats_count(self, type_name: str, cql: str | None = None, exact: bool = False):
        return sum(s.stats_count(type_name, cql, exact) for s, _ in self.stores)
