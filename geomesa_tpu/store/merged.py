"""Federated read-only view over multiple stores.

Role parity: ``geomesa-index-api/.../index/view/MergedDataStoreView.scala:31``
+ ``MergedQueryRunner.scala`` (SURVEY.md §2.3): N underlying stores (each
optionally scoped by a per-store filter) presented as one read-only store;
queries fan out, per-store results merge, sort/limit/aggregations apply at the
view level. Mergeable aggregates merge exactly (density grids sum, stat
sketches are monoids — the reference's reducer pattern, P6/P10 in §2.20).
"""

from __future__ import annotations

import time
from dataclasses import replace

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.filter import ast
from geomesa_tpu.obs import flight as _flight
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.resilience import MEMBER_FAILURE_TYPES, CircuitOpenError
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType
from geomesa_tpu.store.datastore import QueryResult
from geomesa_tpu.utils.timeouts import QueryTimeout

__all__ = ["MergedDataStoreView", "intersection_schema", "intersection_schemas"]


def intersection_schema(stores, name: str) -> FeatureType:
    """The shared multi-store schema contract (the reference's
    ``MergedDataStoreSchemas`` trait): a type must exist on every member
    with the same attribute layout. Used by the merged AND routed views —
    schema-compat rules must not drift between them."""
    sft = stores[0].get_schema(name)
    for s in stores[1:]:
        other = s.get_schema(name)
        if [a.name for a in other.attributes] != [
            a.name for a in sft.attributes
        ]:
            raise ValueError(f"schema mismatch across stores for {name!r}")
    return sft


def intersection_schemas(stores) -> list[str]:
    names = set(stores[0].list_schemas())
    for s in stores[1:]:
        names &= set(s.list_schemas())
    return sorted(names)


class MergedDataStoreView:
    """Read-only fan-out over ``[(store, scope_filter_or_None), ...]``.

    ``on_member_error`` (docs/resilience.md) picks the federation's
    failure posture:

    - ``"fail"`` (default, the historical behavior): any member error
      fails the whole query — strict, every answer is complete.
    - ``"partial"``: a member failing with a MEMBER failure (transport
      error, 5xx after retries, open circuit breaker, blown deadline,
      corrupt payload — :data:`geomesa_tpu.resilience.MEMBER_FAILURE_TYPES`)
      is skipped; the merged result carries the surviving members' rows,
      marked ``degraded=True`` with per-member error details, the way
      query-cache systems serve cached partials under failure (GeoBlocks,
      arXiv:1908.07753). Semantic errors (missing schema, bad filter —
      KeyError/ValueError/PermissionError) still fail: they are the
      caller's bug on every member alike. All members failing fails the
      query in either mode.

    Degradations are observable: ``metrics`` counters
    (``federation.member_errors[.i]``, ``federation.degraded_queries``)
    and an :func:`obs.event` span marker per skipped member.
    """

    def __init__(self, stores, on_member_error: str = "fail", metrics=None,
                 slo=None, slo_target: float = 0.999):
        if not stores:
            raise ValueError("merged view needs at least one store")
        if on_member_error not in ("fail", "partial"):
            raise ValueError(
                f"on_member_error must be 'fail' or 'partial', "
                f"got {on_member_error!r}")
        from geomesa_tpu.filter.cql import parse

        self.on_member_error = on_member_error
        if metrics is None:
            from geomesa_tpu.utils.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        # SLO engine (docs/observability.md § SLOs): every member fan-out
        # leg is one availability observation against
        # ``federation.member`` keyed by member index — the burn-rate /
        # error-budget surface behind GET /api/metrics?format=prometheus
        # and the member_health() scoreboard
        if slo is None:
            from geomesa_tpu.obs.slo import SloEngine

            slo = SloEngine()
        self.slo = slo
        self.slo.objective("federation.member", target=slo_target)
        # scope filters parsed once here, not per query
        self.stores = []
        for s in stores:
            store, scope = s if isinstance(s, tuple) else (s, None)
            if scope is not None and not isinstance(scope, ast.Filter):
                scope = parse(scope)
            self.stores.append((store, scope))
        # per-member observed-cost aggregates (ROADMAP item-3 leftover:
        # per-shard cost asymmetry): every fan-out leg records its wall
        # ms under (member, type, op) — served as the `members` section
        # of GET /api/obs/costs (?member= filter), the member column of
        # `geomesa-tpu obs costs`, and the explain scoreboard, so one
        # slow shard is visible as a COST asymmetry, not just an SLO one
        import threading

        self._member_cost_lock = threading.Lock()  # leaf: the table only
        self._member_costs: dict = {}

    def _member_run(self, i: int, fn, errors: list, outcomes: list | None = None,
                    cost: tuple | None = None):
        """One member's fan-out leg: ``(ok, result)``. In ``partial``
        mode a member failure is recorded (metrics + SLO + span event +
        the errors list) and skipped; in ``fail`` mode it propagates.
        ``outcomes`` (when passed) collects the flight-recorder member
        summary: ``(i, "ok" | "error:<Type>", ms)``. ``cost`` —
        ``(type_name, op)`` — records the leg's wall into the per-member
        observed-cost table (successful legs only: a fail-fast breaker
        leg's near-zero wall is not the member's cost of doing the work)."""
        t0 = time.perf_counter()
        try:
            out = fn()
        except MEMBER_FAILURE_TYPES as e:
            ms = (time.perf_counter() - t0) * 1000.0
            self.slo.observe("federation.member", ok=False,
                             latency_ms=ms, key=str(i))
            if outcomes is not None:
                outcomes.append((i, f"error:{type(e).__name__}", ms))
            if self.on_member_error != "partial":
                raise
            errors.append((i, e))
            self.metrics.counter("federation.member_errors").inc()
            self.metrics.counter(f"federation.member_errors.{i}").inc()
            obs.event("member_error", member=i, error=type(e).__name__)
            return False, None
        ms = (time.perf_counter() - t0) * 1000.0
        self.slo.observe("federation.member", ok=True,
                         latency_ms=ms, key=str(i))
        if cost is not None:
            self._note_member_cost(i, cost[0], cost[1], ms)
        if outcomes is not None:
            outcomes.append((i, "ok", ms))
        return True, out

    def _note_member_cost(self, i: int, type_name: str, op: str,
                          ms: float) -> None:
        from geomesa_tpu.obs.devmon import _Quantiles

        key = (i, type_name, op)
        with self._member_cost_lock:
            ent = self._member_costs.get(key)
            if ent is None:
                ent = self._member_costs[key] = [0, _Quantiles()]
                # bounded: (members × types × ops) is small by
                # construction, but a type-churning workload must not
                # grow it forever
                while len(self._member_costs) > 512:
                    self._member_costs.pop(next(iter(self._member_costs)))
            ent[0] += 1
            ent[1].update(ms)

    def member_costs_snapshot(self, member: int | None = None) -> list:
        """Per-(member, type, op) observed wall-ms aggregates — the
        `members` section of ``GET /api/obs/costs`` (``?member=``
        filters), rendered by ``geomesa-tpu obs costs`` and the merged
        ``explain`` scoreboard."""
        with self._member_cost_lock:
            items = list(self._member_costs.items())
        out = []
        for (i, type_name, op), (n, qs) in items:
            if member is not None and i != member:
                continue
            out.append({
                "member": i,
                "store": getattr(self.stores[i][0], "base_url",
                                 type(self.stores[i][0]).__name__)
                if i < len(self.stores) else "?",
                "type": type_name,
                "op": op,
                "count": n,
                "wall_ms_p50": round(qs.quantile(0.5), 3),
                "wall_ms_p95": round(qs.quantile(0.95), 3),
            })
        out.sort(key=lambda r: (r["member"], r["type"], r["op"]))
        return out

    @staticmethod
    def _anomalies(errors: list) -> tuple:
        """Flight-recorder anomaly classification of a fan-out's member
        failures (degraded/slow are detected by the recorder itself)."""
        out: list[str] = []
        for _, e in errors:
            kind = None
            if isinstance(e, CircuitOpenError):
                kind = _flight.A_BREAKER
            elif isinstance(e, QueryTimeout):
                kind = _flight.A_DEADLINE
            if kind is not None and kind not in out:
                out.append(kind)
        return tuple(out)

    def member_health(self) -> list:
        """The per-member health scoreboard (docs/observability.md):
        rolling 5-minute success rate, latency quantiles from the SLO
        tracker's reservoir, breaker state where the member exposes one,
        and the cumulative error count — what ``/api/metrics`` and
        ``explain`` surface for operators."""
        out = []
        for i, (store, _) in enumerate(self.stores):
            tk = self.slo.tracker("federation.member", key=str(i))
            win = min(tk.objective.windows)
            p50, p95, p99 = tk.latency_quantiles()
            breaker = getattr(store, "breaker", None)
            errs = self.metrics.counters.get(f"federation.member_errors.{i}")
            out.append({
                "member": i,
                "store": getattr(store, "base_url", type(store).__name__),
                "success_rate": 1.0 - tk.burn_rate(win) * (
                    1.0 - tk.objective.target),
                "budget_remaining": tk.budget_remaining(win),
                "window": int(win),
                "p50_ms": p50,
                "p95_ms": p95,
                "p99_ms": p99,
                "breaker": breaker.state if breaker is not None else None,
                "errors": errs.count if errs is not None else 0,
            })
        return out

    def explain(self, type_name: str, q=None) -> str:
        """Federated EXPLAIN: each member's own plan explain (where the
        member supports it) plus the health scoreboard, so a degraded
        member is visible right where the operator is reading plans."""
        if isinstance(q, (str, ast.Filter)) or q is None:
            q = Query(filter=q)
        lines = [f"Federated plan over {len(self.stores)} members "
                 f"(on_member_error={self.on_member_error}):"]
        base_f = q.resolved_filter()
        for i, (store, scope) in enumerate(self.stores):
            f = base_f if scope is None else ast.And((base_f, scope))
            sub = replace(q, filter=f, sort_by=None, limit=None,
                          start_index=None)
            ex = getattr(store, "explain", None)
            lines.append(f"-- member {i}: "
                         f"{getattr(store, 'base_url', type(store).__name__)}")
            if ex is None:
                lines.append("   (no explain surface)")
                continue
            try:
                lines.append("   " + str(ex(type_name, sub)).replace(
                    "\n", "\n   "))
            except MEMBER_FAILURE_TYPES as e:
                lines.append(f"   (unavailable: {type(e).__name__}: {e})")
        lines.append("Member health:")
        for h in self.member_health():
            lines.append(
                f"  member {h['member']} [{h['store']}]: "
                f"success={h['success_rate']:.3f} "
                f"budget={h['budget_remaining']:.2f} "
                f"p95={h['p95_ms']:.1f}ms "
                f"breaker={h['breaker'] or '-'} errors={h['errors']}")
        costs = self.member_costs_snapshot()
        rows = [c for c in costs if c["type"] == type_name]
        if rows:
            lines.append("Member cost asymmetry (observed wall ms):")
            for c in rows:
                lines.append(
                    f"  member {c['member']} {c['op']:<12s} "
                    f"n={c['count']:<5d} p50={c['wall_ms_p50']:.2f} "
                    f"p95={c['wall_ms_p95']:.2f}")
        return "\n".join(lines)

    @staticmethod
    def _error_details(errors: list) -> list:
        return [(i, type(e).__name__, str(e)) for i, e in errors]

    def _member_subset(self, type_name: str, f) -> list | None:
        """Member indices a query with this filter must fan out to:
        ``None`` = all (the merged view's default), ``[]`` = none (a
        provably disjoint filter). The sharded federation
        (:class:`geomesa_tpu.serving.shards.ShardedDataStoreView`)
        overrides this to narrow fan-out to the members whose Z-prefix
        shards the plan's ranges intersect — member indices stay the
        DECLARED positions, so SLO keys, metrics counters and the
        health scoreboard attribute stably across differing subsets."""
        return None

    def _member_subset_rows(self, type_name: str, f) -> list | None:
        """Row-read variant of :meth:`_member_subset`: the sharded view
        widens this to the UNION of old and new owners during a live
        shard migration's dual-apply window (row results dedup by fid
        at the merge), while additive reads — counts, stats sketches,
        density grids, grouped aggregations, everything that SUMS
        across members — keep the authoritative subset (a union fan
        would double-count every dual-applied row). The merged default:
        the two fans are identical."""
        return self._member_subset(type_name, f)

    def _fan_targets(self, type_name: str, f, rows: bool = False) -> list:
        """``[(member_index, (store, scope)), ...]`` for one fan-out.
        ``rows=True`` marks a row-returning read (union fan allowed)."""
        subset = (self._member_subset_rows(type_name, f) if rows
                  else self._member_subset(type_name, f))
        if subset is None:
            return list(enumerate(self.stores))
        return [(i, self.stores[i]) for i in subset]

    def _merge_member_tables(self, tables: list) -> FeatureTable:
        """Merge seam for per-member row results: the sharded view
        overrides this to dedup dual-applied rows by fid while a live
        shard migration union-fans reads."""
        return FeatureTable.concat(tables) if len(tables) > 1 else tables[0]

    def _note_degraded(self, errors: list, op: str) -> None:
        self.metrics.counter("federation.degraded_queries").inc()
        obs.event("degraded", op=op, members_failed=len(errors))

    def get_schema(self, name: str) -> FeatureType:
        stores = [s for s, _ in self.stores]
        if self.on_member_error != "partial":
            return intersection_schema(stores, name)
        # partial mode: the schema contract holds over the ANSWERING
        # members — a dead member must not take down the view's whole
        # schema surface (its data absence is recorded per query by the
        # fan-outs). Layout mismatches are semantic and still raise.
        sft = None
        last: Exception | None = None
        for s in stores:
            try:
                other = s.get_schema(name)
            except MEMBER_FAILURE_TYPES as e:
                last = e
                continue
            if sft is None:
                sft = other
            elif [a.name for a in other.attributes] != [
                a.name for a in sft.attributes
            ]:
                raise ValueError(f"schema mismatch across stores for {name!r}")
        if sft is None:
            raise last if last is not None else KeyError(name)
        return sft

    def list_schemas(self) -> list[str]:
        stores = [s for s, _ in self.stores]
        if self.on_member_error != "partial":
            return intersection_schemas(stores)
        names: set | None = None
        last: Exception | None = None
        for s in stores:
            try:
                ns = set(s.list_schemas())
            except MEMBER_FAILURE_TYPES as e:
                last = e
                continue
            names = ns if names is None else names & ns
        if names is None:
            raise last if last is not None else ValueError("no members")
        return sorted(names)

    def query(self, type_name: str, q: "Query | str | ast.Filter | None" = None, **kwargs) -> QueryResult:
        if isinstance(q, (str, ast.Filter)) or q is None:
            q = Query(filter=q, **kwargs)
        t_start = time.perf_counter()
        outcomes: list = []
        # one federation span per query: member RPC spans (and their
        # grafted remote subtrees) nest under it, member-error/degraded
        # events attach to it — the stitched tree's local frame
        with obs.span("federation.query", type=type_name,
                      members=len(self.stores)):
            filt = q.filter if isinstance(q.filter, str) else str(
                q.filter or "INCLUDE")
            # federation-level tenant attribution: the frontend's request
            # context (member stores attribute their own legs via the
            # propagated X-Geomesa-Tenant header — resilience/http.py)
            from geomesa_tpu.obs import usage as _usage
            from geomesa_tpu.obs import workload as _workload

            tenant = q.hints.get("tenant") or _usage.current_tenant()
            try:
                res, errors = self._query_fanout(type_name, q, outcomes)
            except MEMBER_FAILURE_TYPES as e:
                # whole-query failure (all members down, or fail mode):
                # the always-on record must not miss the worst outcomes
                ms = (time.perf_counter() - t_start) * 1000.0
                _flight.record(
                    op="query", type_name=type_name, source="federation",
                    plan=filt, latency_ms=ms,
                    rows=0, degraded=True, members=outcomes,
                    anomalies=self._anomalies([(None, e)]),
                    tenant=tenant or "", auths=q.auths,
                )
                _usage.observe(tenant, type_name, "federation", rows=0,
                               wall_ms=ms, ok=False)
                raise
            # always-on audit record; anomalies (degraded result, open
            # breaker, blown member deadline) trigger the flight dump
            ms = (time.perf_counter() - t_start) * 1000.0
            _flight.record(
                op="query", type_name=type_name, source="federation",
                plan=filt, latency_ms=ms,
                rows=res.count, degraded=res.degraded, members=outcomes,
                anomalies=self._anomalies(errors),
                tenant=tenant or "", auths=q.auths,
            )
            # view-level metering under the "federation" pseudo-signature:
            # in-process member stores meter their own legs per plan shape,
            # so the device-ms attribution stays with the store tier
            _usage.observe(tenant, type_name, "federation", rows=res.count,
                           wall_ms=ms, ok=not res.degraded)
            if _workload.ENABLED:
                _workload.record(
                    ts=time.time(), op="query", type_name=type_name,
                    source="federation", filter_text=filt, hints=q.hints,
                    tenant=tenant or "", auths=q.auths,
                    plan_signature="federation", predicted_ms=None,
                    latency_ms=ms, rows=res.count, degraded=res.degraded,
                )
        return res

    def _query_fanout(self, type_name: str, q: Query, outcomes: list):
        sft = self.get_schema(type_name)

        # sub-queries: scope filter ANDed in; view-level reduce steps stripped
        # (sort/limit re-applied on the merged stream, reference
        # MergedQueryRunner behavior)
        tables: list[FeatureTable] = []
        density = None
        stats = None
        bin_parts: list[bytes] = []
        errors: list = []
        base_f = q.resolved_filter()
        row_read = not any(k in q.hints for k in ("density", "stats", "bin"))
        targets = self._fan_targets(type_name, base_f, rows=row_read)
        if not targets:
            # provably disjoint under the shard map: no member can hold
            # a matching row. Aggregation-hinted queries (density /
            # stats / bin) still fan to ONE member so the zero answer
            # keeps its channel shape (a zero grid, empty sketches) —
            # a disjoint filter matches nothing on ANY member, so one
            # member's answer IS the global answer. Plain row queries
            # answer empty without any fan-out.
            if any(k in q.hints for k in ("density", "stats", "bin")):
                targets = [(0, self.stores[0])]
            else:
                empty = FeatureTable.from_records(sft, [])
                return QueryResult(empty, np.empty(0, dtype=np.int64)), []
        for i, (store, scope) in targets:
            f = base_f if scope is None else ast.And((base_f, scope))
            sub = replace(q, filter=f, sort_by=None, limit=None, start_index=None)
            ok, res = self._member_run(
                i, lambda s=store, t=sub: s.query(type_name, t), errors,
                outcomes, cost=(type_name, "query"))
            if not ok:
                continue
            if res.density is not None:
                density = res.density if density is None else density + res.density
            if res.stats is not None:
                if stats is None:
                    stats = dict(res.stats)
                else:
                    stats = {k: stats[k].merge(v) for k, v in res.stats.items()}
            if res.bin_data is not None:
                bin_parts.append(res.bin_data)
            if res.density is None and res.stats is None and res.bin_data is None:
                tables.append(res.table)

        if errors and len(errors) == len(targets):
            # zero ATTEMPTED members answered: no partial to serve
            raise errors[-1][1]
        degraded = bool(errors)
        if degraded:
            self._note_degraded(errors, "query")

        if density is not None or stats is not None or bin_parts:
            bin_data = None
            if bin_parts:
                bin_opts = q.hints.get("bin") or {}
                if bin_opts.get("sort"):
                    # per-store chunks are each time-sorted; a plain join
                    # would interleave — merge-sort them (BinSorter role)
                    from geomesa_tpu.utils.bin_format import merge_sorted

                    bin_data = merge_sorted(
                        bin_parts, labeled=bool(bin_opts.get("label"))
                    )
                else:
                    bin_data = b"".join(bin_parts)
            empty = FeatureTable.from_records(sft, [])
            return QueryResult(
                empty,
                np.empty(0, dtype=np.int64),
                density=density,
                stats=stats,
                bin_data=bin_data,
                degraded=degraded,
                member_errors=self._error_details(errors) if errors else None,
            ), errors

        table = self._merge_member_tables(tables)
        rows = np.arange(len(table), dtype=np.int64)
        from geomesa_tpu.store.reduce import sort_limit

        table, rows = sort_limit(table, rows, q.sort_by, q.limit, q.start_index)
        return QueryResult(
            table, rows, degraded=degraded,
            member_errors=self._error_details(errors) if errors else None,
        ), errors

    def stats_count(self, type_name: str, cql=None, exact: bool = False):
        """Count across stores, honoring each store's scope filter. In
        ``partial`` mode a failed member contributes zero (undercount —
        recorded via metrics/span event; the return type stays a bare
        number)."""
        from geomesa_tpu.filter.cql import parse

        f = parse(cql) if isinstance(cql, str) else cql
        total = 0
        errors: list = []
        targets = self._fan_targets(type_name, f)
        for i, (s, scope) in targets:
            sub = f if scope is None else (scope if f is None else ast.And((f, scope)))
            ok, n = self._member_run(
                i, lambda s=s, t=sub: s.stats_count(type_name, t, exact),
                errors, cost=(type_name, "stats_count"))
            if ok:
                total += n
        if errors:
            if len(errors) == len(targets):
                raise errors[-1][1]
            self._note_degraded(errors, "stats_count")
        return total

    def aggregate_many(self, type_name: str, queries, group_by=None,
                       value_cols=(), now_ms: int | None = None):
        """Federated grouped aggregation: push the fold to every member
        (each runs its own fused mesh pass — or its owner's, over HTTP via
        RemoteDataStore) and merge the per-group partials at the view level:
        counts/sums add, extrema min/max, group order is first occurrence
        across members in member order (the same order the view's merged
        host fold would produce). A query any member declines is declined
        (None) for the whole view — the caller's host fold keeps exact
        semantics rather than mixing engines per slice."""
        qs = [
            Query(filter=q) if isinstance(q, (str, ast.Filter)) or q is None
            else q
            for q in queries
        ]
        # capability check BEFORE any fan-out: one member without the fold
        # declines the whole view, and earlier members must not burn device
        # passes / remote round-trips whose results would be discarded
        if any(
            getattr(store, "aggregate_many", None) is None
            for store, _ in self.stores
        ):
            return [None] * len(qs)
        # fan only to the members ANY query of the batch can touch (the
        # sharded view's subset hook; None = all, the merged default)
        subset_u: set | None = set()
        for q in qs:
            s = self._member_subset(type_name, q.resolved_filter())
            if s is None:
                subset_u = None
                break
            subset_u.update(s)
        targets = (list(enumerate(self.stores)) if subset_u is None
                   else [(i, self.stores[i]) for i in sorted(subset_u)])
        per_member = []
        errors: list = []
        for i, (store, scope) in targets:
            agg = store.aggregate_many
            subs = []
            for q in qs:
                f = q.resolved_filter()
                if scope is not None:
                    f = ast.And((f, scope))
                subs.append(replace(q, filter=f))
            ok, partials = self._member_run(
                i, lambda a=agg, s=subs: a(type_name, s, group_by=group_by,
                                           value_cols=value_cols,
                                           now_ms=now_ms),
                errors, cost=(type_name, "aggregate"))
            if ok:
                per_member.append(partials)
        if errors:
            if not per_member:
                raise errors[-1][1]
            # partial federation fold: surviving members' partials merge;
            # each result dict below carries the degraded marker
            self._note_degraded(errors, "aggregate_many")
        degraded = bool(errors)
        out: list = []
        vcols = list(value_cols)
        for qi in range(len(qs)):
            parts = [m[qi] for m in per_member]
            if any(p is None for p in parts):
                out.append(None)
                continue
            keys: list = []
            pos: dict = {}
            cnt: list[int] = []
            acc = {c: {"count": [], "sum": [], "min": [], "max": []}
                   for c in vcols}
            for p in parts:
                for gi, key in enumerate(p["groups"]):
                    g = pos.get(key)
                    if g is None:
                        g = pos[key] = len(keys)
                        keys.append(key)
                        cnt.append(0)
                        for c in vcols:
                            acc[c]["count"].append(0)
                            acc[c]["sum"].append(0.0)
                            acc[c]["min"].append(np.nan)
                            acc[c]["max"].append(np.nan)
                    cnt[g] += int(p["count"][gi])
                    for c in vcols:
                        d = p["cols"][c]
                        acc[c]["count"][g] += int(d["count"][gi])
                        acc[c]["sum"][g] += float(d["sum"][gi])
                        for k, fold in (("min", min), ("max", max)):
                            v = float(d[k][gi])
                            if np.isnan(v):
                                continue
                            cur = acc[c][k][g]
                            acc[c][k][g] = v if np.isnan(cur) else fold(cur, v)
            # no-GROUP-BY single groups merge into one row; grouped results
            # keep only non-empty groups (every member already filters, but
            # scope-disjoint members contribute zero-count groups never)
            rec = {
                "groups": keys,
                "count": np.asarray(cnt, dtype=np.int64),
                "cols": {
                    c: {k: np.asarray(v, dtype=np.float64)
                        if k != "count"
                        else np.asarray(v, dtype=np.int64)
                        for k, v in acc[c].items()}
                    for c in vcols
                },
            }
            if degraded:
                rec["degraded"] = True
                rec["member_errors"] = self._error_details(errors)
            out.append(rec)
        return out
