"""Federated read-only view over multiple stores.

Role parity: ``geomesa-index-api/.../index/view/MergedDataStoreView.scala:31``
+ ``MergedQueryRunner.scala`` (SURVEY.md §2.3): N underlying stores (each
optionally scoped by a per-store filter) presented as one read-only store;
queries fan out, per-store results merge, sort/limit/aggregations apply at the
view level. Mergeable aggregates merge exactly (density grids sum, stat
sketches are monoids — the reference's reducer pattern, P6/P10 in §2.20).
"""

from __future__ import annotations

from dataclasses import replace

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType
from geomesa_tpu.store.datastore import QueryResult

__all__ = ["MergedDataStoreView", "intersection_schema", "intersection_schemas"]


def intersection_schema(stores, name: str) -> FeatureType:
    """The shared multi-store schema contract (the reference's
    ``MergedDataStoreSchemas`` trait): a type must exist on every member
    with the same attribute layout. Used by the merged AND routed views —
    schema-compat rules must not drift between them."""
    sft = stores[0].get_schema(name)
    for s in stores[1:]:
        other = s.get_schema(name)
        if [a.name for a in other.attributes] != [
            a.name for a in sft.attributes
        ]:
            raise ValueError(f"schema mismatch across stores for {name!r}")
    return sft


def intersection_schemas(stores) -> list[str]:
    names = set(stores[0].list_schemas())
    for s in stores[1:]:
        names &= set(s.list_schemas())
    return sorted(names)


class MergedDataStoreView:
    """Read-only fan-out over ``[(store, scope_filter_or_None), ...]``."""

    def __init__(self, stores):
        if not stores:
            raise ValueError("merged view needs at least one store")
        from geomesa_tpu.filter.cql import parse

        # scope filters parsed once here, not per query
        self.stores = []
        for s in stores:
            store, scope = s if isinstance(s, tuple) else (s, None)
            if scope is not None and not isinstance(scope, ast.Filter):
                scope = parse(scope)
            self.stores.append((store, scope))

    def get_schema(self, name: str) -> FeatureType:
        return intersection_schema([s for s, _ in self.stores], name)

    def list_schemas(self) -> list[str]:
        return intersection_schemas([s for s, _ in self.stores])

    def query(self, type_name: str, q: "Query | str | ast.Filter | None" = None, **kwargs) -> QueryResult:
        sft = self.get_schema(type_name)
        if isinstance(q, (str, ast.Filter)) or q is None:
            q = Query(filter=q, **kwargs)

        # sub-queries: scope filter ANDed in; view-level reduce steps stripped
        # (sort/limit re-applied on the merged stream, reference
        # MergedQueryRunner behavior)
        tables: list[FeatureTable] = []
        density = None
        stats = None
        bin_parts: list[bytes] = []
        base_f = q.resolved_filter()
        for store, scope in self.stores:
            f = base_f if scope is None else ast.And((base_f, scope))
            sub = replace(q, filter=f, sort_by=None, limit=None, start_index=None)
            res = store.query(type_name, sub)
            if res.density is not None:
                density = res.density if density is None else density + res.density
            if res.stats is not None:
                if stats is None:
                    stats = dict(res.stats)
                else:
                    stats = {k: stats[k].merge(v) for k, v in res.stats.items()}
            if res.bin_data is not None:
                bin_parts.append(res.bin_data)
            if res.density is None and res.stats is None and res.bin_data is None:
                tables.append(res.table)

        if density is not None or stats is not None or bin_parts:
            bin_data = None
            if bin_parts:
                bin_opts = q.hints.get("bin") or {}
                if bin_opts.get("sort"):
                    # per-store chunks are each time-sorted; a plain join
                    # would interleave — merge-sort them (BinSorter role)
                    from geomesa_tpu.utils.bin_format import merge_sorted

                    bin_data = merge_sorted(
                        bin_parts, labeled=bool(bin_opts.get("label"))
                    )
                else:
                    bin_data = b"".join(bin_parts)
            empty = FeatureTable.from_records(sft, [])
            return QueryResult(
                empty,
                np.empty(0, dtype=np.int64),
                density=density,
                stats=stats,
                bin_data=bin_data,
            )

        table = FeatureTable.concat(tables) if len(tables) > 1 else tables[0]
        rows = np.arange(len(table), dtype=np.int64)
        from geomesa_tpu.store.reduce import sort_limit

        table, rows = sort_limit(table, rows, q.sort_by, q.limit, q.start_index)
        return QueryResult(table, rows)

    def stats_count(self, type_name: str, cql=None, exact: bool = False):
        """Count across stores, honoring each store's scope filter."""
        from geomesa_tpu.filter.cql import parse

        f = parse(cql) if isinstance(cql, str) else cql
        total = 0
        for s, scope in self.stores:
            sub = f if scope is None else (scope if f is None else ast.And((f, scope)))
            total += s.stats_count(type_name, sub, exact)
        return total

    def aggregate_many(self, type_name: str, queries, group_by=None,
                       value_cols=(), now_ms: int | None = None):
        """Federated grouped aggregation: push the fold to every member
        (each runs its own fused mesh pass — or its owner's, over HTTP via
        RemoteDataStore) and merge the per-group partials at the view level:
        counts/sums add, extrema min/max, group order is first occurrence
        across members in member order (the same order the view's merged
        host fold would produce). A query any member declines is declined
        (None) for the whole view — the caller's host fold keeps exact
        semantics rather than mixing engines per slice."""
        qs = [
            Query(filter=q) if isinstance(q, (str, ast.Filter)) or q is None
            else q
            for q in queries
        ]
        # capability check BEFORE any fan-out: one member without the fold
        # declines the whole view, and earlier members must not burn device
        # passes / remote round-trips whose results would be discarded
        if any(
            getattr(store, "aggregate_many", None) is None
            for store, _ in self.stores
        ):
            return [None] * len(qs)
        per_member = []
        for store, scope in self.stores:
            agg = store.aggregate_many
            subs = []
            for q in qs:
                f = q.resolved_filter()
                if scope is not None:
                    f = ast.And((f, scope))
                subs.append(replace(q, filter=f))
            per_member.append(
                agg(type_name, subs, group_by=group_by,
                    value_cols=value_cols, now_ms=now_ms)
            )
        out: list = []
        vcols = list(value_cols)
        for qi in range(len(qs)):
            parts = [m[qi] for m in per_member]
            if any(p is None for p in parts):
                out.append(None)
                continue
            keys: list = []
            pos: dict = {}
            cnt: list[int] = []
            acc = {c: {"count": [], "sum": [], "min": [], "max": []}
                   for c in vcols}
            for p in parts:
                for gi, key in enumerate(p["groups"]):
                    g = pos.get(key)
                    if g is None:
                        g = pos[key] = len(keys)
                        keys.append(key)
                        cnt.append(0)
                        for c in vcols:
                            acc[c]["count"].append(0)
                            acc[c]["sum"].append(0.0)
                            acc[c]["min"].append(np.nan)
                            acc[c]["max"].append(np.nan)
                    cnt[g] += int(p["count"][gi])
                    for c in vcols:
                        d = p["cols"][c]
                        acc[c]["count"][g] += int(d["count"][gi])
                        acc[c]["sum"][g] += float(d["sum"][gi])
                        for k, fold in (("min", min), ("max", max)):
                            v = float(d[k][gi])
                            if np.isnan(v):
                                continue
                            cur = acc[c][k][g]
                            acc[c][k][g] = v if np.isnan(cur) else fold(cur, v)
            # no-GROUP-BY single groups merge into one row; grouped results
            # keep only non-empty groups (every member already filters, but
            # scope-disjoint members contribute zero-count groups never)
            out.append({
                "groups": keys,
                "count": np.asarray(cnt, dtype=np.int64),
                "cols": {
                    c: {k: np.asarray(v, dtype=np.float64)
                        if k != "count"
                        else np.asarray(v, dtype=np.int64)
                        for k, v in acc[c].items()}
                    for c in vcols
                },
            })
        return out
