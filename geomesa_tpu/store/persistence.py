"""Catalog persistence: save/load a DataStore to a directory (checkpoint/resume).

The reference's durable state is the store itself plus catalog metadata
(schema specs, stats) — ``metadata/TableBasedMetadata.scala``,
``fs/.../FileBasedMetadata.scala`` (SURVEY.md §5 "checkpoint/resume"). TPU
equivalent: persisted Arrow/Parquet shard files + a JSON manifest; device
arrays are rebuilt from the manifest on load. Layout:

    catalog/
      manifest.json                  # schema specs + file lists + counts
      <type>/part-<bin>.parquet      # one file per time partition (or part-all)

Time-partitioned files are the ``TablePartition``/``DateTimeScheme`` role
(SURVEY.md §2.12): queries could prune partitions at load; compaction is a
rewrite of the manifest + files.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from geomesa_tpu.io.arrow import from_arrow, to_arrow
from geomesa_tpu.schema.sft import parse_spec

MANIFEST = "manifest.json"
# catalog format history (load() accepts every version listed):
#   1 — rounds 1-2: spec + count + scheme + files
#   2 — adds per-type "index_layout" stamps ("current" | "legacy") so a
#       reload plans with the same curve generation the data was indexed
#       under (the reference's legacy key-space role,
#       geomesa-index-api/.../index/z3/legacy/, AttributeIndexV7.scala:1)
FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)


def save(
    ds, path: str, partition_by_time: bool = True,
    file_format: str = "parquet", durable: bool | None = None,
) -> dict:
    """Persist every schema + table of a DataStore; returns the manifest.

    Partition layout follows each schema's partition scheme (user-data
    ``geomesa.fs.scheme`` — datetime/z2/attribute/composite/flat, the
    ``PartitionScheme.scala`` SPI role); ``partition_by_time=False`` forces
    flat. ``file_format``: ``"parquet"`` (default) or ``"orc"`` — the two
    columnar tiers of ``geomesa-fs`` (SURVEY.md §2.12).

    ``durable=True`` fsyncs shard contents BEFORE their renames and the
    parent directories after (plus the manifest and catalog root): without
    it, a machine crash shortly after the rename can surface an
    empty/torn shard under the committed name — rename orders metadata,
    not data. Defaults ON for WAL-mode checkpoints (the durability plane's
    RPO contract, docs/operations.md § Durability & recovery) and off for
    plain saves (SIGKILL-only durability needs no fsync).

    WAL-mode saves additionally stamp ``(global seq, per-topic applied
    seq)`` into the manifest — the recovery replay floor — and durably
    trim committed WAL segments below the stamps afterwards, and they are
    INCREMENTAL: a type whose ``(ident, data epoch, wal seq)`` stamp is
    unchanged since the previous manifest reuses its shard files instead
    of rewriting them.

    Catalog mutation happens under an exclusive cross-process lock
    (``DistributedLocking.scala:14`` role — :mod:`geomesa_tpu.utils.locks`),
    so concurrent writers can't interleave shard renames / manifest flips.
    """
    from geomesa_tpu.utils.locks import catalog_lock

    if file_format not in ("parquet", "orc"):
        raise ValueError(f"unsupported format: {file_format!r}")
    with catalog_lock(path):
        return _save_locked(ds, path, partition_by_time, file_format,
                            durable=durable)


def _fsync_file(path) -> None:
    fd = os.open(str(path), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path) -> None:
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:  # pragma: no cover — platform-dependent
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_table(at: pa.Table, tmp: Path, file_format: str) -> None:
    if file_format == "orc":
        from pyarrow import orc

        # ORC writer rejects dictionary-encoded columns: decode first
        at = at.combine_chunks()
        cols = []
        for i, col in enumerate(at.columns):
            if pa.types.is_dictionary(col.type):
                col = col.cast(col.type.value_type)
            cols.append(col)
        at = pa.table(cols, names=at.column_names)
        orc.write_table(at, str(tmp))
    else:
        pq.write_table(at, tmp)


def _read_table(path: Path, file_format: str, columns=None) -> pa.Table:
    if file_format == "orc":
        from pyarrow import orc

        at = orc.read_table(str(path), columns=columns)
        # ORC widens timestamp[ms] → timestamp[ns]; restore the ms unit the
        # arrow↔columnar mapping expects
        cols, changed = [], False
        for col in at.columns:
            if pa.types.is_timestamp(col.type) and col.type.unit != "ms":
                col = col.cast(pa.timestamp("ms"))
                changed = True
            cols.append(col)
        return pa.table(cols, names=at.column_names) if changed else at
    return pq.read_table(path, columns=columns)


def _stage_type(ds, name: str, root: Path, gen: int,
                partition_by_time: bool, file_format: str,
                staged: list) -> dict:
    """Compact + write one type's shards under temp names (appended to
    ``staged`` for the caller's atomic rename pass) → its manifest entry."""
    ds.compact(name)  # fold the hot tier in so the catalog is fully sorted
    st = ds._state(name)
    tdir = root / name
    tdir.mkdir(exist_ok=True)
    files = []
    count = 0
    scheme_spec = "flat"
    if st.table is not None and len(st.table):
        count = len(st.table)
        if partition_by_time:
            from geomesa_tpu.store.partitions import scheme_for

            scheme = scheme_for(st.sft)
            scheme_spec = str(
                (st.sft.user_data or {}).get("geomesa.fs.scheme", "datetime")
            )
            keys = scheme.keys(st.sft, st.table)
            parts = {
                str(k): np.nonzero(keys == k)[0] for k in np.unique(keys)
            }
        else:
            parts = {"all": np.arange(count)}
        # lossless WKB by default (reference stores full-precision
        # doubles); schemas may opt into compact fixed-point TWKB via
        # user-data — the codec tag in each file's field metadata keeps
        # catalogs readable either way
        geom_enc = str(
            (st.sft.user_data or {}).get("geomesa.fs.geometry-encoding", "wkb")
        )
        twkb_prec = int(
            (st.sft.user_data or {}).get("geomesa.twkb.precision", 7)
        )
        for key, rows in parts.items():
            at = to_arrow(
                st.table.take(rows),
                geometry_encoding=geom_enc,
                twkb_precision=twkb_prec,
            )
            # short digest disambiguates keys the sanitizer would collide
            # (e.g. 'v 1' and 'v-1' both sanitize to 'v-1')
            import hashlib

            safe = "".join(
                c if c.isalnum() or c in "._" else "-" for c in str(key)
            )[:40]
            digest = hashlib.sha1(str(key).encode()).hexdigest()[:8]
            fn = f"part-{safe}-{digest}-g{gen}.{file_format}"
            tmp = tdir / (fn + ".tmp")
            _write_table(at, tmp, file_format)
            staged.append((tmp, tdir / fn))
            files.append(
                {"file": fn, "rows": int(len(rows)), "partition": str(key)}
            )
    return {
        "spec": st.sft.to_spec(),
        "count": count,
        "scheme": scheme_spec,
        "index_layout": st.sft.index_layout,
        "files": files,
    }


def _stage_or_reuse(ds, name: str, root: Path, gen: int,
                    partition_by_time: bool, file_format: str,
                    staged: list, prev_entry: dict | None) -> dict:
    """Incremental-checkpoint staging: a type whose ``(ident, data epoch,
    wal seq)`` matches the previous manifest entry has had NO mutation
    since that checkpoint — reuse its entry (shard files untouched)
    instead of re-compacting and rewriting. The ident guard keeps a
    delete+recreate of the same name (whose epoch tuple restarts at the
    same values) from resurrecting the dead table's files."""
    st = ds._state(name)
    if prev_entry is not None and prev_entry.get("ident") == st.ident:
        with st.lock:
            unchanged = (
                prev_entry.get("data_epoch") == list(st.data_epoch())
                and prev_entry.get("wal_seq") == st.wal_seq
                and prev_entry.get("spec") == st.sft.to_spec()
            )
        if unchanged:
            from geomesa_tpu.store import wal as _walmod

            _walmod._note(checkpoint_skipped_types=1)
            return dict(prev_entry)
    entry = _stage_type(ds, name, root, gen, partition_by_time,
                        file_format, staged)
    entry["data_epoch"] = list(st.data_epoch())
    return entry


class SchemaExistsError(ValueError):
    """Raised by :func:`register_schema` for the losing concurrent creator."""


def _read_or_init_manifest(root: Path, file_format: str = "parquet") -> dict:
    mpath = root / MANIFEST
    if mpath.exists():
        manifest = json.loads(mpath.read_text())
        if manifest.get("version") not in SUPPORTED_VERSIONS:
            raise ValueError(
                f"unsupported catalog version: {manifest.get('version')}"
            )
        return manifest
    return {
        "version": FORMAT_VERSION,
        "generation": 0,
        "format": file_format,
        "types": {},
    }


def _write_manifest(root: Path, manifest: dict) -> None:
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    os.replace(mtmp, root / MANIFEST)


def register_schema(path: str, sft) -> dict:
    """Coordinated schema CREATION in a shared catalog: merge a zero-row
    entry for ``sft`` into the manifest under the cross-host catalog lock.

    The multi-writer half of the ``DistributedLocking.scala:14`` role
    (SURVEY.md §2.3): many processes/hosts share one catalog; exactly one
    concurrent ``register_schema`` of a name wins, losers raise
    :class:`SchemaExistsError`, and the manifest can never tear (tmp-write
    + atomic rename, all under :func:`geomesa_tpu.utils.locks.catalog_lock`
    = flock + expiring lease). Unlike :func:`save` — a whole-store
    checkpoint that OWNS its catalog — this merges, so writers owning
    different types coexist (see :func:`save_type`)."""
    from geomesa_tpu.utils.locks import catalog_lock

    with catalog_lock(path):
        root = Path(path)
        manifest = _read_or_init_manifest(root)
        if sft.name in manifest["types"]:
            raise SchemaExistsError(
                f"schema {sft.name!r} already exists in catalog {path!r}"
            )
        manifest["types"][sft.name] = {
            "spec": sft.to_spec(),
            "count": 0,
            "scheme": "flat",
            "index_layout": sft.index_layout,
            "files": [],
        }
        (root / sft.name).mkdir(exist_ok=True)
        _write_manifest(root, manifest)
        return manifest


def save_type(ds, path: str, type_name: str, partition_by_time: bool = True,
              file_format: str | None = None, durable: bool = False) -> dict:
    """Coordinated per-type checkpoint into a SHARED catalog: write ONE
    type's shards and merge its manifest entry, leaving every other type's
    entry and files untouched (the multi-writer companion of
    :func:`register_schema`; :func:`save` remains the whole-store
    checkpoint). Same crash-safe commit order as :func:`save`: shards
    rename in, manifest flips atomically, then only THIS type's stale
    generations are collected. Returns the new manifest entry."""
    from geomesa_tpu.utils.locks import catalog_lock

    if getattr(ds, "_wal", None) is not None:
        # a per-type merge would rewrite this type's shards while leaving
        # the manifest's WAL replay floors stale — the next recovery would
        # re-apply already-persisted records (duplicate rows). WAL-mode
        # stores checkpoint through save() (whole-store, stamp-coordinated).
        raise ValueError(
            "save_type is not supported on a WAL-attached store; use "
            "DataStore.save (the WAL-stamped whole-store checkpoint)")
    with catalog_lock(path):
        root = Path(path)
        root.mkdir(parents=True, exist_ok=True)
        manifest = _read_or_init_manifest(
            root, file_format=file_format or "parquet"
        )
        fmt = manifest.get("format", "parquet")
        if file_format is not None and file_format != fmt:
            raise ValueError(
                f"catalog format is {fmt!r}; cannot save {file_format!r}"
            )
        gen = int(manifest.get("generation", 0)) + 1
        manifest["generation"] = gen
        staged: list[tuple[Path, Path]] = []
        entry = _stage_type(
            ds, type_name, root, gen, partition_by_time, fmt, staged
        )
        manifest["types"][type_name] = entry
        dirs = set()
        for tmp, final in staged:
            if durable:  # see save(): rename orders metadata, not data
                _fsync_file(tmp)
            os.replace(tmp, final)
            dirs.add(final.parent)
        if durable:
            for d in dirs:
                _fsync_dir(d)
        _write_manifest(root, manifest)
        if durable:
            _fsync_dir(root)
        keep = {f["file"] for f in entry["files"]}
        for p in (root / type_name).glob("part-*"):
            if p.name not in keep:
                p.unlink()
        return entry


SHARD_MANIFEST = "shard.json"


def save_shard(ds, type_name: str, path: str, selector, *,
               durable: bool = True, file_format: str = "parquet") -> dict:
    """Shard-scoped export of ONE type's row subset, stamped with the
    source's WAL replay floor — the live-migration ship format
    (serving/elastic.py).

    Unlike :func:`save_type` — which REFUSES WAL-attached stores because
    merging one type's shards into a shared catalog leaves the
    manifest's replay floors stale — this writes a standalone
    self-contained bundle that never touches the source's catalog or
    trims its WAL, so it is safe on a live WAL-mode store: the snapshot
    and the floor are captured at the SAME instant under the type's
    ``wal_lock`` (the write path's commit lock), which means every
    record with seq > ``wal_floor`` is exactly the tail the destination
    must replay on top of the bundle — no gap, no overlap.

    ``selector(table) -> bool mask | row indices`` picks the shard's
    rows (the caller owns the keying — the router's shard function
    stays in the serving layer). Layout::

        <path>/
          shard.json            # type, spec, rows, wal_floor, file
          rows.<format>         # the selected rows (absent when empty)

    ``durable`` fsyncs the data file before its rename and the bundle
    directory after (same rationale as :func:`save`). Returns the shard
    manifest. Non-WAL stores export with ``wal_floor = None``.
    """
    from geomesa_tpu.schema.columnar import FeatureTable

    if file_format not in ("parquet", "orc"):
        raise ValueError(f"unsupported format: {file_format!r}")
    st = ds._state(type_name)
    wal = getattr(ds, "_wal", None)

    def _capture():
        # lock order matches the mutation paths: wal_lock > mutate_lock
        # > lock (docs/concurrency.md) — holding wal_lock blocks every
        # WAL-mode mutation, so rows and floor are one consistent cut
        with st.mutate_lock:
            main, _, delta, _ = st.consume_snapshot()
        tables = [t for t in (main, delta) if t is not None and len(t)]
        if not tables:
            return None
        return tables[0] if len(tables) == 1 else FeatureTable.concat(tables)

    if wal is not None:
        with st.wal_lock:
            combined = _capture()
            with st.lock:
                floor = st.wal_seq
    else:
        combined = _capture()
        floor = None

    # row selection + file I/O run OUTSIDE every store lock: the captured
    # tables are immutable snapshots
    if combined is None:
        table = None
    else:
        rows = np.asarray(selector(combined))
        if rows.dtype == bool:
            rows = np.nonzero(rows)[0]
        table = combined.take(rows) if len(rows) else None

    root = Path(path)
    root.mkdir(parents=True, exist_ok=True)
    manifest = {
        "version": 1,
        "type": type_name,
        "spec": st.sft.to_spec(),
        "rows": 0 if table is None else int(len(table)),
        "wal_floor": floor,
        "format": file_format,
        "file": None,
    }
    if table is not None:
        geom_enc = str(
            (st.sft.user_data or {}).get("geomesa.fs.geometry-encoding",
                                         "wkb"))
        twkb_prec = int(
            (st.sft.user_data or {}).get("geomesa.twkb.precision", 7))
        at = to_arrow(table, geometry_encoding=geom_enc,
                      twkb_precision=twkb_prec)
        fn = f"rows.{file_format}"
        tmp = root / (fn + ".tmp")
        _write_table(at, tmp, file_format)
        if durable:
            _fsync_file(tmp)
        os.replace(tmp, root / fn)
        manifest["file"] = fn
    mtmp = root / (SHARD_MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    if durable:
        _fsync_file(mtmp)
    os.replace(mtmp, root / SHARD_MANIFEST)
    if durable:
        _fsync_dir(root)
    return manifest


def load_shard(ds, path: str) -> int:
    """Bulk-load a :func:`save_shard` bundle into ``ds`` (the migration
    destination). The type must already exist with a matching attribute
    layout; the rows append through the NORMAL write path — on a
    WAL-attached destination they journal like any other write, so a
    destination crash after cutover recovers them from its own WAL.
    Returns the number of rows loaded."""
    root = Path(path)
    manifest = json.loads((root / SHARD_MANIFEST).read_text())
    type_name = manifest["type"]
    sft = ds.get_schema(type_name)
    want = parse_spec(type_name, manifest["spec"])
    if [a.name for a in want.attributes] != [a.name for a in sft.attributes]:
        raise ValueError(
            f"shard bundle schema mismatch for {type_name!r}")
    if not manifest.get("file"):
        return 0
    at = _read_table(root / manifest["file"],
                     manifest.get("format", "parquet"))
    table = from_arrow(sft, at)
    ds.write(type_name, table)
    return len(table)


def _save_locked(ds, path: str, partition_by_time: bool, file_format: str,
                 durable: bool | None = None) -> dict:
    from geomesa_tpu.resilience import faults as _faults

    wal = getattr(ds, "_wal", None)
    if wal is not None and getattr(ds, "_wal_unreplayed", False):
        # stamping + trimming around a tail that was never applied would
        # DESTROY acked history (the post-save trim reclaims below the
        # stamps) — recovery must account for it first
        from geomesa_tpu.store.wal import WalTailError

        raise WalTailError(
            f"WAL {wal.path!r} holds un-replayed acked records; refusing "
            f"to checkpoint over them — open the catalog with "
            f"DataStore.open(..., recover=True) first")
    if durable is None:
        durable = wal is not None
    root = Path(path)
    root.mkdir(parents=True, exist_ok=True)
    # generation-unique shard names: renames must never clobber files the
    # *live* manifest references, or a crash between shard renames and the
    # manifest flip would leave a hybrid (old manifest → new data) checkpoint
    gen = 0
    prev_types: dict = {}
    mpath = root / MANIFEST
    if mpath.exists():
        try:
            prev = json.loads(mpath.read_text())
            gen = int(prev.get("generation", 0)) + 1
            if prev.get("format", "parquet") == file_format:
                prev_types = prev.get("types", {})
        except (ValueError, json.JSONDecodeError):
            gen = 1
    manifest = {
        "version": FORMAT_VERSION,
        "generation": gen,
        "format": file_format,
        "types": {},
    }
    wal_stamps: dict | None = None
    if wal is not None:
        from geomesa_tpu.store import wal as _walmod

        # schema stamp + type list captured ATOMICALLY under the WAL's
        # schema-order lock: every schema op at/below the stamp is in this
        # list; ops after it carry larger seqs and replay over the
        # checkpoint (docs/operations.md § Durability & recovery)
        with wal.schema_lock:
            names = ds.list_schemas()
            wal_stamps = {
                "seq": wal.seq_highwater(),
                "topics": {_walmod.SCHEMA_TOPIC: ds._wal_schema_seq},
            }
    else:
        names = ds.list_schemas()
    staged: list[tuple[Path, Path]] = []  # (tmp, final) shard renames
    for name in names:
        if wal is not None:
            st = ds._state(name)
            # wal_lock: the applied-seq stamp and the staged snapshot must
            # be the same instant — a write between them would be covered
            # by neither the checkpoint nor the replay floor
            with st.wal_lock:
                entry = _stage_or_reuse(
                    ds, name, root, gen, partition_by_time, file_format,
                    staged, prev_types.get(name))
                with st.lock:
                    entry["ident"] = st.ident
                    entry["wal_seq"] = st.wal_seq
                wal_stamps["topics"][_walmod.topic_for(name)] = entry["wal_seq"]
            manifest["types"][name] = entry
        else:
            manifest["types"][name] = _stage_type(
                ds, name, root, gen, partition_by_time, file_format, staged
            )
    if wal_stamps is not None:
        manifest["wal"] = wal_stamps

    # crash-safe commit order: new shards land under temp names above and
    # rename into generation-unique final names (never overwriting a file the
    # old manifest references); the manifest then replaces atomically, and
    # lastly stale generations are garbage-collected — a crash at any point
    # leaves either the old or the new checkpoint loadable intact.
    # durable mode additionally fsyncs shard CONTENTS before each rename
    # and the parent directories after: rename orders metadata, not data —
    # without the data sync a machine crash can surface an empty shard
    # under the committed name (the satellite-1 torn-shard bug)
    dirs = set()
    for i, (tmp, final) in enumerate(staged):
        if i:
            _faults.crash_point("ckpt.mid_shard_renames")
        if durable:
            _fsync_file(tmp)
        os.replace(tmp, final)
        dirs.add(final.parent)
    if durable:
        for d in dirs:
            _fsync_dir(d)
    _faults.crash_point("ckpt.pre_manifest_replace")
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    if durable:
        _fsync_file(mtmp)
    os.replace(mtmp, root / MANIFEST)
    if durable:
        _fsync_dir(root)
    if wal is not None:
        # the manifest is committed: everything below the stamps is
        # durably covered — reclaim it so WAL disk stays bounded
        wal.note_checkpoint(wal_stamps["topics"], wal_stamps["seq"])

    for name, meta in manifest["types"].items():
        keep = {f["file"] for f in meta["files"]}
        tdir = root / name
        for p in tdir.glob("part-*"):
            if p.name not in keep:
                p.unlink()
    wal_path = None
    if wal is not None:
        try:
            wal_path = Path(wal.path).resolve()
        except OSError:  # pragma: no cover
            pass
    for p in root.iterdir():
        if p.is_dir() and p.name not in manifest["types"]:
            # the durability WAL lives INSIDE the catalog by default
            # (<catalog>/wal): deleted-type GC must never eat it
            if p.name == "wal" or (wal_path is not None
                                   and p.resolve() == wal_path):
                continue
            import shutil

            shutil.rmtree(p)
    # cost-model persistence: snapshot the learned cost profiles +
    # calibration alongside the catalog save (no-op unless
    # GEOMESA_TPU_WORKLOAD_DIR names a sidecar location)
    from geomesa_tpu.obs import devmon

    devmon.save_cost_snapshot()
    return manifest


def upgrade(path: str) -> int:
    """Migrate a catalog manifest to the CURRENT format version in place.

    The data files are untouched — only the manifest is rewritten (v1 → v2
    adds per-type ``index_layout`` stamps derived from each spec's
    user-data). Returns the version migrated FROM. Atomic: the new manifest
    replaces the old via rename, so a crash leaves a loadable catalog.
    """
    root = Path(path)
    manifest = json.loads((root / MANIFEST).read_text())
    version = int(manifest.get("version", 0))
    if version == FORMAT_VERSION:
        return version
    if version not in SUPPORTED_VERSIONS:
        raise ValueError(f"cannot upgrade catalog version {version}")
    for name, meta in manifest["types"].items():
        if "index_layout" not in meta:
            meta["index_layout"] = parse_spec(name, meta["spec"]).index_layout
    manifest["version"] = FORMAT_VERSION
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    os.replace(mtmp, root / MANIFEST)
    return version


def load(
    path: str,
    backend: str = "tpu",
    column_group: str | None = None,
    filter=None,
    into=None,
):
    """Restore a DataStore (device state rebuilt) from a catalog directory.

    ``column_group``: load only that group's columns (ColumnGroups role,
    SURVEY.md §2.3) — the parquet read materializes the reduced attribute
    set, so HBM/host residency scales with the group, not the full schema.
    Schemas without the named group load in full.

    ``filter`` (CQL string or AST): partition PRUNING — only files whose
    partition key can contain matches are read (the reference's
    partition-scheme query pruning, ``PartitionScheme.scala`` role). The
    filter is NOT applied row-wise; the restored store holds every row of
    the surviving partitions and queries still run normally.

    ``into``: restore into an EXISTING empty DataStore instead of
    constructing one — the recovery path (``DataStore.open``) loads the
    checkpoint into the store that already holds the WAL lock.
    """
    from geomesa_tpu.schema.columnar import FeatureTable
    from geomesa_tpu.store.datastore import DataStore

    root = Path(path)
    manifest = json.loads((root / MANIFEST).read_text())
    if manifest.get("version") not in SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported catalog version: {manifest.get('version')}")
    file_format = manifest.get("format", "parquet")
    if into is not None:
        if into.list_schemas():
            raise ValueError("load(into=) requires an empty DataStore")
        ds = into
    else:
        ds = DataStore(backend=backend)
    # a WAL-attached store (into= from recovery, or an ambient
    # GEOMESA_TPU_WAL) must NOT journal its own checkpoint restore: the
    # rows being written ARE the persisted history, and journaling them
    # would replay them a second time over the next recovery
    prev_replay = getattr(ds, "_wal_replay", False)
    if getattr(ds, "_wal", None) is not None:
        ds._wal_replay = True
    try:
        _load_types(ds, root, manifest, file_format, column_group, filter)
    finally:
        ds._wal_replay = prev_replay
    # cost-model persistence (docs/observability.md § Cost-model
    # persistence): learned per-(type, plan-signature) p50 rankings +
    # calibration reload from the GEOMESA_TPU_WORKLOAD_DIR sidecar, so
    # the adaptive planner opens warm instead of re-probing from scratch
    from geomesa_tpu.obs import devmon

    devmon.load_cost_snapshot()
    return ds


def _load_types(ds, root: Path, manifest: dict, file_format: str,
                column_group, filter) -> None:
    from geomesa_tpu.schema.columnar import FeatureTable

    for name, meta in manifest["types"].items():
        sft = parse_spec(name, meta["spec"])
        # v2 index-layout stamp wins over (and back-fills) the spec's
        # user-data, so the reload plans with the curves the data was
        # indexed under; v1 manifests predate legacy layouts → current
        layout = meta.get("index_layout")
        if layout == "legacy":
            sft.user_data["geomesa.index.layout"] = "legacy"
        pruner = None
        extraction = None
        if filter is not None:
            from geomesa_tpu.filter.bounds import extract
            from geomesa_tpu.filter.cql import parse
            from geomesa_tpu.store.partitions import scheme_for

            f_ast = parse(filter) if isinstance(filter, str) else filter
            attrs = tuple(a.name for a in sft.attributes if not a.type.is_geometry)
            extraction = extract(f_ast, sft.geom_field, sft.dtg_field, attrs)
            pruner = scheme_for(sft)
        columns = None
        if column_group is not None:
            from geomesa_tpu.schema.column_groups import ColumnGroups

            groups = ColumnGroups(sft)
            if column_group in groups.groups:
                sft = groups.reduced_sft(column_group)
                columns = ["__fid__"] + [a.name for a in sft.attributes]
        ds.create_schema(sft)
        tables = []
        pruned = 0
        for f in meta["files"]:
            if pruner is not None and not pruner.prune(
                sft, extraction, f["partition"]
            ):
                pruned += 1
                continue
            at = _read_table(root / name / f["file"], file_format, columns=columns)
            tables.append(from_arrow(sft, at))
        if tables:
            table = tables[0] if len(tables) == 1 else FeatureTable.concat(tables)
            ds.write(name, table)
            ds.compact(name)  # restored data is the main tier, not hot writes
        ds.metrics.counter(f"catalog.partitions_pruned.{name}").inc(pruned)
