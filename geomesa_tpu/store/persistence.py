"""Catalog persistence: save/load a DataStore to a directory (checkpoint/resume).

The reference's durable state is the store itself plus catalog metadata
(schema specs, stats) — ``metadata/TableBasedMetadata.scala``,
``fs/.../FileBasedMetadata.scala`` (SURVEY.md §5 "checkpoint/resume"). TPU
equivalent: persisted Arrow/Parquet shard files + a JSON manifest; device
arrays are rebuilt from the manifest on load. Layout:

    catalog/
      manifest.json                  # schema specs + file lists + counts
      <type>/part-<bin>.parquet      # one file per time partition (or part-all)

Time-partitioned files are the ``TablePartition``/``DateTimeScheme`` role
(SURVEY.md §2.12): queries could prune partitions at load; compaction is a
rewrite of the manifest + files.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from geomesa_tpu.io.arrow import from_arrow, to_arrow
from geomesa_tpu.schema.sft import parse_spec

MANIFEST = "manifest.json"
FORMAT_VERSION = 1


def save(ds, path: str, partition_by_time: bool = True) -> dict:
    """Persist every schema + table of a DataStore; returns the manifest.

    Catalog mutation happens under an exclusive cross-process lock
    (``DistributedLocking.scala:14`` role — :mod:`geomesa_tpu.utils.locks`),
    so concurrent writers can't interleave shard renames / manifest flips.
    """
    from geomesa_tpu.utils.locks import catalog_lock

    with catalog_lock(path):
        return _save_locked(ds, path, partition_by_time)


def _save_locked(ds, path: str, partition_by_time: bool) -> dict:
    root = Path(path)
    root.mkdir(parents=True, exist_ok=True)
    # generation-unique shard names: renames must never clobber files the
    # *live* manifest references, or a crash between shard renames and the
    # manifest flip would leave a hybrid (old manifest → new data) checkpoint
    gen = 0
    mpath = root / MANIFEST
    if mpath.exists():
        try:
            gen = int(json.loads(mpath.read_text()).get("generation", 0)) + 1
        except (ValueError, json.JSONDecodeError):
            gen = 1
    manifest = {"version": FORMAT_VERSION, "generation": gen, "types": {}}
    staged: list[tuple[Path, Path]] = []  # (tmp, final) shard renames
    for name in ds.list_schemas():
        ds.compact(name)  # fold the hot tier in so the catalog is fully sorted
        st = ds._state(name)
        tdir = root / name
        tdir.mkdir(exist_ok=True)
        files = []
        count = 0
        if st.table is not None and len(st.table):
            count = len(st.table)
            parts = _partitions(st) if partition_by_time else {"all": np.arange(count)}
            for key, rows in parts.items():
                at = to_arrow(st.table.take(rows))
                fn = f"part-{key}-g{gen}.parquet"
                tmp = tdir / (fn + ".tmp")
                pq.write_table(at, tmp)
                staged.append((tmp, tdir / fn))
                files.append({"file": fn, "rows": int(len(rows)), "partition": str(key)})
        manifest["types"][name] = {
            "spec": st.sft.to_spec(),
            "count": count,
            "files": files,
        }

    # crash-safe commit order: new shards land under temp names above and
    # rename into generation-unique final names (never overwriting a file the
    # old manifest references); the manifest then replaces atomically, and
    # lastly stale generations are garbage-collected — a crash at any point
    # leaves either the old or the new checkpoint loadable intact
    for tmp, final in staged:
        os.replace(tmp, final)
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    os.replace(mtmp, root / MANIFEST)

    for name, meta in manifest["types"].items():
        keep = {f["file"] for f in meta["files"]}
        tdir = root / name
        for p in tdir.glob("part-*.parquet*"):
            if p.name not in keep:
                p.unlink()
    for p in root.iterdir():
        if p.is_dir() and p.name not in manifest["types"]:
            import shutil

            shutil.rmtree(p)
    return manifest


def _partitions(st) -> dict:
    """Rows grouped by z3 time bin (coarse time partitioning)."""
    sft = st.sft
    if sft.dtg_field is None:
        return {"all": np.arange(len(st.table))}
    from geomesa_tpu.curve.binned_time import BinnedTime

    bins, _ = BinnedTime(sft.z3_interval).to_bin_and_offset(st.table.dtg_millis())
    out = {}
    for b in np.unique(bins):
        out[int(b)] = np.nonzero(bins == b)[0]
    return out


def load(path: str, backend: str = "tpu", column_group: str | None = None):
    """Restore a DataStore (device state rebuilt) from a catalog directory.

    ``column_group``: load only that group's columns (ColumnGroups role,
    SURVEY.md §2.3) — the parquet read materializes the reduced attribute
    set, so HBM/host residency scales with the group, not the full schema.
    Schemas without the named group load in full.
    """
    from geomesa_tpu.schema.columnar import FeatureTable
    from geomesa_tpu.store.datastore import DataStore

    root = Path(path)
    manifest = json.loads((root / MANIFEST).read_text())
    if manifest.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported catalog version: {manifest.get('version')}")
    ds = DataStore(backend=backend)
    for name, meta in manifest["types"].items():
        sft = parse_spec(name, meta["spec"])
        columns = None
        if column_group is not None:
            from geomesa_tpu.schema.column_groups import ColumnGroups

            groups = ColumnGroups(sft)
            if column_group in groups.groups:
                sft = groups.reduced_sft(column_group)
                columns = ["__fid__"] + [a.name for a in sft.attributes]
        ds.create_schema(sft)
        tables = []
        for f in meta["files"]:
            at = pq.read_table(root / name / f["file"], columns=columns)
            tables.append(from_arrow(sft, at))
        if tables:
            table = tables[0] if len(tables) == 1 else FeatureTable.concat(tables)
            ds.write(name, table)
            ds.compact(name)  # restored data is the main tier, not hot writes
    return ds
