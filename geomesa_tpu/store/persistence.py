"""Catalog persistence: save/load a DataStore to a directory (checkpoint/resume).

The reference's durable state is the store itself plus catalog metadata
(schema specs, stats) — ``metadata/TableBasedMetadata.scala``,
``fs/.../FileBasedMetadata.scala`` (SURVEY.md §5 "checkpoint/resume"). TPU
equivalent: persisted Arrow/Parquet shard files + a JSON manifest; device
arrays are rebuilt from the manifest on load. Layout:

    catalog/
      manifest.json                  # schema specs + file lists + counts
      <type>/part-<bin>.parquet      # one file per time partition (or part-all)

Time-partitioned files are the ``TablePartition``/``DateTimeScheme`` role
(SURVEY.md §2.12): queries could prune partitions at load; compaction is a
rewrite of the manifest + files.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from geomesa_tpu.io.arrow import from_arrow, to_arrow
from geomesa_tpu.schema.sft import parse_spec

MANIFEST = "manifest.json"
# catalog format history (load() accepts every version listed):
#   1 — rounds 1-2: spec + count + scheme + files
#   2 — adds per-type "index_layout" stamps ("current" | "legacy") so a
#       reload plans with the same curve generation the data was indexed
#       under (the reference's legacy key-space role,
#       geomesa-index-api/.../index/z3/legacy/, AttributeIndexV7.scala:1)
FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)


def save(
    ds, path: str, partition_by_time: bool = True, file_format: str = "parquet"
) -> dict:
    """Persist every schema + table of a DataStore; returns the manifest.

    Partition layout follows each schema's partition scheme (user-data
    ``geomesa.fs.scheme`` — datetime/z2/attribute/composite/flat, the
    ``PartitionScheme.scala`` SPI role); ``partition_by_time=False`` forces
    flat. ``file_format``: ``"parquet"`` (default) or ``"orc"`` — the two
    columnar tiers of ``geomesa-fs`` (SURVEY.md §2.12).

    Catalog mutation happens under an exclusive cross-process lock
    (``DistributedLocking.scala:14`` role — :mod:`geomesa_tpu.utils.locks`),
    so concurrent writers can't interleave shard renames / manifest flips.
    """
    from geomesa_tpu.utils.locks import catalog_lock

    if file_format not in ("parquet", "orc"):
        raise ValueError(f"unsupported format: {file_format!r}")
    with catalog_lock(path):
        return _save_locked(ds, path, partition_by_time, file_format)


def _write_table(at: pa.Table, tmp: Path, file_format: str) -> None:
    if file_format == "orc":
        from pyarrow import orc

        # ORC writer rejects dictionary-encoded columns: decode first
        at = at.combine_chunks()
        cols = []
        for i, col in enumerate(at.columns):
            if pa.types.is_dictionary(col.type):
                col = col.cast(col.type.value_type)
            cols.append(col)
        at = pa.table(cols, names=at.column_names)
        orc.write_table(at, str(tmp))
    else:
        pq.write_table(at, tmp)


def _read_table(path: Path, file_format: str, columns=None) -> pa.Table:
    if file_format == "orc":
        from pyarrow import orc

        at = orc.read_table(str(path), columns=columns)
        # ORC widens timestamp[ms] → timestamp[ns]; restore the ms unit the
        # arrow↔columnar mapping expects
        cols, changed = [], False
        for col in at.columns:
            if pa.types.is_timestamp(col.type) and col.type.unit != "ms":
                col = col.cast(pa.timestamp("ms"))
                changed = True
            cols.append(col)
        return pa.table(cols, names=at.column_names) if changed else at
    return pq.read_table(path, columns=columns)


def _stage_type(ds, name: str, root: Path, gen: int,
                partition_by_time: bool, file_format: str,
                staged: list) -> dict:
    """Compact + write one type's shards under temp names (appended to
    ``staged`` for the caller's atomic rename pass) → its manifest entry."""
    ds.compact(name)  # fold the hot tier in so the catalog is fully sorted
    st = ds._state(name)
    tdir = root / name
    tdir.mkdir(exist_ok=True)
    files = []
    count = 0
    scheme_spec = "flat"
    if st.table is not None and len(st.table):
        count = len(st.table)
        if partition_by_time:
            from geomesa_tpu.store.partitions import scheme_for

            scheme = scheme_for(st.sft)
            scheme_spec = str(
                (st.sft.user_data or {}).get("geomesa.fs.scheme", "datetime")
            )
            keys = scheme.keys(st.sft, st.table)
            parts = {
                str(k): np.nonzero(keys == k)[0] for k in np.unique(keys)
            }
        else:
            parts = {"all": np.arange(count)}
        # lossless WKB by default (reference stores full-precision
        # doubles); schemas may opt into compact fixed-point TWKB via
        # user-data — the codec tag in each file's field metadata keeps
        # catalogs readable either way
        geom_enc = str(
            (st.sft.user_data or {}).get("geomesa.fs.geometry-encoding", "wkb")
        )
        twkb_prec = int(
            (st.sft.user_data or {}).get("geomesa.twkb.precision", 7)
        )
        for key, rows in parts.items():
            at = to_arrow(
                st.table.take(rows),
                geometry_encoding=geom_enc,
                twkb_precision=twkb_prec,
            )
            # short digest disambiguates keys the sanitizer would collide
            # (e.g. 'v 1' and 'v-1' both sanitize to 'v-1')
            import hashlib

            safe = "".join(
                c if c.isalnum() or c in "._" else "-" for c in str(key)
            )[:40]
            digest = hashlib.sha1(str(key).encode()).hexdigest()[:8]
            fn = f"part-{safe}-{digest}-g{gen}.{file_format}"
            tmp = tdir / (fn + ".tmp")
            _write_table(at, tmp, file_format)
            staged.append((tmp, tdir / fn))
            files.append(
                {"file": fn, "rows": int(len(rows)), "partition": str(key)}
            )
    return {
        "spec": st.sft.to_spec(),
        "count": count,
        "scheme": scheme_spec,
        "index_layout": st.sft.index_layout,
        "files": files,
    }


class SchemaExistsError(ValueError):
    """Raised by :func:`register_schema` for the losing concurrent creator."""


def _read_or_init_manifest(root: Path, file_format: str = "parquet") -> dict:
    mpath = root / MANIFEST
    if mpath.exists():
        manifest = json.loads(mpath.read_text())
        if manifest.get("version") not in SUPPORTED_VERSIONS:
            raise ValueError(
                f"unsupported catalog version: {manifest.get('version')}"
            )
        return manifest
    return {
        "version": FORMAT_VERSION,
        "generation": 0,
        "format": file_format,
        "types": {},
    }


def _write_manifest(root: Path, manifest: dict) -> None:
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    os.replace(mtmp, root / MANIFEST)


def register_schema(path: str, sft) -> dict:
    """Coordinated schema CREATION in a shared catalog: merge a zero-row
    entry for ``sft`` into the manifest under the cross-host catalog lock.

    The multi-writer half of the ``DistributedLocking.scala:14`` role
    (SURVEY.md §2.3): many processes/hosts share one catalog; exactly one
    concurrent ``register_schema`` of a name wins, losers raise
    :class:`SchemaExistsError`, and the manifest can never tear (tmp-write
    + atomic rename, all under :func:`geomesa_tpu.utils.locks.catalog_lock`
    = flock + expiring lease). Unlike :func:`save` — a whole-store
    checkpoint that OWNS its catalog — this merges, so writers owning
    different types coexist (see :func:`save_type`)."""
    from geomesa_tpu.utils.locks import catalog_lock

    with catalog_lock(path):
        root = Path(path)
        manifest = _read_or_init_manifest(root)
        if sft.name in manifest["types"]:
            raise SchemaExistsError(
                f"schema {sft.name!r} already exists in catalog {path!r}"
            )
        manifest["types"][sft.name] = {
            "spec": sft.to_spec(),
            "count": 0,
            "scheme": "flat",
            "index_layout": sft.index_layout,
            "files": [],
        }
        (root / sft.name).mkdir(exist_ok=True)
        _write_manifest(root, manifest)
        return manifest


def save_type(ds, path: str, type_name: str, partition_by_time: bool = True,
              file_format: str | None = None) -> dict:
    """Coordinated per-type checkpoint into a SHARED catalog: write ONE
    type's shards and merge its manifest entry, leaving every other type's
    entry and files untouched (the multi-writer companion of
    :func:`register_schema`; :func:`save` remains the whole-store
    checkpoint). Same crash-safe commit order as :func:`save`: shards
    rename in, manifest flips atomically, then only THIS type's stale
    generations are collected. Returns the new manifest entry."""
    from geomesa_tpu.utils.locks import catalog_lock

    with catalog_lock(path):
        root = Path(path)
        root.mkdir(parents=True, exist_ok=True)
        manifest = _read_or_init_manifest(
            root, file_format=file_format or "parquet"
        )
        fmt = manifest.get("format", "parquet")
        if file_format is not None and file_format != fmt:
            raise ValueError(
                f"catalog format is {fmt!r}; cannot save {file_format!r}"
            )
        gen = int(manifest.get("generation", 0)) + 1
        manifest["generation"] = gen
        staged: list[tuple[Path, Path]] = []
        entry = _stage_type(
            ds, type_name, root, gen, partition_by_time, fmt, staged
        )
        manifest["types"][type_name] = entry
        for tmp, final in staged:
            os.replace(tmp, final)
        _write_manifest(root, manifest)
        keep = {f["file"] for f in entry["files"]}
        for p in (root / type_name).glob("part-*"):
            if p.name not in keep:
                p.unlink()
        return entry


def _save_locked(ds, path: str, partition_by_time: bool, file_format: str) -> dict:
    root = Path(path)
    root.mkdir(parents=True, exist_ok=True)
    # generation-unique shard names: renames must never clobber files the
    # *live* manifest references, or a crash between shard renames and the
    # manifest flip would leave a hybrid (old manifest → new data) checkpoint
    gen = 0
    mpath = root / MANIFEST
    if mpath.exists():
        try:
            gen = int(json.loads(mpath.read_text()).get("generation", 0)) + 1
        except (ValueError, json.JSONDecodeError):
            gen = 1
    manifest = {
        "version": FORMAT_VERSION,
        "generation": gen,
        "format": file_format,
        "types": {},
    }
    staged: list[tuple[Path, Path]] = []  # (tmp, final) shard renames
    for name in ds.list_schemas():
        manifest["types"][name] = _stage_type(
            ds, name, root, gen, partition_by_time, file_format, staged
        )

    # crash-safe commit order: new shards land under temp names above and
    # rename into generation-unique final names (never overwriting a file the
    # old manifest references); the manifest then replaces atomically, and
    # lastly stale generations are garbage-collected — a crash at any point
    # leaves either the old or the new checkpoint loadable intact
    for tmp, final in staged:
        os.replace(tmp, final)
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    os.replace(mtmp, root / MANIFEST)

    for name, meta in manifest["types"].items():
        keep = {f["file"] for f in meta["files"]}
        tdir = root / name
        for p in tdir.glob("part-*"):
            if p.name not in keep:
                p.unlink()
    for p in root.iterdir():
        if p.is_dir() and p.name not in manifest["types"]:
            import shutil

            shutil.rmtree(p)
    # cost-model persistence: snapshot the learned cost profiles +
    # calibration alongside the catalog save (no-op unless
    # GEOMESA_TPU_WORKLOAD_DIR names a sidecar location)
    from geomesa_tpu.obs import devmon

    devmon.save_cost_snapshot()
    return manifest


def upgrade(path: str) -> int:
    """Migrate a catalog manifest to the CURRENT format version in place.

    The data files are untouched — only the manifest is rewritten (v1 → v2
    adds per-type ``index_layout`` stamps derived from each spec's
    user-data). Returns the version migrated FROM. Atomic: the new manifest
    replaces the old via rename, so a crash leaves a loadable catalog.
    """
    root = Path(path)
    manifest = json.loads((root / MANIFEST).read_text())
    version = int(manifest.get("version", 0))
    if version == FORMAT_VERSION:
        return version
    if version not in SUPPORTED_VERSIONS:
        raise ValueError(f"cannot upgrade catalog version {version}")
    for name, meta in manifest["types"].items():
        if "index_layout" not in meta:
            meta["index_layout"] = parse_spec(name, meta["spec"]).index_layout
    manifest["version"] = FORMAT_VERSION
    mtmp = root / (MANIFEST + ".tmp")
    mtmp.write_text(json.dumps(manifest, indent=2))
    os.replace(mtmp, root / MANIFEST)
    return version


def load(
    path: str,
    backend: str = "tpu",
    column_group: str | None = None,
    filter=None,
):
    """Restore a DataStore (device state rebuilt) from a catalog directory.

    ``column_group``: load only that group's columns (ColumnGroups role,
    SURVEY.md §2.3) — the parquet read materializes the reduced attribute
    set, so HBM/host residency scales with the group, not the full schema.
    Schemas without the named group load in full.

    ``filter`` (CQL string or AST): partition PRUNING — only files whose
    partition key can contain matches are read (the reference's
    partition-scheme query pruning, ``PartitionScheme.scala`` role). The
    filter is NOT applied row-wise; the restored store holds every row of
    the surviving partitions and queries still run normally.
    """
    from geomesa_tpu.schema.columnar import FeatureTable
    from geomesa_tpu.store.datastore import DataStore

    root = Path(path)
    manifest = json.loads((root / MANIFEST).read_text())
    if manifest.get("version") not in SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported catalog version: {manifest.get('version')}")
    file_format = manifest.get("format", "parquet")
    ds = DataStore(backend=backend)
    for name, meta in manifest["types"].items():
        sft = parse_spec(name, meta["spec"])
        # v2 index-layout stamp wins over (and back-fills) the spec's
        # user-data, so the reload plans with the curves the data was
        # indexed under; v1 manifests predate legacy layouts → current
        layout = meta.get("index_layout")
        if layout == "legacy":
            sft.user_data["geomesa.index.layout"] = "legacy"
        pruner = None
        extraction = None
        if filter is not None:
            from geomesa_tpu.filter.bounds import extract
            from geomesa_tpu.filter.cql import parse
            from geomesa_tpu.store.partitions import scheme_for

            f_ast = parse(filter) if isinstance(filter, str) else filter
            attrs = tuple(a.name for a in sft.attributes if not a.type.is_geometry)
            extraction = extract(f_ast, sft.geom_field, sft.dtg_field, attrs)
            pruner = scheme_for(sft)
        columns = None
        if column_group is not None:
            from geomesa_tpu.schema.column_groups import ColumnGroups

            groups = ColumnGroups(sft)
            if column_group in groups.groups:
                sft = groups.reduced_sft(column_group)
                columns = ["__fid__"] + [a.name for a in sft.attributes]
        ds.create_schema(sft)
        tables = []
        pruned = 0
        for f in meta["files"]:
            if pruner is not None and not pruner.prune(
                sft, extraction, f["partition"]
            ):
                pruned += 1
                continue
            at = _read_table(root / name / f["file"], file_format, columns=columns)
            tables.append(from_arrow(sft, at))
        if tables:
            table = tables[0] if len(tables) == 1 else FeatureTable.concat(tables)
            ds.write(name, table)
            ds.compact(name)  # restored data is the main tier, not hot writes
        ds.metrics.counter(f"catalog.partitions_pruned.{name}").inc(pruned)
    # cost-model persistence (docs/observability.md § Cost-model
    # persistence): learned per-(type, plan-signature) p50 rankings +
    # calibration reload from the GEOMESA_TPU_WORKLOAD_DIR sidecar, so
    # the adaptive planner opens warm instead of re-probing from scratch
    from geomesa_tpu.obs import devmon

    devmon.load_cost_snapshot()
    return ds
