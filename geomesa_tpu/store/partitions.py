"""Partition schemes: directory-layout-as-coarse-index + query-time pruning.

Role parity: ``geomesa-fs-storage-api/.../PartitionScheme.scala`` and the
scheme implementations in ``geomesa-fs-storage-common/.../partitions/``
(DateTimeScheme, Z2Scheme, AttributeScheme, CompositeScheme, FlatScheme —
SURVEY.md §2.12): the partition key doubles as a coarse index, letting a
query prune whole files before any scan. Schemes are chosen per schema via
user-data ``geomesa.fs.scheme`` (e.g. ``datetime``, ``z2-4``, ``xz2-6``,
``attribute:name``, ``datetime,z2-4``). Point schemas partition tightest
with ``z2``; extended-geometry schemas should use ``xz2`` (enlarged-cell
semantics keep pruning exact for any feature extent).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter.bounds import Extraction

__all__ = ["scheme_for", "PartitionScheme"]


class PartitionScheme:
    """Maps rows → partition keys, and filter bounds → keep/skip predicate."""

    name = "flat"

    def keys(self, sft, table) -> np.ndarray:
        """(n,) object array of partition-key strings."""
        return np.full(len(table), "all", dtype=object)

    def prune(self, sft, extraction: Extraction | None, key: str) -> bool:
        """True = partition may contain matches (keep); False = provably not."""
        return True


class FlatScheme(PartitionScheme):
    name = "flat"


class DateTimeScheme(PartitionScheme):
    """One partition per z3 time bin (``DateTimeScheme`` role; the partition
    key is the bin ordinal, so interval bounds prune directly)."""

    name = "datetime"

    def keys(self, sft, table) -> np.ndarray:
        if sft.dtg_field is None:
            return np.full(len(table), "all", dtype=object)
        from geomesa_tpu.curve.binned_time import BinnedTime

        bins, _ = BinnedTime(sft.z3_interval).to_bin_and_offset(
            table.dtg_millis()
        )
        return np.array([f"bin{int(b)}" for b in bins], dtype=object)

    def prune(self, sft, extraction, key: str) -> bool:
        if (
            extraction is None
            or extraction.intervals is None
            or not key.startswith("bin")
            or sft.dtg_field is None
        ):
            return True
        from geomesa_tpu.curve.binned_time import BinnedTime

        binned = BinnedTime(sft.z3_interval)
        b = int(key[3:])
        lo_ms = int(binned.bin_start_millis(np.array([b]))[0])
        hi_ms = int(binned.bin_start_millis(np.array([b + 1]))[0]) - 1
        for lo, hi in extraction.intervals:
            if int(hi) >= lo_ms and int(lo) <= hi_ms:
                return True
        return False


class Z2Scheme(PartitionScheme):
    """One partition per ``bits``-per-dimension z2 prefix cell (``Z2Scheme``
    role): the key is the coarse Morton cell of the geometry centroid, so a
    bbox prunes to the cells its cover touches."""

    name = "z2"

    def __init__(self, bits: int = 4):
        if not (1 <= bits <= 12):
            raise ValueError(f"z2 scheme bits must be in [1, 12]: {bits}")
        self.bits = bits

    def _cells(self, x, y) -> np.ndarray:
        from geomesa_tpu.curve import zorder
        from geomesa_tpu.curve.normalize import lat as nlat, lon as nlon

        xi = nlon(self.bits).normalize(x)
        yi = nlat(self.bits).normalize(y)
        return zorder.encode2(xi, yi)

    def keys(self, sft, table) -> np.ndarray:
        if sft.geom_field is None:
            return np.full(len(table), "all", dtype=object)
        col = table.geom_column()
        if col.x is not None:
            cells = self._cells(col.x, col.y)
            return np.array(
                [f"z2_{self.bits}_{int(c)}" for c in cells], dtype=object
            )
        if col.bounds is None:
            return np.full(len(table), "all", dtype=object)
        # extended geometries: the centroid's cell only bounds the feature if
        # the whole bbox sits in that cell — otherwise the feature must go to
        # the unprunable spill partition or pruning would drop rows whose
        # extent reaches into cells the centroid is not in (use the xz2
        # scheme for extended-geometry schemas; this is the safe fallback)
        bb = col.bounds  # (n, 4) xmin ymin xmax ymax
        lo = self._cells(bb[:, 0], bb[:, 1])
        hi = self._cells(bb[:, 2], bb[:, 3])
        keys = np.array([f"z2_{self.bits}_{int(c)}" for c in lo], dtype=object)
        keys[lo != hi] = "all"
        return keys

    def prune(self, sft, extraction, key: str) -> bool:
        if extraction is None or extraction.boxes is None:
            return True
        parts = key.split("_")
        if len(parts) != 3 or parts[0] != "z2":
            return True
        bits, cell = int(parts[1]), int(parts[2])
        if bits != self.bits:
            return True
        from geomesa_tpu.curve import zorder
        from geomesa_tpu.curve.normalize import lat as nlat, lon as nlon

        ix, iy = zorder.decode2(np.array([cell], dtype=np.uint64))
        nx, ny = nlon(bits), nlat(bits)
        cell_x1 = float(nx.bin_lo(ix)[0])
        cell_x2 = float(nx.bin_hi(ix)[0])
        cell_y1 = float(ny.bin_lo(iy)[0])
        cell_y2 = float(ny.bin_hi(iy)[0])
        for x1, y1, x2, y2 in extraction.boxes:
            if x2 >= cell_x1 and x1 <= cell_x2 and y2 >= cell_y1 and y1 <= cell_y2:
                return True
        return False


class XZ2Scheme(PartitionScheme):
    """Extended-geometry partitioning with XZ enlarged-cell semantics
    (``XZ2Scheme`` role, after ``XZ2SFC.scala:24``): each feature keys to the
    finest quad-tree cell (level ≤ ``g``) whose *doubled* extent contains its
    bbox, anchored at the cell holding the bbox's lower-left corner. Pruning
    keeps a partition iff its doubled extent intersects a query box — exact
    for any geometry extent, no spill partition needed."""

    name = "xz2"

    def __init__(self, g: int = 6):
        if not (1 <= g <= 12):
            raise ValueError(f"xz2 scheme resolution must be in [1, 12]: {g}")
        self.g = g

    def _elements(self, bb: np.ndarray):
        """bbox (n,4) → (level, ix, iy) XZ elements."""
        w = np.clip(bb[:, 2] - bb[:, 0], 0.0, None)
        h = np.clip(bb[:, 3] - bb[:, 1], 0.0, None)
        # finest level where the doubled cell still covers the bbox:
        # cell_w(l) = 360/2^l, need w <= cell_w(l)  (doubled extent provides
        # the slack for arbitrary anchor alignment, as in XZ ordering)
        with np.errstate(divide="ignore"):
            lw = np.floor(np.log2(np.where(w > 0, 360.0 / w, np.inf)))
            lh = np.floor(np.log2(np.where(h > 0, 180.0 / h, np.inf)))
        lvl = np.clip(np.minimum(lw, lh), 0, self.g).astype(np.int64)
        cw = 360.0 / (2.0**lvl)
        ch = 180.0 / (2.0**lvl)
        nx = (2**lvl).astype(np.int64)
        ix = np.clip(((bb[:, 0] + 180.0) / cw).astype(np.int64), 0, nx - 1)
        iy = np.clip(((bb[:, 1] + 90.0) / ch).astype(np.int64), 0, nx - 1)
        return lvl, ix, iy

    def keys(self, sft, table) -> np.ndarray:
        if sft.geom_field is None:
            return np.full(len(table), "all", dtype=object)
        col = table.geom_column()
        if col.x is not None:
            bb = np.stack([col.x, col.y, col.x, col.y], axis=1)
        elif col.bounds is not None:
            bb = col.bounds
        else:
            return np.full(len(table), "all", dtype=object)
        lvl, ix, iy = self._elements(np.nan_to_num(bb))
        return np.array(
            [
                f"xz2_{self.g}_{int(l)}_{int(i)}_{int(j)}"
                for l, i, j in zip(lvl, ix, iy)
            ],
            dtype=object,
        )

    def prune(self, sft, extraction, key: str) -> bool:
        if extraction is None or extraction.boxes is None:
            return True
        parts = key.split("_")
        if len(parts) != 5 or parts[0] != "xz2" or int(parts[1]) != self.g:
            return True
        lvl, ix, iy = int(parts[2]), int(parts[3]), int(parts[4])
        cw = 360.0 / (2.0**lvl)
        ch = 180.0 / (2.0**lvl)
        # doubled extent: anchor cell plus one cell width/height of slack
        x1 = -180.0 + ix * cw
        y1 = -90.0 + iy * ch
        x2 = min(x1 + 2 * cw, 180.0)
        y2 = min(y1 + 2 * ch, 90.0)
        for qx1, qy1, qx2, qy2 in extraction.boxes:
            if qx2 >= x1 and qx1 <= x2 and qy2 >= y1 and qy1 <= y2:
                return True
        return False


class AttributeScheme(PartitionScheme):
    """One partition per attribute value (``AttributeScheme`` role); equality
    bounds on that attribute prune to the matching partition."""

    name = "attribute"

    def __init__(self, field: str):
        self.field = field

    def keys(self, sft, table) -> np.ndarray:
        col = table.columns.get(self.field)
        if col is None:
            return np.full(len(table), "all", dtype=object)
        return np.array([f"a_{v}" for v in col.values], dtype=object)

    def prune(self, sft, extraction, key: str) -> bool:
        bounds = extraction.attributes.get(self.field) if extraction else None
        if bounds is None or not key.startswith("a_"):
            return True
        # prune only on pure equality/IN covers (every interval a point);
        # range intervals keep everything — conservative over-approximation
        eqs = set()
        for lo, hi, lo_inc, hi_inc in bounds:
            if lo is None or hi is None or lo != hi or not (lo_inc and hi_inc):
                return True
            eqs.add(str(lo))
        return key[2:] in eqs


class CompositeScheme(PartitionScheme):
    """Schemes chained with ``/`` in the key (``CompositeScheme`` role):
    a partition survives pruning only if every component keeps its part."""

    name = "composite"

    def __init__(self, parts: list[PartitionScheme]):
        self.parts = parts

    def keys(self, sft, table) -> np.ndarray:
        all_keys = [p.keys(sft, table) for p in self.parts]
        return np.array(
            ["/".join(ks) for ks in zip(*all_keys)], dtype=object
        )

    def prune(self, sft, extraction, key: str) -> bool:
        segs = key.split("/")
        if len(segs) != len(self.parts):
            return True
        return all(
            p.prune(sft, extraction, s) for p, s in zip(self.parts, segs)
        )


def scheme_for(sft) -> PartitionScheme:
    """Resolve the schema's partition scheme from user-data
    ``geomesa.fs.scheme`` (comma-separated composite), default ``datetime``.
    """
    return scheme_from_spec(
        (sft.user_data or {}).get("geomesa.fs.scheme", "datetime")
    )


def scheme_from_spec(spec) -> PartitionScheme:
    """Parse a scheme spec string (as recorded in catalog manifests)."""
    parts = []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok == "flat":
            parts.append(FlatScheme())
        elif tok == "datetime":
            parts.append(DateTimeScheme())
        elif tok.startswith("xz2"):
            g = int(tok.split("-")[1]) if "-" in tok else 6
            parts.append(XZ2Scheme(g))
        elif tok.startswith("z2"):
            bits = int(tok.split("-")[1]) if "-" in tok else 4
            parts.append(Z2Scheme(bits))
        elif tok.startswith("attribute:"):
            parts.append(AttributeScheme(tok.split(":", 1)[1]))
        else:
            raise ValueError(f"unknown partition scheme: {tok!r}")
    if not parts:
        return FlatScheme()
    if len(parts) == 1:
        return parts[0]
    return CompositeScheme(parts)
