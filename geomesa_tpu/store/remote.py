"""Remote datastore client: a store whose scans run across an HTTP boundary.

Role parity: the reference federates independent stores with
``MergedDataStoreView.scala:31`` / ``MergedQueryRunner.scala``; each member
store reaches its own cluster over the network. Here a
:class:`RemoteDataStore` speaks to another process's REST endpoint
(:mod:`geomesa_tpu.web.app`) — filters ship as CQL text
(:func:`geomesa_tpu.filter.ast.to_cql`), results come back as Arrow IPC —
and plugs straight into ``MergedDataStoreView``, giving the multi-slice /
DCN federation story (SURVEY.md §2.20 P10): per-slice plans run where the
data lives, only Arrow results cross the wire.
"""

from __future__ import annotations

import json

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.resilience import http as rhttp
from geomesa_tpu.resilience.policy import (
    CircuitBreaker,
    CorruptPayloadError,
    RetryPolicy,
)
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec
from geomesa_tpu.store.datastore import QueryResult

__all__ = ["RemoteDataStore"]


class RemoteDataStore:
    """Client over a geomesa_tpu REST endpoint — reads AND writes.

    Implements the store surface ``MergedDataStoreView`` consumes
    (``get_schema`` / ``list_schemas`` / ``query`` / ``stats_count``), so a
    federation can mix in-process stores and remote slices freely; the
    write surface (``create_schema`` / ``write`` / ``update_features`` /
    ``delete_features`` / ``delete_schema``) forwards mutations to the
    owning process (VERDICT r3 item 3 — the write half of the multi-slice
    federation, SURVEY.md §2.20 P10). Conflicts surface as the same
    exception types the local store raises (ValueError for an existing
    schema, KeyError for missing features), so callers handle local and
    remote stores uniformly.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0,
                 forward_auths_header: str | None = None,
                 retry: RetryPolicy | None = None,
                 breaker: CircuitBreaker | None = None):
        # forward_auths_header: name of the TRUSTED header the remote's
        # AuthorizationsProvider is configured with (e.g.
        # "X-Geomesa-Auths"). When set, auths-scoped queries forward the
        # caller's auths in that header; when None (default), they FAIL
        # CLOSED — a remote that is not enforcing visibility must never
        # silently return unrestricted rows to a restricted caller.
        #
        # retry/breaker (docs/resilience.md): every exchange runs through
        # the resilience envelope — reads retry on 5xx/connect errors with
        # decorrelated-jitter backoff, mutations retry only on
        # connect-before-send failures, and the per-endpoint breaker fails
        # fast (CircuitOpenError) once this member has proven unhealthy.
        # Pass RetryPolicy(max_attempts=1) to disable retries, or share
        # one breaker across clients of the same endpoint.
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.forward_auths_header = forward_auths_header
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = (
            breaker if breaker is not None
            else CircuitBreaker(endpoint=self.base_url)
        )
        self._schemas: dict[str, FeatureType] = {}

    def _request(self, method: str, path: str, *, params: dict | None = None,
                 body: dict | None = None, headers: dict | None = None,
                 idempotent: bool = True, deadline=None) -> bytes:
        """One resilient exchange (the shared request helper): retry +
        breaker + deadline header, with server 4xx errors re-raised as the
        local store's exception types and 504 as QueryTimeout — GET and
        mutation paths share ONE error mapping, so ``query`` against a
        missing type raises the same ``KeyError`` a mutation would."""
        return rhttp.request(
            method, self.base_url + path,
            params=params, body=body, headers=headers,
            timeout_s=self.timeout_s, retry=self.retry,
            breaker=self.breaker, idempotent=idempotent,
            deadline=deadline,
        )

    def _get(self, path: str, params: dict | None = None,
             headers: dict | None = None, deadline=None) -> bytes:
        return self._request("GET", path, params=params, headers=headers,
                             deadline=deadline)

    def _parse_json(self, raw: bytes):
        """JSON response → object, with decode failures surfaced as the
        typed :class:`CorruptPayloadError` — a torn/garbage JSON body from
        a flaky member is a MEMBER failure the federation can degrade on,
        exactly like a torn Arrow stream."""
        try:
            return json.loads(raw)
        except ValueError as e:
            raise CorruptPayloadError(
                f"undecodable JSON payload ({len(raw)} bytes) from "
                f"{self.base_url}: {e}"
            ) from e

    def _get_json(self, path: str, params: dict | None = None):
        return self._parse_json(self._get(path, params))

    def _send(self, method: str, path: str, body: dict | None = None,
              params: dict | None = None, headers: dict | None = None,
              idempotent: bool = False, deadline=None):
        """JSON request (mutations by default: ``idempotent=False`` limits
        retries to connect-before-send failures; batched READ posts —
        select-many/aggregate — pass ``idempotent=True``)."""
        raw = self._request(method, path, params=params, body=body,
                            headers=headers, idempotent=idempotent,
                            deadline=deadline)
        return self._parse_json(raw) if raw else None

    def _decode_arrow(self, sft: FeatureType, data: bytes) -> FeatureTable:
        """Arrow IPC payload → table, with decode failures surfaced as the
        typed :class:`CorruptPayloadError` (a truncated/corrupt stream
        from a flaky member must read as a MEMBER failure the federation
        can degrade on, not an opaque pyarrow traceback)."""
        from geomesa_tpu.io.arrow import from_ipc_bytes

        try:
            return from_ipc_bytes(sft, data)
        except Exception as e:  # noqa: BLE001 — decode errors are member faults
            raise CorruptPayloadError(
                f"undecodable Arrow IPC payload ({len(data)} bytes) from "
                f"{self.base_url}: {type(e).__name__}: {e}"
            ) from e

    # -- store surface --------------------------------------------------------
    def list_schemas(self) -> list[str]:
        return self._get_json("/api/schemas")["schemas"]

    def get_schema(self, name: str) -> FeatureType:
        if name not in self._schemas:
            meta = self._get_json(f"/api/schemas/{name}")
            self._schemas[name] = parse_spec(name, meta["spec"])
        return self._schemas[name]

    def query(self, type_name: str, q: Query | str | None = None, **kwargs) -> QueryResult:
        if isinstance(q, str) or q is None:
            q = Query(filter=q, **kwargs)
        params = {"format": "arrow"}
        f = q.resolved_filter()
        if not isinstance(f, ast.Include):
            params["cql"] = f if isinstance(f, str) else ast.to_cql(f)
        if q.limit is not None:
            params["limit"] = str(q.limit)
        if q.start_index is not None:
            params["startIndex"] = str(q.start_index)
        if q.sort_by is not None:  # pages are only stable under a sort
            fld, desc = q.sort_by
            params["sortBy"] = ("-" if desc else "") + fld
        headers = None
        if q.auths is not None:
            # visibility-scoped query against a remote member: forward the
            # auths in the remote's trusted header, or fail closed — this
            # client cannot apply row visibility to the remote's rows
            if self.forward_auths_header is None:
                raise PermissionError(
                    "remote member cannot apply caller visibility; "
                    "configure forward_auths_header to the remote's "
                    "trusted auths header, or exclude auths-scoped "
                    "queries from this member")
            headers = {self.forward_auths_header: ",".join(q.auths)}
        data = self._get(f"/api/schemas/{type_name}/query", params,
                         headers=headers,
                         deadline=q.hints.get("deadline"))
        table = self._decode_arrow(self.get_schema(type_name), data)
        return QueryResult(table, np.arange(len(table)))

    def stats_count(self, type_name: str, cql=None, exact: bool = False) -> float:
        params = {"exact": "true" if exact else "false"}
        if cql:
            params["cql"] = cql if isinstance(cql, str) else ast.to_cql(cql)
        out = self._get_json(f"/api/schemas/{type_name}/stats/count", params)
        return float(out["count"])

    def select_many(self, type_name: str, queries) -> list[QueryResult]:
        """Batched row retrieval over the wire (``POST .../select-many``):
        the remote owner runs the whole batch's device work in two
        dispatches (DataStore.select_many) and per-query Arrow IPC tables
        come back — one HTTP round trip for N queries, the federation
        analog of the local batch path. Queries may be CQL strings/None
        or Query objects (filter only; auths follow the same
        fail-closed/forward-header contract as :meth:`query`)."""
        import base64

        cqls = []
        deadline = None
        batch_auths: set[tuple[str, ...] | None] = set()
        for q in queries:
            if isinstance(q, Query):
                if deadline is None:
                    deadline = q.hints.get("deadline")
                # normalized: auths are a SET of visibility labels, so
                # ('a','b') and ('b','a') are the same scope
                batch_auths.add(
                    None if q.auths is None
                    else tuple(sorted(set(q.auths))))
                f = q.resolved_filter()
                cqls.append(
                    None if isinstance(f, ast.Include)
                    else (f if isinstance(f, str) else ast.to_cql(f)))
            else:
                batch_auths.add(None)  # bare CQL carries no visibility scope
                cqls.append(q if q is None or isinstance(q, str)
                            else ast.to_cql(q))
        headers = None
        scoped = {a for a in batch_auths if a is not None}
        if scoped:
            # ONE auths header covers the whole batch: a mix of different
            # auths (or auths and unscoped queries) would silently run every
            # query under one visibility — fail closed, same posture as the
            # single-query path
            if len(batch_auths) > 1:
                raise PermissionError(
                    "select_many batch mixes queries with different auths; "
                    "split the batch so each carries one visibility scope")
            if self.forward_auths_header is None:
                raise PermissionError(
                    "remote member cannot apply caller visibility; "
                    "configure forward_auths_header")
            headers = {self.forward_auths_header: ",".join(scoped.pop())}
        out = self._send(
            "POST", f"/api/schemas/{type_name}/select-many",
            {"queries": cqls}, headers=headers,
            idempotent=True,  # a batched READ: safe to replay on 5xx
            deadline=deadline)
        sft = self.get_schema(type_name)
        results = []
        for rec in out["results"]:
            table = self._decode_arrow(sft, base64.b64decode(rec["arrow_b64"]))
            results.append(QueryResult(table, np.arange(len(table))))
        return results

    def aggregate_many(self, type_name: str, queries, group_by=None,
                       value_cols=(), now_ms: int | None = None):
        """Remote grouped aggregation: ship the query batch, get per-group
        partials back — the federation surface of the fused mesh
        segment-reduce (same result shape as DataStore.aggregate_many;
        None entries mean the owner declined and the caller folds)."""
        # only PLAIN filters ship: a Query carrying auths/hints/limit/
        # start_index must decline locally (None) exactly as the local
        # store's batch gate does — shipping just its filter would compute
        # aggregates over rows the caller may not see (visibility leak) or
        # silently drop limit/hint semantics
        cqls: list = []
        declined: set[int] = set()
        deadline = None
        for i, q in enumerate(queries):
            if q is None or isinstance(q, str):
                cqls.append(q)
                continue
            if isinstance(q, Query):
                if deadline is None:
                    deadline = q.hints.get("deadline")
                # execution-control hints (deadline/timeout) don't change
                # RESULTS — the remote enforces the shipped deadline
                # header itself — so only semantic hints decline
                semantic_hints = any(
                    k not in ("deadline", "timeout") for k in q.hints
                )
                if (
                    q.auths is not None or semantic_hints
                    or q.limit is not None or q.start_index is not None
                ):
                    declined.add(i)
                    cqls.append(None)
                    continue
                f = q.resolved_filter()
            else:
                f = q
            cqls.append(None if isinstance(f, ast.Include) else ast.to_cql(f))
        body = {
            "queries": cqls,
            "group_by": list(group_by) if group_by else None,
            "value_cols": list(value_cols),
        }
        if now_ms is not None:
            body["now_ms"] = int(now_ms)  # pinned TTL clock crosses the wire
        res = self._send(
            "POST", f"/api/schemas/{type_name}/aggregate", body,
            idempotent=True,  # a batched READ: safe to replay on 5xx
            deadline=deadline,
        )["results"]
        out = []
        for i, r in enumerate(res):
            if i in declined:
                out.append(None)
                continue
            if r is None:
                out.append(None)
                continue
            out.append({
                "groups": [tuple(k) for k in r["groups"]],
                "count": np.asarray(r["count"], dtype=np.int64),
                "cols": {
                    c: {
                        "count": np.asarray(d["count"], dtype=np.int64),
                        "sum": np.asarray(d["sum"], dtype=np.float64),
                        "min": np.asarray(
                            [np.nan if v is None else v for v in d["min"]],
                            dtype=np.float64,
                        ),
                        "max": np.asarray(
                            [np.nan if v is None else v for v in d["max"]],
                            dtype=np.float64,
                        ),
                    }
                    for c, d in r["cols"].items()
                },
            })
        return out

    # -- write forwarding (P10 write half) ------------------------------------
    def create_schema(self, name_or_sft, spec: str | None = None) -> None:
        """Create a schema on the owning process. Raises ValueError when the
        type already exists there — concurrent creators race at the owner's
        in-process serialization, so exactly one wins cluster-wide."""
        if isinstance(name_or_sft, FeatureType):
            name, spec = name_or_sft.name, name_or_sft.to_spec()
        else:
            name = name_or_sft
            if spec is None:
                raise ValueError("create_schema needs (name, spec) or a FeatureType")
        self._send("POST", "/api/schemas", {"name": name, "spec": spec})
        self._schemas.pop(name, None)

    def _feature_collection(self, type_name: str, data, fids) -> dict:
        from geomesa_tpu.geometry.geojson import geometry_to_geojson
        from geomesa_tpu.geometry.types import Geometry

        sft = self.get_schema(type_name)
        if isinstance(data, FeatureTable):
            fids = list(data.fids) if fids is None else list(fids)
            data = [data.record(i) for i in range(len(data))]
        feats = []
        for i, rec in enumerate(data):
            props = {}
            geom = None
            for k, v in rec.items():
                if isinstance(v, Geometry):
                    if k == sft.geom_field:
                        geom = geometry_to_geojson(v)
                        continue
                    v = geometry_to_geojson(v)
                elif isinstance(v, np.generic):
                    v = v.item()
                props[k] = v
            f = {"type": "Feature", "geometry": geom, "properties": props}
            if fids is not None:
                f["id"] = str(fids[i])
            feats.append(f)
        return {"type": "FeatureCollection", "features": feats}

    def write(self, type_name: str, data, fids=None) -> int:
        """Append features on the owning process (GeoJSON over the wire)."""
        body = self._feature_collection(type_name, data, fids)
        return int(
            self._send("POST", f"/api/schemas/{type_name}/features", body)
            ["written"]
        )

    def update_features(self, type_name: str, data, fids) -> int:
        """WFS-T Update analog: replace features by id on the owner."""
        if fids is None:
            raise ValueError("update_features requires explicit fids")
        body = self._feature_collection(type_name, data, fids)
        return int(
            self._send("PUT", f"/api/schemas/{type_name}/features", body)
            ["updated"]
        )

    def delete_features(self, type_name: str, fids) -> int:
        return int(
            self._send(
                "DELETE", f"/api/schemas/{type_name}/features",
                {"fids": [str(f) for f in fids]},
            )["deleted"]
        )

    def delete_schema(self, name: str) -> None:
        self._send("DELETE", f"/api/schemas/{name}")
        self._schemas.pop(name, None)

    def update_schema(self, name: str, **changes) -> None:
        """Schema evolution on the owner: ``add=``/``keywords=``/
        ``rename_to=`` (the PATCH body keys of the web layer)."""
        self._send("PATCH", f"/api/schemas/{name}", dict(changes))
        self._schemas.pop(name, None)
