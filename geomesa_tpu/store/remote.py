"""Remote datastore client: a store whose scans run across an HTTP boundary.

Role parity: the reference federates independent stores with
``MergedDataStoreView.scala:31`` / ``MergedQueryRunner.scala``; each member
store reaches its own cluster over the network. Here a
:class:`RemoteDataStore` speaks to another process's REST endpoint
(:mod:`geomesa_tpu.web.app`) — filters ship as CQL text
(:func:`geomesa_tpu.filter.ast.to_cql`), results come back as Arrow IPC —
and plugs straight into ``MergedDataStoreView``, giving the multi-slice /
DCN federation story (SURVEY.md §2.20 P10): per-slice plans run where the
data lives, only Arrow results cross the wire.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec
from geomesa_tpu.store.datastore import QueryResult

__all__ = ["RemoteDataStore"]


class RemoteDataStore:
    """Read-only client over a geomesa_tpu REST endpoint.

    Implements the store surface ``MergedDataStoreView`` consumes
    (``get_schema`` / ``list_schemas`` / ``query`` / ``stats_count``), so a
    federation can mix in-process stores and remote slices freely.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._schemas: dict[str, FeatureType] = {}

    def _get(self, path: str, params: dict | None = None) -> bytes:
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read()

    def _get_json(self, path: str, params: dict | None = None):
        return json.loads(self._get(path, params))

    # -- store surface --------------------------------------------------------
    def list_schemas(self) -> list[str]:
        return self._get_json("/api/schemas")["schemas"]

    def get_schema(self, name: str) -> FeatureType:
        if name not in self._schemas:
            meta = self._get_json(f"/api/schemas/{name}")
            self._schemas[name] = parse_spec(name, meta["spec"])
        return self._schemas[name]

    def query(self, type_name: str, q: Query | str | None = None, **kwargs) -> QueryResult:
        from geomesa_tpu.io.arrow import from_ipc_bytes

        if isinstance(q, str) or q is None:
            q = Query(filter=q, **kwargs)
        params = {"format": "arrow"}
        f = q.resolved_filter()
        if not isinstance(f, ast.Include):
            params["cql"] = f if isinstance(f, str) else ast.to_cql(f)
        if q.limit is not None:
            params["limit"] = str(q.limit)
        if q.start_index is not None:
            params["startIndex"] = str(q.start_index)
        if q.sort_by is not None:  # pages are only stable under a sort
            fld, desc = q.sort_by
            params["sortBy"] = ("-" if desc else "") + fld
        data = self._get(f"/api/schemas/{type_name}/query", params)
        table = from_ipc_bytes(self.get_schema(type_name), data)
        return QueryResult(table, np.arange(len(table)))

    def stats_count(self, type_name: str, cql=None, exact: bool = False) -> float:
        params = {"exact": "true" if exact else "false"}
        if cql:
            params["cql"] = cql if isinstance(cql, str) else ast.to_cql(cql)
        out = self._get_json(f"/api/schemas/{type_name}/stats/count", params)
        return float(out["count"])
