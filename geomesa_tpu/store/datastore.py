"""DataStore: the top seam — schema CRUD, writes, queries (GeoTools role).

Reference: ``GeoMesaDataStore`` (``geomesa-index-api/.../geotools/
GeoMesaDataStore.scala:49``) + ``QueryPlanner.runQuery`` (SURVEY.md §3.3).
Host-side orchestration: schemas and the canonical columnar tables live here;
each write rebuilds index permutations and backend device state (bulk-load
semantics v1 — the streaming LSM delta tier is the lambda-pattern follow-up,
SURVEY.md §2.11).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.index.api import FeatureIndex
from geomesa_tpu.planning.planner import Query, QueryPlanner, build_indices
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec
from geomesa_tpu.store.backends import ExecutionBackend, OracleBackend, TpuBackend

_BACKENDS = {"oracle": OracleBackend, "tpu": TpuBackend}


@dataclass
class QueryResult:
    """Materialized query result + plan trace."""

    table: FeatureTable
    row_ids: np.ndarray
    plan_info: Any = None

    @property
    def count(self) -> int:
        return len(self.table)

    def records(self) -> list[dict]:
        return [self.table.record(i) for i in range(len(self.table))]


@dataclass
class _TypeState:
    sft: FeatureType
    table: FeatureTable | None = None
    indices: dict[str, FeatureIndex] = field(default_factory=dict)
    backend_state: Any = None


class DataStore:
    """An in-process spatio-temporal datastore over a pluggable backend."""

    def __init__(self, backend: str | ExecutionBackend = "tpu"):
        if isinstance(backend, str):
            backend = _BACKENDS[backend]()
        self.backend = backend
        self._types: dict[str, _TypeState] = {}

    # -- schema CRUD (MetadataBackedDataStore role) --------------------------
    def create_schema(self, sft: FeatureType | str, spec: str | None = None) -> FeatureType:
        if isinstance(sft, str):
            if spec is None:
                raise ValueError("create_schema('name', 'spec string') requires a spec")
            sft = parse_spec(sft, spec)
        if sft.name in self._types:
            raise ValueError(f"schema already exists: {sft.name}")
        self._types[sft.name] = _TypeState(sft=sft, indices=build_indices(sft))
        return sft

    def get_schema(self, name: str) -> FeatureType:
        return self._state(name).sft

    def list_schemas(self) -> list[str]:
        return sorted(self._types)

    def delete_schema(self, name: str) -> None:
        del self._types[name]

    def _state(self, name: str) -> _TypeState:
        if name not in self._types:
            raise KeyError(f"no such schema: {name!r}")
        return self._types[name]

    # -- writes (GeoMesaFeatureWriter role; bulk semantics) ------------------
    def write(self, type_name: str, data, fids=None) -> int:
        """Append features (FeatureTable or list of record dicts); rebuilds
        index order and backend state for the new snapshot.

        Validation before commit (the reference's all-indices-validate-before-
        write pattern, ``IndexAdapter.scala:139-149``): rows with a null
        default geometry or null dtg are rejected — and state is only swapped
        in after every index builds successfully, so a failed write never
        leaves the store half-applied.
        """
        st = self._state(type_name)
        if isinstance(data, list):
            if fids is None:
                base = 0 if st.table is None else len(st.table)
                fids = [f"{type_name}.{base + i}" for i in range(len(data))]
            data = FeatureTable.from_records(st.sft, data, fids)
        self._validate(st.sft, data)
        table = (
            data if st.table is None else FeatureTable.concat([st.table, data])
        )
        # build into fresh index instances; commit only on success (atomic)
        indices = build_indices(st.sft)
        for index in indices.values():
            index.build(table)
        backend_state = self.backend.load(st.sft, table, indices)
        st.table = table
        st.indices = indices
        st.backend_state = backend_state
        return len(data)

    @staticmethod
    def _validate(sft: FeatureType, table: FeatureTable) -> None:
        if sft.geom_field is not None:
            col = table.columns[sft.geom_field]
            if not col.is_valid().all():
                bad = int((~col.is_valid()).sum())
                raise ValueError(
                    f"{bad} feature(s) with null geometry {sft.geom_field!r}: "
                    "indexed geometries must be non-null"
                )
        if sft.dtg_field is not None:
            col = table.columns[sft.dtg_field]
            if not col.is_valid().all():
                bad = int((~col.is_valid()).sum())
                raise ValueError(
                    f"{bad} feature(s) with null date {sft.dtg_field!r}: "
                    "indexed dates must be non-null"
                )

    # -- queries (QueryPlanner.runQuery role) --------------------------------
    def query(
        self, type_name: str, q: Query | str | None = None, **kwargs
    ) -> QueryResult:
        st = self._state(type_name)
        if isinstance(q, str) or q is None:
            q = Query(filter=q, **kwargs)
        elif kwargs:
            raise ValueError(
                "pass query options inside the Query object, not as kwargs: "
                f"{sorted(kwargs)}"
            )
        if st.table is None or len(st.table) == 0:
            empty = FeatureTable.from_records(st.sft, [])
            return QueryResult(empty, np.empty(0, dtype=np.int64))

        f = q.resolved_filter()
        if isinstance(self.backend, OracleBackend):
            # referee path: no planning, brute force
            rows = self.backend.select(None, None, None, None, f, st.table)
            info = None
        else:
            planner = QueryPlanner(st.sft, st.indices)
            plan, f, info = planner.plan(q)
            index = st.indices[info.index_name]
            rows = self.backend.select(
                st.backend_state, index, plan, info.extraction, f, st.table
            )

        rows = np.sort(rows)  # deterministic order before transforms
        table = st.table.take(rows)

        # client-side reduce: sort / limit / projection (QueryPlanner.scala:75-98)
        if q.sort_by is not None:
            fld, desc = q.sort_by
            keys = table.fids if fld == "id" else table.columns[fld].values
            order = np.argsort(keys, kind="stable")
            if desc:
                order = order[::-1]
            table = table.take(order)
            rows = rows[order]
        if q.limit is not None:
            table = table.take(np.arange(min(q.limit, len(table))))
            rows = rows[: q.limit]
        if q.properties is not None:
            keep = {p: table.columns[p] for p in q.properties}
            table = FeatureTable(table.sft, table.fids, {**keep})

        return QueryResult(table, rows, info)

    def explain(self, type_name: str, q: Query | str) -> str:
        st = self._state(type_name)
        if isinstance(q, str):
            q = Query(filter=q)
        planner = QueryPlanner(st.sft, st.indices)
        _, _, info = planner.plan(q)
        return info.explain()

    def stats_count(self, type_name: str) -> int:
        st = self._state(type_name)
        return 0 if st.table is None else len(st.table)
